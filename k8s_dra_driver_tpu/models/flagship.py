"""SliceProof: the flagship sharded-training workload.

A compact decoder-only transformer written TPU-first:

- matmuls run in bfloat16 so XLA tiles them onto the MXU; master params and
  the loss stay float32,
- a static Python layer loop (layer count is compile-time constant) with no
  data-dependent control flow, so everything fuses under one ``jit``,
- tensor parallelism shards attention heads and the FFN hidden dim over the
  ``model`` mesh axis; data parallelism shards the batch over ``data``.
  Shardings are expressed with ``NamedSharding`` on the inputs plus
  ``with_sharding_constraint`` pins on activations — XLA inserts the
  all-reduces (over ICI on real slices) itself.

This is the workload the ComputeDomain e2e schedules to prove an assembled
slice trains at rate (role of the reference's nvbandwidth job,
/root/reference/demo/specs/imex/nvbandwidth-test-job.yaml).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from k8s_dra_driver_tpu.models.common import (
    causal_einsum_attention,
    make_sharded_state,
    make_token_batch,
    meshed_step,
    momentum_sgd,
    nll_loss,
    rmsnorm as _rmsnorm,
)
from k8s_dra_driver_tpu.parallel.mesh import build_mesh, choose_dp_tp

Params = Dict[str, Any]


@dataclass(frozen=True)
class SliceProofConfig:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 8
    n_layers: int = 2
    d_ff: int = 512
    seq_len: int = 64
    learning_rate: float = 1e-3
    # "einsum": portable O(s²)-memory attention (CPU mesh dryruns, tiny
    # tests). "flash": the Pallas TPU flash-attention kernel — O(s) memory,
    # never materializes the [b,h,s,s] score matrix in HBM.
    attention: str = "einsum"
    # Rematerialize each block on the backward pass (jax.checkpoint):
    # trades ~+1/3 of the forward FLOPs for O(L)→O(1) activation memory,
    # buying batch (better MXU amortization) when HBM binds.
    remat: bool = False

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @classmethod
    def tiny(cls) -> "SliceProofConfig":
        return cls()

    @classmethod
    def bench(cls) -> "SliceProofConfig":
        """MXU-sized single-chip benchmark config (~690M matmul params):
        large, bf16, static — dims multiples of 128 so XLA tiles cleanly
        onto the systolic array. Shape chosen by the measured sweeps
        (ops/mfu_sweep.py; full ladder in docs/benchmarks.md): d_model
        2048 with a ratio-8 FFN (d_ff 16384) and 2 heads of head_dim 1024
        measures 80.4-81.1% MFU median-of-3 on v5e (best 82.2). The complete
        head ladder at identical counted FLOPs: 16×128 65.4, 8×256
        74.5-76.4 (run-to-run tunnel variance), 4×512 78.3-78.9, 2×1024
        ~81, 1×2048 77.3 — fatter per-head GEMMs tile the 128×128 MXU
        better until a single full-width head regresses. This is a
        benchmark shape chosen for hardware fit; the conventional-head-dim
        numbers stay recorded alongside in docs so the headline is never
        mistaken for an 8×256 claim. FFN ratio 4 measured 54%, d_model
        1024 32%. XLA's fused einsum attention beats the Pallas flash
        kernel at this seq_len, so einsum stays the default;
        attention="flash" is the long-sequence escape hatch and remat=True
        the HBM escape hatch (both cost reported MFU)."""
        return cls(vocab=8192, d_model=2048, n_heads=2, n_layers=8,
                   d_ff=16384, seq_len=1024)


def matmul_param_count(cfg: SliceProofConfig) -> int:
    """Parameters on the matmul path (excludes norms/embedding lookup) —
    the N in the standard 6·N·T FLOPs-per-train-step estimate."""
    per_layer = 3 * cfg.d_model * cfg.d_model   # wqkv
    per_layer += cfg.d_model * cfg.d_model      # wo
    per_layer += 2 * cfg.d_model * cfg.d_ff     # w1 + w2
    return cfg.n_layers * per_layer + cfg.d_model * cfg.vocab  # + unembed


def init_params(cfg: SliceProofConfig, seed: int = 0) -> Params:
    k = jax.random.PRNGKey(seed)
    keys = jax.random.split(k, 2 + cfg.n_layers)
    scale = 0.02

    def dense(key, *shape):
        return scale * jax.random.normal(key, shape, dtype=jnp.float32)

    layers = []
    for i in range(cfg.n_layers):
        lk = jax.random.split(keys[2 + i], 6)
        layers.append(
            {
                # Heads as an explicit axis so tp sharding is a plain
                # PartitionSpec on axis 1.
                "wqkv": dense(lk[0], cfg.d_model, 3, cfg.n_heads, cfg.head_dim),
                "wo": dense(lk[1], cfg.n_heads, cfg.head_dim, cfg.d_model),
                "w1": dense(lk[2], cfg.d_model, cfg.d_ff),
                "w2": dense(lk[3], cfg.d_ff, cfg.d_model),
                "ln1": jnp.ones((cfg.d_model,), jnp.float32),
                "ln2": jnp.ones((cfg.d_model,), jnp.float32),
            }
        )
    return {
        "embed": dense(keys[0], cfg.vocab, cfg.d_model),
        "unembed": dense(keys[1], cfg.d_model, cfg.vocab),
        "layers": layers,
    }


def param_pspecs(cfg: SliceProofConfig) -> Params:
    """PartitionSpecs mirroring init_params: tp over heads / ffn-hidden."""
    layer = {
        "wqkv": P(None, None, "model", None),
        "wo": P("model", None, None),
        "w1": P(None, "model"),
        "w2": P("model", None),
        "ln1": P(None),
        "ln2": P(None),
    }
    return {
        "embed": P(None, None),
        "unembed": P(None, None),
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
    }


def _ambient_mesh_empty() -> bool:
    get_am = getattr(jax.sharding, "get_abstract_mesh", None)
    if get_am is not None:
        return get_am().empty
    from jax._src import mesh as _mesh  # jax < 0.5: thread-resources mesh

    return _mesh.thread_resources.env.physical_mesh.empty


def _pin(x: jax.Array, spec: P) -> jax.Array:
    """Sharding-constrain x when a mesh context is active; no-op single-chip,
    so the same forward serves entry() (one device) and the sharded step."""
    if _ambient_mesh_empty():
        return x
    return jax.lax.with_sharding_constraint(x, spec)


def _block(cfg: SliceProofConfig, p: Params, x: jax.Array) -> jax.Array:
    h = _rmsnorm(x, p["ln1"])
    if cfg.attention == "flash":
        # [b,h,s,k] layout straight out of the projection; the kernel keeps
        # the running softmax in VMEM (HBM-bandwidth win over einsum).
        from jax.experimental.pallas.ops.tpu.flash_attention import flash_attention

        qkv = jnp.einsum("bsd,dthk->tbhsk", h, p["wqkv"].astype(jnp.bfloat16))
        # Same tp pinning as the einsum path: heads ride the model axis so
        # the kernel partitions per-head instead of all-gathering q/k/v.
        q = _pin(qkv[0], P("data", "model", None, None))
        kk = _pin(qkv[1], P("data", "model", None, None))
        v = _pin(qkv[2], P("data", "model", None, None))
        attn_bhsk = flash_attention(
            q, kk, v, causal=True,
            sm_scale=float(1.0 / np.sqrt(cfg.head_dim)),
        )
        attn = jnp.swapaxes(attn_bhsk, 1, 2)  # -> [b,s,h,k]
        x = x + jnp.einsum("bshk,hkd->bsd", attn, p["wo"].astype(jnp.bfloat16))
    else:
        x = causal_einsum_attention(
            p, x, h, cfg.head_dim,
            pin_q=lambda q: _pin(q, P("data", None, "model", None)),
        )

    h = _rmsnorm(x, p["ln2"])
    ff = jax.nn.gelu(jnp.einsum("bsd,df->bsf", h, p["w1"].astype(jnp.bfloat16)))
    ff = _pin(ff, P("data", None, "model"))
    x = x + jnp.einsum("bsf,fd->bsd", ff, p["w2"].astype(jnp.bfloat16))
    return x


def forward_hidden(cfg: SliceProofConfig, params: Params, tokens: jax.Array) -> jax.Array:
    """tokens [b, s] int32 -> final hidden states [b, s, d_model] bf16."""
    x = params["embed"].astype(jnp.bfloat16)[tokens]
    block = partial(_block, cfg)
    if cfg.remat:
        block = jax.checkpoint(block)
    for p in params["layers"]:
        x = block(p, x)
    return x


def forward(cfg: SliceProofConfig, params: Params, tokens: jax.Array) -> jax.Array:
    """tokens [b, s] int32 -> logits [b, s, vocab] float32."""
    x = forward_hidden(cfg, params, tokens)
    return jnp.einsum("bsd,dv->bsv", x, params["unembed"].astype(jnp.bfloat16)).astype(
        jnp.float32
    )


def evaluate_nll(cfg: SliceProofConfig, params: Params, tokens: jax.Array,
                 *, block_t: int = 256, interpret=None) -> jax.Array:
    """Mean next-token NLL for scoring/eval — the fused-CE path.

    Same value as ``loss_fn`` but the unembed projection and softmax
    cross-entropy run in the in-repo Pallas kernel (ops/fused_ce.py):
    the [tokens, vocab] logits never touch HBM, measured 1.4-1.5× faster
    than the materializing loss on v5e at vocab ≥ 32k and the only path
    at token×vocab products whose logits exceed HBM
    (docs/benchmarks.md table). No-grad scoring is exactly where the
    kernel wins; training keeps the XLA loss (its backward is faster at
    fitting sizes — measured, and documented honestly)."""
    from k8s_dra_driver_tpu.ops.fused_ce import fused_ce_losses

    h = forward_hidden(cfg, params, tokens)[:, :-1]
    labels = tokens[:, 1:].reshape(-1)
    flat = h.reshape(-1, cfg.d_model)
    t_dim = flat.shape[0]
    block_v = min(512, cfg.vocab)
    pad = (-t_dim) % block_t
    if pad:
        flat = jnp.concatenate(
            [flat, jnp.zeros((pad, cfg.d_model), flat.dtype)])
        labels = jnp.concatenate(
            [labels, jnp.full((pad,), -1, labels.dtype)])  # matches no class
    losses = fused_ce_losses(flat, params["unembed"].astype(jnp.bfloat16),
                             labels, block_t, block_v, interpret)
    return losses[:t_dim].mean()


def loss_fn(cfg: SliceProofConfig, params: Params, batch: Dict[str, jax.Array]) -> jax.Array:
    return nll_loss(forward(cfg, params, batch["tokens"]), batch["tokens"])


def sgd_train_step(cfg: SliceProofConfig, state: Dict[str, Any], batch: Dict[str, jax.Array]):
    """One full training step: fwd, bwd, momentum-SGD update."""
    params, mom = state["params"], state["momentum"]
    loss, grads = jax.value_and_grad(partial(loss_fn, cfg))(params, batch)
    new_params, new_mom = momentum_sgd(params, mom, grads, cfg.learning_rate)
    return {"params": new_params, "momentum": new_mom}, loss


def make_sharded_train_step(
    cfg: SliceProofConfig,
    devices: Sequence,
    *,
    batch_per_replica: int = 2,
    seed: int = 0,
):
    """Build (jitted_step, sharded_state, sharded_batch) over a dp×tp mesh."""
    dp, tp = choose_dp_tp(len(devices), max_tp=min(8, cfg.n_heads))
    mesh = build_mesh(devices, dp, tp)

    state = make_sharded_state(init_params(cfg, seed=seed), param_pspecs(cfg), mesh)
    batch = make_token_batch(seed, dp * batch_per_replica, cfg.seq_len,
                             cfg.vocab, mesh, P("data", None))
    jitted = jax.jit(partial(sgd_train_step, cfg), donate_argnums=(0,))
    return meshed_step(jitted, mesh), state, batch
