"""Training scaffolding shared by the model families (flagship, MoE).

One home for the pieces that must not drift between families: causal
einsum attention, the NLL loss, the momentum-SGD update, and the
state/batch sharding helpers used by every ``make_*_train_step``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def rmsnorm(x: jax.Array, g: jax.Array) -> jax.Array:
    x = x.astype(jnp.float32)
    x = x * jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + 1e-6)
    return (x * g).astype(jnp.bfloat16)


def causal_einsum_attention(
    p: Dict[str, jax.Array],
    x: jax.Array,
    h: jax.Array,
    head_dim: int,
    pin_q: Optional[Callable[[jax.Array], jax.Array]] = None,
) -> jax.Array:
    """x + Attn(h) with p["wqkv"]/p["wo"]; h is the pre-normed input.
    ``pin_q`` optionally sharding-constrains q (tp head pinning)."""
    s = x.shape[1]
    qkv = jnp.einsum("bsd,dthk->tbshk", h, p["wqkv"].astype(jnp.bfloat16))
    q, k, v = qkv[0], qkv[1], qkv[2]
    if pin_q is not None:
        q = pin_q(q)
    scores = jnp.einsum("bshk,bthk->bhst", q, k) / np.sqrt(head_dim)
    mask = jnp.tril(jnp.ones((s, s), dtype=bool))
    scores = jnp.where(mask[None, None], scores.astype(jnp.float32), -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(jnp.bfloat16)
    attn = jnp.einsum("bhst,bthk->bshk", probs, v)
    return x + jnp.einsum("bshk,hkd->bsd", attn, p["wo"].astype(jnp.bfloat16))


def nll_loss(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Mean next-token NLL: logits [b, s, v] f32, tokens [b, s] int."""
    logp = jax.nn.log_softmax(logits[:, :-1])
    tgt = tokens[:, 1:]
    return -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0].mean()


def momentum_sgd(params, momentum, grads, lr: float, beta: float = 0.9):
    """Heavyweight-ball SGD shared by every family's train step."""
    new_mom = jax.tree.map(lambda m, g: beta * m + g, momentum, grads)
    new_params = jax.tree.map(lambda p, m: p - lr * m, params, new_mom)
    return new_params, new_mom


def shard_tree(tree, specs, mesh: Mesh):
    """device_put every leaf with its NamedSharding spec."""
    return jax.tree.map(
        lambda x, sp: jax.device_put(x, NamedSharding(mesh, sp)),
        tree, specs,
        is_leaf=lambda x: isinstance(x, jnp.ndarray),
    )


def make_sharded_state(params, pspecs, mesh: Mesh) -> Dict[str, Any]:
    """{"params", "momentum"} with momentum zeros_like, both sharded."""
    return {
        "params": shard_tree(params, pspecs, mesh),
        "momentum": shard_tree(jax.tree.map(jnp.zeros_like, params), pspecs, mesh),
    }


def make_token_batch(seed: int, rows: int, seq_len: int, vocab: int,
                     mesh: Mesh, spec: P) -> Dict[str, jax.Array]:
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, vocab, size=(rows, seq_len))
    return {
        "tokens": jax.device_put(
            jnp.asarray(tokens, dtype=jnp.int32), NamedSharding(mesh, spec)
        )
    }


def mesh_context(mesh: Mesh):
    """Ambient-mesh context across jax versions: ``jax.set_mesh`` where it
    exists (>= 0.6), the classic ``with mesh:`` thread-resources context on
    older runtimes — both make bare PartitionSpecs in
    ``with_sharding_constraint`` resolve against ``mesh``."""
    set_mesh = getattr(jax, "set_mesh", None)
    return set_mesh(mesh) if set_mesh is not None else mesh


def meshed_step(jitted, mesh: Mesh):
    """Wrap a jitted step so it runs under the mesh context."""
    def step(state, batch):
        with mesh_context(mesh):
            return jitted(state, batch)

    return step
