"""Pipelined flagship: the SliceProof transformer trained layer-per-device.

Third composition of the workload tier: the same transformer family as
``models/flagship`` but with one block per device along a ``pp`` mesh axis
(``parallel/pipeline.py``'s GPipe schedule). Embedding and unembedding are
replicated (cheap at these widths); the block stack is the pipeline.
``jax.grad`` through the pipeline scan is the reverse schedule — the whole
train step is still one jitted computation.

Use when a model's layers don't fit one device's HBM but a single layer
does — the orthogonal axis to dp×tp (flagship) and ep (MoE).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from k8s_dra_driver_tpu.models.common import (
    causal_einsum_attention,
    make_sharded_state,
    make_token_batch,
    meshed_step,
    momentum_sgd,
    nll_loss,
    rmsnorm as _rmsnorm,
)
from k8s_dra_driver_tpu.models.flagship import SliceProofConfig, init_params
from k8s_dra_driver_tpu.parallel.mesh import family_mesh
from k8s_dra_driver_tpu.parallel.pipeline import pipeline_apply

Params = Dict[str, Any]


def _stage_fn(cfg: SliceProofConfig, p: Params, x: jax.Array) -> jax.Array:
    """One transformer block, pin-free: under a pp-only mesh there are no
    data/model axes to constrain onto. Einsum attention only — the flash
    kernel is rejected up front in make_pipelined_train_step."""
    x = causal_einsum_attention(p, x, _rmsnorm(x, p["ln1"]), cfg.head_dim)
    h = _rmsnorm(x, p["ln2"])
    ff = jax.nn.gelu(jnp.einsum("bsd,df->bsf", h, p["w1"].astype(jnp.bfloat16)))
    return x + jnp.einsum("bsf,fd->bsd", ff, p["w2"].astype(jnp.bfloat16))


def stack_layer_params(params: Params) -> Params:
    """[{'wqkv': ...} x L] -> {'wqkv': [L, ...]} for stage sharding."""
    layers = params["layers"]
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *layers)


def param_pspecs(cfg: SliceProofConfig, pipe_axis: str = "pp") -> Params:
    stage = jax.tree.map(
        lambda _: P(pipe_axis),
        {"wqkv": 0, "wo": 0, "w1": 0, "w2": 0, "ln1": 0, "ln2": 0},
    )
    return {"embed": P(), "unembed": P(), "stages": stage}


def forward(cfg: SliceProofConfig, params: Params, tokens: jax.Array,
            mesh: Mesh, *, num_microbatches: int,
            batch_axis: Optional[str] = None) -> jax.Array:
    x = params["embed"].astype(jnp.bfloat16)[tokens]
    x = pipeline_apply(
        partial(_stage_fn, cfg), params["stages"], x, mesh,
        num_microbatches=num_microbatches, batch_axis=batch_axis,
    )
    return jnp.einsum(
        "bsd,dv->bsv", x, params["unembed"].astype(jnp.bfloat16)
    ).astype(jnp.float32)


def loss_fn(cfg, params, batch, mesh, *, num_microbatches, batch_axis=None):
    logits = forward(cfg, params, batch["tokens"], mesh,
                     num_microbatches=num_microbatches, batch_axis=batch_axis)
    return nll_loss(logits, batch["tokens"])


def make_pipelined_train_step(
    cfg: SliceProofConfig,
    devices: Sequence,
    *,
    batch_per_microbatch: int = 2,
    num_microbatches: Optional[int] = None,
    seed: int = 0,
    pipe_axis: str = "pp",
    data_parallel: int = 1,
):
    """Build (jitted_step, sharded_state, sharded_batch) with one block per
    pipeline stage. With ``data_parallel`` > 1 the mesh composes dp×pp:
    cfg.n_layers stages each hold their block, replicated over the data
    axis, and every data replica pipelines its own shard of each
    microbatch (XLA inserts the stage-grad allreduce over data).
    cfg.n_layers * data_parallel must equal the device count."""
    n = len(devices)
    if cfg.n_layers * data_parallel != n:
        raise ValueError(
            f"n_layers*data_parallel ({cfg.n_layers}*{data_parallel}) must "
            f"equal device count ({n}) — one block per pipeline stage"
        )
    if cfg.attention != "einsum":
        raise ValueError(
            f"pipelined stages support einsum attention only, got "
            f"{cfg.attention!r} (the flash kernel's tp pins have no axes "
            f"on a pp-only mesh)"
        )
    stages = cfg.n_layers
    if num_microbatches is None:
        num_microbatches = stages  # enough to keep every stage busy
    if data_parallel > 1:
        # pp innermost: stage hops ride neighbor ICI links (bundle-ordered
        # when a mesh bundle is ambient); the per-stage gradient allreduce
        # over data crosses the outer axis.
        mesh = family_mesh(devices, (data_parallel, stages),
                           ("data", pipe_axis))
        batch_axis: Optional[str] = "data"
        batch_spec = P("data")
    else:
        mesh = family_mesh(devices, (stages,), (pipe_axis,))
        batch_axis = None
        batch_spec = P()  # batch replicated; microbatching splits it

    flat = init_params(cfg, seed=seed)
    params = {
        "embed": flat["embed"],
        "unembed": flat["unembed"],
        "stages": stack_layer_params(flat),
    }
    state = make_sharded_state(params, param_pspecs(cfg, pipe_axis), mesh)
    batch = make_token_batch(
        seed, num_microbatches * batch_per_microbatch * data_parallel,
        cfg.seq_len, cfg.vocab, mesh, batch_spec,
    )

    def train_step(state, batch):
        params, mom = state["params"], state["momentum"]
        loss, grads = jax.value_and_grad(partial(
            loss_fn, cfg, num_microbatches=num_microbatches,
            batch_axis=batch_axis,
        ), argnums=0)(params, batch, mesh)
        new_params, new_mom = momentum_sgd(params, mom, grads, cfg.learning_rate)
        return {"params": new_params, "momentum": new_mom}, loss

    jitted = jax.jit(train_step, donate_argnums=(0,))
    return meshed_step(jitted, mesh), state, batch
