"""Workload models that run on claimed TPU slices.

The flagship ``SliceProof`` transformer is the framework's proof-of-function
workload: the job a user schedules onto a ComputeDomain to validate that a
freshly assembled multi-host ICI slice trains at expected throughput —
the role the reference fills with nvbandwidth test jobs
(/root/reference/demo/specs/imex/nvbandwidth-test-job.yaml), upgraded to a
real sharded training step.
"""

from k8s_dra_driver_tpu.models.flagship import (  # noqa: F401
    SliceProofConfig,
    forward,
    init_params,
    make_sharded_train_step,
)
