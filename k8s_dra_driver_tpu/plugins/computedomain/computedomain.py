"""ComputeDomainManager — the plugin's view of domains, cliques and labels.

Reference: /root/reference/cmd/compute-domain-kubelet-plugin/
computedomain.go:61-107 (manager), 298-354 (AssertComputeDomainReady),
356 (namespace anti-spoof), 372-400 (AddNodeLabel → DaemonSet follows).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

from k8s_dra_driver_tpu.api.computedomain import (
    COMPUTE_DOMAIN_NODE_LABEL,
    COORDINATOR_PORT_ANNOTATION,
    ComputeDomainClique,
)
from k8s_dra_driver_tpu.daemon.cliquemanager import clique_name
from k8s_dra_driver_tpu.k8s import APIServer
from k8s_dra_driver_tpu.k8s.core import COMPUTE_DOMAIN, COMPUTE_DOMAIN_CLIQUE, NODE
from k8s_dra_driver_tpu.pkg.meshgen import MESH_BUNDLE_ENV, PROCESS_BOUNDS_ENV
from k8s_dra_driver_tpu.tpulib.types import HostInventory

log = logging.getLogger(__name__)

MEGASCALE_COORDINATOR_PORT = 8476


def coordinator_port(cd) -> int:
    """The coordinator port this domain's workers advertise: the
    per-domain annotation when the controller allocated one dynamically
    (loopback/sim deployments sharing the host port space), else the fixed
    well-known port."""
    raw = cd.meta.annotations.get(COORDINATOR_PORT_ANNOTATION, "")
    try:
        return int(raw) if raw else MEGASCALE_COORDINATOR_PORT
    except ValueError:
        log.warning("malformed %s annotation %r on %s; using default",
                    COORDINATOR_PORT_ANNOTATION, raw, cd.key)
        return MEGASCALE_COORDINATOR_PORT


class RetryableError(Exception):
    """Prepare must be retried by the kubelet; the pod stays
    ContainerCreating (the mechanism that serializes domain-up before
    workload-start, SURVEY.md §3.5)."""


class PermanentError(Exception):
    """Prepare must NOT be retried (namespace spoof, bad config)."""


class ComputeDomainManager:
    def __init__(self, api: APIServer, node_name: str, inventory: HostInventory):
        self.api = api
        self.node_name = node_name
        self.inventory = inventory

    # -- lookups ------------------------------------------------------------

    def get_domain_by_uid(self, cd_uid: str):
        """One cluster-wide scan; callers resolve once per prepare and pass
        the object down (three scans per prepare otherwise)."""
        for cd in self.api.list(COMPUTE_DOMAIN):
            if cd.uid == cd_uid:
                return cd
        return None

    def resolve(self, cd_uid: str):
        """(domain, clique-or-None) for this node's ICI domain; retryable
        while the domain doesn't exist yet."""
        cd = self.get_domain_by_uid(cd_uid)
        if cd is None:
            raise RetryableError(f"ComputeDomain {cd_uid} not found (yet)")
        return cd, self.get_clique(cd)

    def get_clique(self, cd) -> Optional[ComputeDomainClique]:
        name = clique_name(cd.uid, self.inventory.ici_domain)
        return self.api.try_get(COMPUTE_DOMAIN_CLIQUE, name, cd.namespace)  # type: ignore[return-value]

    @staticmethod
    def assert_domain_namespace(cd, claim_namespace: str) -> None:
        """Anti-spoof: the claim's namespace must be the CD's namespace, so a
        claim in namespace A cannot join a domain in namespace B."""
        if cd.namespace != claim_namespace:
            raise PermanentError(
                f"claim namespace {claim_namespace!r} does not match "
                f"ComputeDomain namespace {cd.namespace!r}"
            )

    # -- the readiness gate --------------------------------------------------

    def assert_domain_ready(self, cd, clique: Optional[ComputeDomainClique]) -> None:
        """Local daemon Ready in this node's clique, else retryable."""
        if clique is None:
            raise RetryableError(
                f"clique for domain {cd.uid} on {self.inventory.ici_domain} not created yet"
            )
        info = clique.node_info(self.node_name)
        if info is None:
            raise RetryableError(f"slice agent on {self.node_name} not registered yet")
        if not info.ready:
            raise RetryableError(f"slice agent on {self.node_name} not ready yet")

    # -- node labels ---------------------------------------------------------

    def add_node_label(self, cd_uid: str) -> None:
        """Label this node for the domain. A node can host at most one
        domain's DaemonSet: overwriting another domain's label would evict
        its agent under a running workload, so that's an error (reference
        AddNodeLabel guard, computedomain.go:372-400)."""

        def mutate(node):
            current = node.meta.labels.get(COMPUTE_DOMAIN_NODE_LABEL)
            if current and current != cd_uid:
                raise RetryableError(
                    f"node {self.node_name} already belongs to ComputeDomain "
                    f"{current}; wait for it to release"
                )
            node.meta.labels[COMPUTE_DOMAIN_NODE_LABEL] = cd_uid

        self.api.update_with_retry(NODE, self.node_name, "", mutate)

    def remove_node_label(self, cd_uid: str) -> None:
        def mutate(node):
            if node.meta.labels.get(COMPUTE_DOMAIN_NODE_LABEL) == cd_uid:
                del node.meta.labels[COMPUTE_DOMAIN_NODE_LABEL]

        self.api.update_with_retry(NODE, self.node_name, "", mutate)

    # -- workload bootstrap env ----------------------------------------------

    def bootstrap_env(self, cd, clique: ComputeDomainClique) -> Dict[str, str]:
        """The slice-identity environment the channel device injects: worker
        id, ordered peer hostnames, coordinator address — what libtpu/JAX
        need to initialize the multi-host slice (the IMEX channel +
        /imexd-config analog, device_state.go:681-733). ``cd`` is the
        resolved ComputeDomain: its coordinator-port annotation (when the
        controller allocated one at DaemonSet render) overrides the fixed
        well-known port."""
        members = sorted(clique.nodes, key=lambda n: n.index)
        self_info = clique.node_info(self.node_name)
        if self_info is None:
            raise RetryableError(f"{self.node_name} missing from clique")
        hostnames = [m.dns_name or m.ip_address for m in members]
        coordinator = hostnames[0] if hostnames else ""
        port = coordinator_port(cd)
        # Worker ids are the DENSE RANK of each member's CAS index, not the
        # raw index: after an elastic heal deregisters a dead member the
        # surviving indices have a hole (e.g. {0,2,3}), and jax.distributed
        # with num_processes=N requires process ids 0..N-1. Rank-of-index
        # equals the raw index whenever indices are dense (every
        # pre-elastic domain), so nothing changes for the steady state,
        # and enumeration order (sorted by index) is preserved.
        ranks = {m.node_name: rank for rank, m in enumerate(members)}
        env = {
            "TPU_WORKER_ID": str(ranks[self.node_name]),
            "TPU_WORKER_HOSTNAMES": ",".join(hostnames),
            "TPU_TOPOLOGY": self.inventory.slice_topology,
            "TPU_ACCELERATOR_TYPE": self.inventory.accelerator_type,
            "TPU_HOST_BOUNDS": self.inventory.host_topology,
            "MEGASCALE_COORDINATOR_ADDRESS": (
                f"{coordinator}:{port}" if coordinator else ""
            ),
            "MEGASCALE_NUM_SLICES": "1",
            "MEGASCALE_SLICE_ID": "0",
            "COMPUTE_DOMAIN_UUID": cd.uid,
        }
        # The Placement→JAX mesh compiler output, when the controller has
        # emitted one: the claiming pod boots straight into a topology-
        # aligned Mesh (parallel/mesh.py::mesh_from_bundle) instead of
        # reshaping jax.devices() enumeration order. The status bundle's
        # worker slots are BLOCK positions; the env copy remaps them to
        # this clique's CAS indices — the order jax.devices() actually
        # enumerates (process index = TPU_WORKER_ID). Absent bundle =
        # absent env: the client falls back to enumeration order, so a
        # cluster without topology attributes keeps working unchanged.
        bundle = cd.status.mesh_bundle
        if bundle is not None:
            bundle = bundle.remap_workers(ranks)
            env[MESH_BUNDLE_ENV] = bundle.to_json()
            env[PROCESS_BOUNDS_ENV] = bundle.process_bounds
        return env
