"""compute-domain-kubelet-plugin — DRA plugin for driver
``compute-domain.tpu.google.com``.

Role of the reference's compute-domain-kubelet-plugin (SURVEY.md §2.1,
§2.5, §3.5): publishes exactly one channel device + one daemon device per
node, gates workload Prepare on domain readiness via the retry-until-ready
loop, and labels the node so the per-CD DaemonSet follows the workload.
"""
