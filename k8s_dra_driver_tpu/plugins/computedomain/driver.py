"""ComputeDomainDriver — the compute-domain-kubelet-plugin core.

Publishes channel-0 + one daemon device (reference driver.go:46-58), and
implements the workload gate chain of SURVEY.md §3.5:

    assert channel unallocated -> assert CD namespace (anti-spoof)
    -> add node label (DaemonSet follows) -> assert domain ready
    (retryable) -> CDI edits with slice bootstrap env

plus the PrepareAborted tombstone state
(/root/reference/cmd/compute-domain-kubelet-plugin/device_state.go:206-208,
430-446): after HandleError aborts a claim, re-preparing it fails
permanently until the tombstone ages out.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Tuple

from k8s_dra_driver_tpu.api.configs import (
    COMPUTE_DOMAIN_DRIVER_NAME,
    ComputeDomainChannelConfig,
    ComputeDomainDaemonConfig,
    nonstrict_decode,
)
from k8s_dra_driver_tpu.cdi import CDIHandler, ContainerEdits
from k8s_dra_driver_tpu.k8s import APIServer
from k8s_dra_driver_tpu.k8s.core import (
    Device,
    RESOURCE_CLAIM,
    RESOURCE_SLICE,
    ResourceClaim,
    ResourcePool,
    ResourceSlice,
)
from k8s_dra_driver_tpu.k8s.objects import new_meta
from k8s_dra_driver_tpu.pkg import devcaps
from k8s_dra_driver_tpu.pkg import featuregates as fg
from k8s_dra_driver_tpu.pkg import tracing
from k8s_dra_driver_tpu.pkg.bootid import read_boot_id
from k8s_dra_driver_tpu.pkg.events import (
    EventRecorder,
    REASON_CHECKPOINT_RECOVERED,
    REASON_PREPARE_FAILED,
    REASON_PREPARED_DEVICES,
)
from k8s_dra_driver_tpu.pkg.flock import Flock
from k8s_dra_driver_tpu.pkg.metrics import DRARequestMetrics, Registry
from k8s_dra_driver_tpu.pkg.sliceconfig import Isolation, SliceAgentConfig
from k8s_dra_driver_tpu.plugins.checkpoint import (
    Checkpoint,
    CheckpointStore,
    FAULT_PRE_COMPLETED,
    FAULT_STARTED_PERSISTED,
    MIGRATION_CHECKPOINTED,
    PREPARE_ABORTED,
    PREPARE_COMPLETED,
    PREPARE_STARTED,
    PreparedClaim,
    PreparedDevice,
)
from k8s_dra_driver_tpu.plugins.computedomain.computedomain import (
    ComputeDomainManager,
    PermanentError,
    RetryableError,
)
from k8s_dra_driver_tpu.plugins.tpu.deviceinfo import create_or_update_slice
from k8s_dra_driver_tpu.tpulib.lib import TpuLib

log = logging.getLogger(__name__)

CHANNEL_DEVICE = "channel-0"
DAEMON_DEVICE = "daemon"
PU_LOCK_TIMEOUT_S = 10.0
# Bound on concurrent CDI spec writes in one batch (mirrors the tpu
# plugin's device_state pipeline).
CDI_MATERIALIZE_WORKERS = 8
# Channels CDI-injected under AllocationMode All (the reference's
# maxImexChannelCount, cmd/compute-domain-kubelet-plugin/main.go).
DEFAULT_MAX_CHANNEL_COUNT = 32


class ComputeDomainDriver:
    def __init__(
        self,
        api: APIServer,
        node_name: str,
        tpulib: TpuLib,
        plugin_dir: str,
        cdi_root: Optional[str] = None,
        gates: Optional[fg.FeatureGates] = None,
        metrics_registry: Optional[Registry] = None,
        driver_name: str = COMPUTE_DOMAIN_DRIVER_NAME,
        max_channel_count: int = DEFAULT_MAX_CHANNEL_COUNT,
        slice_config: Optional[SliceAgentConfig] = None,
    ):
        self.max_channel_count = max_channel_count
        # Deployment mode/isolation (pkg/sliceconfig, the pkg/imex analog) —
        # validated against the gates at binary startup.
        self.slice_config = slice_config or SliceAgentConfig()
        self.api = api
        self.node_name = node_name
        self.driver_name = driver_name
        self.gates = gates or fg.FeatureGates()
        self.inventory = tpulib.enumerate()
        self.cd = ComputeDomainManager(api, node_name, self.inventory)
        self.cdi = CDIHandler(cdi_root)
        registry = metrics_registry or Registry()
        self.metrics = DRARequestMetrics(driver=driver_name, registry=registry)
        self.recorder = EventRecorder(api, "compute-domain-kubelet-plugin",
                                      metrics_registry=registry)
        os.makedirs(plugin_dir, exist_ok=True)
        self._mutex = threading.Lock()
        self._pu_lock = Flock(os.path.join(plugin_dir, "pu.lock"))
        self._pool_generation = 1
        self._store = CheckpointStore(
            plugin_dir, Flock, read_boot_id(),
            on_discard=self.cdi.delete_claim_spec_file,
        )
        # Crash-injection seam for the batched pipeline (same FAULT_* points
        # as plugins.tpu.device_state).
        self.fault_hook: Optional[Callable[[str], None]] = None

    def _fire_fault(self, point: str) -> None:
        if self.fault_hook is not None:
            self.fault_hook(point)

    def _get_checkpoint(self) -> Checkpoint:
        return self._store.get()

    def _save_checkpoint(self, cp: Checkpoint) -> None:
        self._store.save(cp)  # tpulint: disable=lock-order -- one locked atomic write; test-seeding helper, never paired with _get_checkpoint on a live path

    # -- publishing ----------------------------------------------------------

    def publish_resources(self) -> None:
        devices = [
            Device(
                name=CHANNEL_DEVICE,
                attributes={
                    "type": "channel",
                    "tpu.google.com/iciDomain": self.inventory.ici_domain,
                },
            ),
            Device(
                name=DAEMON_DEVICE,
                attributes={
                    "type": "daemon",
                    "tpu.google.com/iciDomain": self.inventory.ici_domain,
                },
            ),
        ]
        rs = ResourceSlice(
            meta=new_meta(f"{self.node_name}-{self.driver_name}"),
            driver=self.driver_name,
            node_name=self.node_name,
            pool=ResourcePool(name=self.node_name, generation=self._pool_generation),
            devices=devices,
        )
        self._pool_generation += 1
        create_or_update_slice(self.api, rs)

    def start(self, cleanup_interval_s: float = 600.0) -> None:
        self.publish_resources()
        self._stop_evt = threading.Event()
        self._cleanup_thread = None
        if cleanup_interval_s > 0:
            # interval <= 0 disables the timer thread (see TpuDriver.start:
            # thousands of in-process sim plugins must not each own one).
            self._cleanup_thread = threading.Thread(
                target=self._cleanup_loop, args=(cleanup_interval_s,),
                name="cd-tombstone-cleanup", daemon=True,
            )
            self._cleanup_thread.start()

    def shutdown(self) -> None:
        if getattr(self, "_stop_evt", None) is not None:
            self._stop_evt.set()
            if self._cleanup_thread is not None:
                self._cleanup_thread.join(timeout=5)

    def healthy(self) -> bool:
        """Registration-status leg of the healthcheck probe (health.go:145)."""
        stop_evt = getattr(self, "_stop_evt", None)
        return stop_evt is not None and not stop_evt.is_set()

    def _cleanup_loop(self, interval_s: float) -> None:
        """Periodic tombstone expiry (the reference's cleanup manager runs
        this tier, cleanup.go:99-141)."""
        while not self._stop_evt.wait(interval_s):
            try:
                self.expire_aborted()
            except Exception:  # noqa: BLE001
                log.exception("tombstone expiry failed")

    # -- DRA service ----------------------------------------------------------

    def prepare_resource_claims(
        self, claims: List[ResourceClaim]
    ) -> Dict[str, object]:
        """Batch-amortized prepare: one pu flock acquire and one checkpoint
        session (two fsyncs) per NodePrepareResources call; per-claim gate
        failures come back inline without failing siblings."""
        if not claims:
            return {}
        out: Dict[str, object] = {}
        with self.metrics.track_batch("PrepareResourceClaims", len(claims)), \
                tracing.span(
                    "dra.prepare_batch", driver=self.driver_name,
                    batch_size=len(claims),
                    claim_uids=[c.uid for c in claims]) as sp:
            try:
                with self._pu_lock.hold(timeout=PU_LOCK_TIMEOUT_S,
                                        trace_name="pu_flock"):
                    out = self._prepare_batch(claims)
            except Exception as e:  # noqa: BLE001 — whole-batch failure
                log.warning("cd prepare batch of %d failed: %s", len(claims), e)
                out = {c.uid: e for c in claims}
            failed = sum(1 for r in out.values() if isinstance(r, Exception))
            sp.attrs["failed_claims"] = failed
        self.metrics.record_claim_errors("PrepareResourceClaims", failed)
        for claim in claims:
            r = out.get(claim.uid)
            if isinstance(r, Exception):
                log.warning("cd prepare %s failed: %s", claim.key, r)
                self.recorder.warning(
                    claim, REASON_PREPARE_FAILED,
                    f"prepare on {self.node_name} failed: {r}")
            elif r is not None:
                self.recorder.normal(
                    claim, REASON_PREPARED_DEVICES,
                    f"prepared channel/daemon devices on {self.node_name}")
        return out

    def unprepare_resource_claims(self, claim_uids: List[str]) -> Dict[str, Optional[Exception]]:
        if not claim_uids:
            return {}
        out: Dict[str, Optional[Exception]] = {}
        with self.metrics.track_batch("UnprepareResourceClaims", len(claim_uids)), \
                tracing.span(
                    "dra.unprepare_batch", driver=self.driver_name,
                    batch_size=len(claim_uids),
                    claim_uids=list(claim_uids)) as sp:
            try:
                with self._pu_lock.hold(timeout=PU_LOCK_TIMEOUT_S,
                                        trace_name="pu_flock"):
                    out = self._unprepare_batch(claim_uids)
            except Exception as e:  # noqa: BLE001 — whole-batch failure
                log.warning("cd unprepare batch of %d failed: %s",
                            len(claim_uids), e)
                out = {uid: e for uid in claim_uids}
            failed = sum(1 for r in out.values() if r is not None)
            sp.attrs["failed_claims"] = failed
        self.metrics.record_claim_errors("UnprepareResourceClaims", failed)
        return out

    def handle_error(self, claim_uid: str) -> None:
        """Abort a claim (kubeletplugin HandleError analog): mark the
        tombstone so future Prepares reject it until the TTL expires.

        One pu-flock + checkpoint-session hold end to end: the old
        get→mutate→save pair released the cp flock between load and
        write, so a concurrent batch in another plugin process could
        slip a checkpoint in between and have it overwritten wholesale.

        Lock order matches the prepare path: pu flock OUTSIDE the
        in-process mutex (prepare takes the flock in the gRPC wrapper,
        then _mutex inside _prepare_batch) — taking _mutex first here
        would deadlock-by-timeout against a concurrent prepare.
        """
        with self._pu_lock.hold(timeout=PU_LOCK_TIMEOUT_S):
            with self._mutex:
                with self._store.session() as sess:
                    cp = sess.checkpoint
                    entry = cp.claims.get(claim_uid)
                    if entry is None:
                        entry = cp.claims[claim_uid] = PreparedClaim(
                            claim_uid=claim_uid)
                    entry.state = PREPARE_ABORTED
                    entry.aborted_at = time.time()
                    sess.save()
                    self.cdi.delete_claim_spec_file(claim_uid)

    def migrate_claim_out(self, claim_uid: str) -> PreparedClaim:
        """Checkpoint-aware release for resize/migration quiesce — the
        channel/daemon half of the MigrationCheckpoint handshake both
        kubelet plugins now share. The state transition is fsync'd BEFORE
        the CDI spec is removed (the channel plugin's only node-side
        artifact), so a crash mid-quiesce leaves an entry the next Prepare
        clears and re-prepares fresh (the branch _prepare_batch already
        carries). Same pu-flock-then-mutex order as every other path."""
        with tracing.span("dra.migrate_out", driver=self.driver_name,
                          claim_uid=claim_uid), \
                self._pu_lock.hold(timeout=PU_LOCK_TIMEOUT_S,
                                   trace_name="pu_flock"):
            with self._mutex:
                with self._store.session() as sess:
                    cp = sess.checkpoint
                    entry = cp.claims.get(claim_uid)
                    if entry is None:
                        raise RetryableError(
                            f"claim {claim_uid} has no checkpoint entry on "
                            f"this node; nothing to migrate")
                    if entry.state != PREPARE_COMPLETED:
                        raise RetryableError(
                            f"claim {claim_uid} is {entry.state}, not "
                            f"{PREPARE_COMPLETED}; refusing to migrate")
                    entry.state = MIGRATION_CHECKPOINTED
                    entry.migration_started_at = time.time()
                    sess.save()
                    self.cdi.delete_claim_spec_file(claim_uid)
                    return entry

    def migrate_claim_end(self, claim_uid: str) -> None:
        """Drop the MigrationCheckpoint entry once the claim completed on
        its destination (or the same-node re-prepare cleared it already);
        idempotent, a no-op for claims in any other state."""
        with tracing.span("dra.migrate_end", driver=self.driver_name,
                          claim_uid=claim_uid), \
                self._pu_lock.hold(timeout=PU_LOCK_TIMEOUT_S,
                                   trace_name="pu_flock"):
            with self._mutex:
                with self._store.session() as sess:
                    cp = sess.checkpoint
                    entry = cp.claims.get(claim_uid)
                    if (entry is not None
                            and entry.state == MIGRATION_CHECKPOINTED):
                        del cp.claims[claim_uid]
                        sess.save()

    def expire_aborted(self) -> int:
        """Drop expired PrepareAborted tombstones (cleanup loop tier,
        reference cleanup.go:35-37). Returns count removed. Same
        single-session read-modify-write — and same pu-flock-then-mutex
        lock order — as handle_error; this runs on the tombstone-cleanup
        thread concurrently with gRPC prepares."""
        with self._pu_lock.hold(timeout=PU_LOCK_TIMEOUT_S):
            with self._mutex:
                with self._store.session() as sess:
                    cp = sess.checkpoint
                    doomed = [u for u, e in cp.claims.items()
                              if e.aborted_expired()]
                    for u in doomed:
                        del cp.claims[u]
                    if doomed:
                        sess.save()
                    return len(doomed)

    # -- prepare internals ----------------------------------------------------

    def _decode_config(self, claim: ResourceClaim):
        for cc in claim.config:
            if cc.opaque is None or cc.opaque.driver != self.driver_name:
                continue
            cfg = nonstrict_decode(cc.opaque.parameters)
            cfg.validate()
            return cfg
        raise PermanentError(f"claim {claim.key} has no {self.driver_name} config")

    def _prepare_batch(self, claims: List[ResourceClaim]) -> Dict[str, object]:
        # tpulint: holds=pu-flock (prepare_resource_claims takes it)
        """The batched state machine: one checkpoint session, two fsync'd
        writes (all PrepareStarted, then all PrepareCompleted), per-claim
        gate chains run sequentially (they mutate node labels and read the
        API) with the CDI spec writes fanned out in between."""
        out: Dict[str, object] = {}
        with self._mutex:
            with self._store.session() as sess:
                cp = sess.checkpoint
                dirty = False
                pending: List[Tuple[ResourceClaim, object, List[str]]] = []
                seen: set = set()
                for claim in claims:
                    uid = claim.uid
                    if uid in seen or uid in out:
                        continue  # duplicate uid in one batch: first wins
                    entry = cp.claims.get(uid)
                    if entry is not None and entry.state == PREPARE_COMPLETED:
                        out[uid] = [i for d in entry.devices for i in d.cdi_device_ids]
                        continue
                    try:
                        if entry is not None and entry.state == PREPARE_ABORTED:
                            if not entry.aborted_expired():
                                raise PermanentError(
                                    f"claim {uid} was aborted; refusing to re-prepare")
                            del cp.claims[uid]
                            dirty = True
                            self.recorder.warning(
                                claim, REASON_CHECKPOINT_RECOVERED,
                                f"expired PrepareAborted tombstone cleared on "
                                f"{self.node_name}; re-preparing")
                        elif (entry is not None
                                and entry.state == MIGRATION_CHECKPOINTED):
                            # Mid-migration claim re-preparing here (the
                            # rollback-to-source path of the live-repack
                            # rebalancer): clear the migration record and
                            # prepare fresh — channel/daemon devices hold no
                            # node state beyond the CDI spec.
                            log.info("claim %s has a MigrationCheckpoint "
                                     "entry; clearing and re-preparing", uid)
                            del cp.claims[uid]
                            self.cdi.delete_claim_spec_file(uid)
                            dirty = True
                        devices = [
                            r.device
                            for r in (claim.allocation.devices if claim.allocation else [])
                            if r.driver == self.driver_name
                        ]
                        if not devices:
                            raise PermanentError(
                                f"claim {claim.key}: no {self.driver_name} devices")
                        cfg = self._decode_config(claim)
                    except Exception as e:  # noqa: BLE001 — per-claim contract
                        out[uid] = e
                        continue
                    cp.claims[uid] = PreparedClaim(
                        claim_uid=uid, namespace=claim.namespace, name=claim.name,
                        state=PREPARE_STARTED, started_at=time.time(),
                    )
                    seen.add(uid)
                    pending.append((claim, cfg, devices))
                    dirty = True
                if not pending:
                    if dirty:
                        sess.save()
                    return out
                # Write #1: every PrepareStarted entry in ONE fsync'd write.
                sess.save()
                self._fire_fault(FAULT_STARTED_PERSISTED)

                # Gate chains are sequential: they plant node labels, read
                # domain/clique state, and check channel exclusivity against
                # cp — including batch siblings completed just above.
                staged: List[Tuple[ResourceClaim, Dict[str, ContainerEdits],
                                   List[PreparedDevice]]] = []
                for claim, cfg, devices in pending:
                    try:
                        if isinstance(cfg, ComputeDomainDaemonConfig):
                            edits, prepared = self._stage_daemon(claim, cfg, devices)
                        elif isinstance(cfg, ComputeDomainChannelConfig):
                            edits, prepared = self._stage_channel(claim, cfg, devices, cp)
                        else:
                            raise PermanentError(
                                f"config kind {cfg.kind} not valid here")
                    except Exception as e:  # noqa: BLE001 — per-claim contract
                        # Retryable or not, this attempt is over: clear the
                        # Started entry so the next Prepare starts clean.
                        cp.claims.pop(claim.uid, None)
                        self.cdi.delete_claim_spec_file(claim.uid)
                        out[claim.uid] = e
                        continue
                    # Mark completed in the in-memory cp NOW so a batch
                    # sibling's channel-exclusivity check sees this claim;
                    # it is persisted by write #2 below.
                    entry = cp.claims[claim.uid]
                    entry.devices = prepared
                    entry.state = PREPARE_COMPLETED
                    entry.completed_at = time.time()
                    staged.append((claim, edits, prepared))

                # Fan the CDI spec writes out between the two checkpoint
                # writes (independent fsync'd files). Capture the batch span
                # context: pool threads carry no thread-local context.
                batch_ctx = tracing.current()

                def materialize(item) -> List[str]:
                    claim, edits, prepared = item
                    with tracing.span("cdi.materialize", parent=batch_ctx,
                                      claim_uid=claim.uid):
                        ids = self.cdi.create_claim_spec_file(claim.uid, edits)
                        for d in prepared:
                            d.cdi_device_ids = list(ids)
                        return ids

                results: Dict[str, object] = {}
                if len(staged) == 1:
                    try:
                        results[staged[0][0].uid] = materialize(staged[0])
                    except Exception as e:  # noqa: BLE001
                        results[staged[0][0].uid] = e
                elif staged:
                    workers = min(CDI_MATERIALIZE_WORKERS, len(staged))
                    with ThreadPoolExecutor(
                        max_workers=workers, thread_name_prefix="cd-cdi-spec"
                    ) as pool:
                        futs = {item[0].uid: pool.submit(materialize, item)
                                for item in staged}
                        for uid, fut in futs.items():
                            try:
                                results[uid] = fut.result()
                            except Exception as e:  # noqa: BLE001
                                results[uid] = e
                for claim, _edits, _prepared in staged:
                    got = results[claim.uid]
                    if isinstance(got, Exception):
                        cp.claims.pop(claim.uid, None)
                        self.cdi.delete_claim_spec_file(claim.uid)
                        out[claim.uid] = got
                    else:
                        out[claim.uid] = got
                self._fire_fault(FAULT_PRE_COMPLETED)
                # Write #2: every PrepareCompleted transition in ONE write.
                sess.save()
        return out

    def _stage_daemon(
        self, claim: ResourceClaim, cfg: ComputeDomainDaemonConfig, devices: List[str]
    ) -> Tuple[Dict[str, ContainerEdits], List[PreparedDevice]]:
        if devices != [DAEMON_DEVICE]:
            raise PermanentError(f"daemon claim must allocate exactly [{DAEMON_DEVICE}]")
        edits = ContainerEdits(env={
            "COMPUTE_DOMAIN_UUID": cfg.domain_id,
            "COMPUTE_DOMAIN_NAMESPACE": claim.namespace,
            "NODE_NAME": self.node_name,
            "ICI_DOMAIN": self.inventory.ici_domain,
        })
        return {DAEMON_DEVICE: edits}, [PreparedDevice(
            name=DAEMON_DEVICE, device_type="daemon",
            extra={"domain": cfg.domain_id},
        )]

    def _assert_channel_not_allocated(
        self, cp: Checkpoint, claim_uid: str, channel_id: int
    ) -> None:
        """At most one claim may hold a channel id on this node
        (assertImexChannelNotAllocated, reference device_state.go:878-906).
        The checkpoint is the allocation source of truth. Entries written
        before channel ids existed implicitly hold channel 0."""
        for other_uid, entry in cp.claims.items():
            if other_uid == claim_uid:
                continue
            # Only completed prepares hold a channel; Aborted tombstones and
            # stale Started entries must not block the id (the reference
            # filters on ClaimCheckpointStatePrepareCompleted the same way).
            if entry.state != PREPARE_COMPLETED:
                continue
            for d in entry.devices:
                if d.device_type == "channel" and d.extra.get("channel_id", 0) == channel_id:
                    raise PermanentError(
                        f"slice channel {channel_id} is already allocated to "
                        f"claim {other_uid} on this node"
                    )

    def _channel_cdi_nodes(self, cfg: ComputeDomainChannelConfig) -> List[dict]:
        """Char-device nodes to inject: all channels up to max_channel_count
        (AllocationMode All, device_state.go:690-733) or just the claim's.
        On a real node a missing kernel channel class is a fault — retry
        until the facility comes up; only the mock seam (CPU CI,
        UsingAltProcDevices analog) degrades to env-only bootstrap."""
        if devcaps.get_char_device_major() is None:
            if devcaps.using_alt_proc_devices():
                return []
            raise RetryableError(
                f"char device class {devcaps.CHANNEL_CLASS_NAME!r} not registered "
                "in /proc/devices (kernel facility not up yet?)"
            )
        if cfg.allocation_mode == "Single" or (
            self.slice_config.isolation == Isolation.CHANNEL
        ):
            # Channel isolation: workloads only ever see their own channel
            # device, regardless of the claim's allocation mode (the
            # pkg/imex Isolation=channel semantics).
            dev = devcaps.channel_device(cfg.channel_id)
            return [dev.to_cdi_node()] if dev else []
        chans = devcaps.enumerate_channels(self.max_channel_count)
        return [c.to_cdi_node() for c in chans]

    def _stage_channel(
        self,
        claim: ResourceClaim,
        cfg: ComputeDomainChannelConfig,
        devices: List[str],
        cp: Checkpoint,
    ) -> Tuple[Dict[str, ContainerEdits], List[PreparedDevice]]:
        if devices != [CHANNEL_DEVICE]:
            raise PermanentError(f"channel claim must allocate exactly [{CHANNEL_DEVICE}]")
        if cfg.channel_id >= self.max_channel_count:
            raise PermanentError(
                f"channel_id {cfg.channel_id} >= max channel count {self.max_channel_count}"
            )
        cd_uid = cfg.domain_id
        self._assert_channel_not_allocated(cp, claim.uid, cfg.channel_id)
        # The gate chain (§3.5) — order matters: anti-spoof before any
        # mutation; label before the ready check so the DaemonSet can land.
        domain, clique = self.cd.resolve(cd_uid)
        self.cd.assert_domain_namespace(domain, claim.namespace)
        if not self.slice_config.host_managed:
            # Host-managed agents ship with the node image: no DaemonSet
            # follows the workload, so no label is planted (reference
            # HostManagedIMEXDaemon path).
            self.cd.add_node_label(cd_uid)
        # Re-read the clique: it may have appeared since resolve().
        clique = self.cd.get_clique(domain)
        self.cd.assert_domain_ready(domain, clique)
        env = self.cd.bootstrap_env(domain, clique)
        env["TPU_SLICE_CHANNEL_ID"] = str(cfg.channel_id)
        edits = ContainerEdits(env=env, char_devices=self._channel_cdi_nodes(cfg))
        return {CHANNEL_DEVICE: edits}, [PreparedDevice(
            name=CHANNEL_DEVICE, device_type="channel",
            extra={"domain": cd_uid, "channel_id": cfg.channel_id},
        )]

    def _unprepare_batch(
        self, claim_uids: List[str]
    ) -> Dict[str, Optional[Exception]]:
        # tpulint: holds=pu-flock (unprepare_resource_claims takes it)
        """Batched unprepare: one checkpoint session, at most one fsync'd
        write for the whole batch; node-label cleanup runs once per domain
        against the batch's final state."""
        out: Dict[str, Optional[Exception]] = {}
        domains_to_check: set = set()
        with self._mutex:
            with self._store.session() as sess:
                cp = sess.checkpoint
                dirty = False
                for uid in claim_uids:
                    try:
                        entry = cp.claims.get(uid)
                        if entry is None:
                            self.cdi.delete_claim_spec_file(uid)
                            out[uid] = None
                            continue
                        if entry.state == PREPARE_ABORTED:
                            # Keep the tombstone: it guards against a stale
                            # Prepare retry arriving after this Unprepare
                            # (reference device_state.go:328-329); TTL
                            # expiry removes it.
                            self.cdi.delete_claim_spec_file(uid)
                            out[uid] = None
                            continue
                        domains_to_check |= {
                            d.extra.get("domain") for d in entry.devices
                            if d.device_type == "channel"
                        }
                        del cp.claims[uid]
                        dirty = True
                        self.cdi.delete_claim_spec_file(uid)
                        out[uid] = None
                    except Exception as e:  # noqa: BLE001 — per-claim contract
                        out[uid] = e
                if dirty:
                    sess.save()
                # Last channel claim for a domain on this node: drop the
                # label so the DaemonSet can leave with the workload.
                # Checked once per domain against the post-batch state.
                for cd_uid in filter(None, domains_to_check):
                    still_used = any(
                        d.extra.get("domain") == cd_uid
                        for e in cp.claims.values() for d in e.devices
                        if d.device_type == "channel"
                    )
                    if not still_used:
                        try:
                            self.cd.remove_node_label(cd_uid)
                        except Exception:  # noqa: BLE001 — controller also sweeps
                            log.exception("label removal for %s failed", cd_uid)
        return out

    def prepared_claims(self) -> Dict[str, PreparedClaim]:
        return dict(self._get_checkpoint().claims)
