"""Crash-consistent claim checkpoint, shared by both kubelet plugins.

Carries the reference's checkpoint semantics wholesale — they encode years
of crash-consistency fixes (SURVEY.md §5-checkpoint;
/root/reference/cmd/gpu-kubelet-plugin/checkpoint.go:26-140,
checkpointv.go:59-133, device_state.go:246-302,740-805):

- versioned schema with migration to latest (V1 had no boot id; loading it
  yields an empty boot id, which mismatches the live one and recreates);
- checksum over the canonical payload; on mismatch a unified diff of
  on-disk vs re-marshaled JSON is raised for operators;
- node boot-id invalidation across reboots;
- claim states PrepareStarted -> PrepareCompleted, plus the PrepareAborted
  tombstone (TTL'd) the compute-domain plugin uses;
- every write is atomic (tmp + fsync + rename).

Batched access: ``CheckpointStore.session()`` holds the cp flock across one
read-modify-write *sequence*, so an N-claim NodePrepareResources batch pays
one lock acquire and two fsyncs (one save persisting every PrepareStarted,
one persisting every PrepareCompleted) instead of N of each.
"""

from __future__ import annotations

import difflib
import json
import os
import time
import zlib
from contextlib import contextmanager
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, Iterator, List, Optional

LATEST_VERSION = "v2"

PREPARE_STARTED = "PrepareStarted"
PREPARE_COMPLETED = "PrepareCompleted"
PREPARE_ABORTED = "PrepareAborted"
# Live-repack handshake: a PrepareCompleted claim being migrated off this
# node. The state is persisted BEFORE any device is released, so a crash
# mid-migration leaves an entry whose rollback (stale-entry path /
# destroy_unknown_partitions) frees every partition — a leaked ICI
# partition is impossible by construction. The entry keeps its ``devices``
# list as the source-placement record the rollback-to-source path
# re-prepares from.
MIGRATION_CHECKPOINTED = "MigrationCheckpoint"

# Fault-injection points both plugins' batched pipelines fire between their
# two checkpoint writes (tests install a hook that raises to simulate a
# crash at the exact point); shared here so the seams can never drift.
FAULT_STARTED_PERSISTED = "batch:started-persisted"   # after write #1
FAULT_PRE_COMPLETED = "batch:pre-completed"           # before write #2

# TTL for PrepareAborted tombstones (reference:
# cmd/compute-domain-kubelet-plugin/cleanup.go:35-37).
ABORTED_TTL_S = 10 * 60.0


class CorruptCheckpointError(Exception):
    def __init__(self, path: str, diff: str):
        super().__init__(f"checkpoint {path} failed checksum; diff:\n{diff}")
        self.diff = diff


@dataclass
class PreparedDevice:
    name: str = ""                      # canonical device name (tpu-0, ...)
    device_type: str = ""               # tpu | subslice | vfio | channel | daemon
    chip_indices: List[int] = field(default_factory=list)
    cdi_device_ids: List[str] = field(default_factory=list)
    request: str = ""                   # claim request this satisfied
    extra: Dict[str, Any] = field(default_factory=dict)


@dataclass
class PreparedClaim:
    claim_uid: str = ""
    namespace: str = ""
    name: str = ""
    state: str = PREPARE_STARTED
    devices: List[PreparedDevice] = field(default_factory=list)
    started_at: float = 0.0
    completed_at: float = 0.0
    aborted_at: float = 0.0
    migration_started_at: float = 0.0

    def aborted_expired(self, now: Optional[float] = None) -> bool:
        if self.state != PREPARE_ABORTED:
            return False
        return (now if now is not None else time.time()) - self.aborted_at > ABORTED_TTL_S


@dataclass
class Checkpoint:
    node_boot_id: str = ""
    claims: Dict[str, PreparedClaim] = field(default_factory=dict)


def _to_payload(cp: Checkpoint) -> Dict[str, Any]:
    return {"node_boot_id": cp.node_boot_id,
            "claims": {uid: asdict(c) for uid, c in cp.claims.items()}}


def _from_payload(data: Dict[str, Any]) -> Checkpoint:
    claims = {}
    for uid, c in data.get("claims", {}).items():
        devices = [PreparedDevice(**d) for d in c.pop("devices", [])]
        claims[uid] = PreparedClaim(**{**c, "devices": devices})
    return Checkpoint(node_boot_id=data.get("node_boot_id", ""), claims=claims)


def _canonical(payload: Dict[str, Any]) -> str:
    return json.dumps(payload, sort_keys=True, separators=(",", ":"))


class CheckpointStore:
    """Locked checkpoint access + boot-id invalidation, shared by both
    kubelet plugins so crash-consistency fixes land once.

    ``on_discard(uid)`` runs for every claim dropped by boot-id invalidation
    (CDI spec removal, sharing-state cleanup, ...).
    """

    def __init__(self, plugin_dir, flock_factory, boot_id: str, on_discard=None):
        import os

        os.makedirs(plugin_dir, exist_ok=True)
        self._lock = flock_factory(os.path.join(plugin_dir, "cp.lock"))
        self._mgr = CheckpointManager(os.path.join(plugin_dir, "checkpoint.json"))
        with self._lock.hold(timeout=10):
            cp = self._mgr.load()
            if cp is None:
                self._mgr.save(Checkpoint(node_boot_id=boot_id))
            elif cp.node_boot_id != boot_id:
                for uid in cp.claims:
                    if on_discard:
                        on_discard(uid)
                self._mgr.save(Checkpoint(node_boot_id=boot_id))

    @property
    def manager(self) -> "CheckpointManager":
        """The underlying manager — tests pin write counts via its
        ``save_count``."""
        return self._mgr

    def get(self) -> "Checkpoint":
        with self._lock.hold(timeout=10):
            cp = self._mgr.load()
            assert cp is not None, "checkpoint disappeared"
            return cp

    def save(self, cp: "Checkpoint") -> None:
        with self._lock.hold(timeout=10):
            self._mgr.save(cp)

    @contextmanager
    def session(self, timeout: float = 10) -> Iterator["CheckpointSession"]:
        """Hold the cp flock across a whole read-modify-write batch.

        The yielded session exposes the loaded checkpoint and a ``save()``
        that writes (one atomic write + fsync per call) WITHOUT re-acquiring
        the lock — the batched prepare pipeline does exactly two saves per
        session. The lock is released even if the caller raises mid-batch
        (crash injection leaves per-claim PrepareStarted tombstones behind,
        recovered by the stale-entry path on restart)."""
        with self._lock.hold(timeout=timeout, trace_name="cp_flock"):
            cp = self._mgr.load()
            assert cp is not None, "checkpoint disappeared"
            yield CheckpointSession(self._mgr, cp)


class CheckpointSession:
    """One locked batch over the checkpoint. ``checkpoint`` is the state as
    loaded (mutate it in place); every ``save()`` is one fsync'd write."""

    def __init__(self, mgr: "CheckpointManager", cp: "Checkpoint"):
        self._mgr = mgr
        self.checkpoint = cp
        self.saves = 0

    def save(self) -> None:
        self._mgr.save(self.checkpoint)
        self.saves += 1


class CheckpointManager:
    """Atomic load/save of the checkpoint file. Callers serialize access via
    the cp flock (device_state owns that)."""

    def __init__(self, path: str):
        self.path = path
        # Write accounting: each save() is exactly one fsync'd atomic write,
        # so tests pin the batched pipeline's write amplification (2 per
        # N-claim batch) by diffing this counter.
        self.save_count = 0

    def load(self) -> Optional[Checkpoint]:
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                raw = f.read()
        except FileNotFoundError:
            return None
        doc = json.loads(raw)
        version = doc.get("version", "v1")
        payload = doc.get("data", {})
        if "checksum" in doc:
            want = doc["checksum"]
            got = zlib.crc32(_canonical(payload).encode())
            if want != got:
                remarshaled = json.dumps(
                    {"version": version, "checksum": got, "data": payload},
                    sort_keys=True, indent=1,
                )
                diff = "\n".join(
                    difflib.unified_diff(
                        raw.splitlines(), remarshaled.splitlines(),
                        fromfile="on-disk", tofile="re-marshaled", lineterm="",
                    )
                )
                raise CorruptCheckpointError(self.path, diff)
        return self._migrate(version, payload)

    @staticmethod
    def _migrate(version: str, payload: Dict[str, Any]) -> Checkpoint:
        if version == "v1":
            # v1 had no boot id: leave it empty so it never matches a live
            # boot id and state is rebuilt (ToLatestVersion analog).
            payload = dict(payload)
            payload.setdefault("node_boot_id", "")
        elif version != LATEST_VERSION:
            raise ValueError(f"unknown checkpoint version {version!r}")
        return _from_payload(payload)

    def save(self, cp: Checkpoint) -> None:
        from k8s_dra_driver_tpu.pkg.tracing import span

        payload = _to_payload(cp)
        doc = {
            "version": LATEST_VERSION,
            "checksum": zlib.crc32(_canonical(payload).encode()),
            "data": payload,
        }
        tmp = f"{self.path}.tmp"
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        # One span per fsync'd write: the batched pipeline's exactly-two
        # checkpoint writes are individually visible in the batch trace.
        with span("checkpoint.save", path=self.path, claims=len(cp.claims)):
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        self.save_count += 1

    def delete(self) -> None:
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass
