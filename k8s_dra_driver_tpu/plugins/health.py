"""Plugin healthcheck service.

The reference exposes a gRPC grpc.health.v1 server on each kubelet plugin
whose Check() performs a LIVE round-trip through the plugin's own serving
surface — a GetInfo against the kubelet registration socket plus a noop
NodePrepareResources against the DRA socket — rather than reporting a cached
flag (/root/reference/cmd/gpu-kubelet-plugin/health.go:39-148). That makes
the probe catch wedged serving loops, not just process liveness.

TPU-native analog: our kubelet seam is the in-process DRA service object
(TpuDriver / ComputeDomainDriver), so the live probe is a noop
prepare_resource_claims([]) call plus the driver's registration status,
served over HTTP (`/healthz`, `/healthz/liveness`) for kubelet httpGet
probes. Responses mirror grpc.health.v1 semantics: 200 "SERVING",
503 "NOT_SERVING", 404 for unknown service names (codes.NotFound,
health.go:122-125).
"""

from __future__ import annotations

import http.server
import logging
import threading
from typing import Optional, Protocol

log = logging.getLogger(__name__)

# Known service names, matching the reference's map (health.go:122).
KNOWN_SERVICES = ("", "liveness")


class DRAService(Protocol):
    """What the healthcheck needs from a plugin driver."""

    def prepare_resource_claims(self, claims): ...
    def healthy(self) -> bool: ...


class Healthcheck:
    """HTTP healthcheck server bound to a plugin driver.

    check() is usable standalone (unit tests, in-process probes); start()
    serves it at /healthz[/<service>] the way the reference serves
    grpc.health.v1 on --healthcheck-port (health.go:51-110).
    """

    def __init__(self, driver: DRAService, host: str = "127.0.0.1", port: int = 0):
        self.driver = driver
        self._host = host
        self._port = port
        self._httpd: Optional[http.server.ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- probe ---------------------------------------------------------------

    def check(self, service: str = "") -> str:
        """Return "SERVING"/"NOT_SERVING"; raise KeyError for unknown service."""
        if service not in KNOWN_SERVICES:
            raise KeyError(service)
        try:
            # Noop request through the real serving path (health.go:138-144):
            # an empty claim list must round-trip without error.
            result = self.driver.prepare_resource_claims([])
            if result != {}:
                return "NOT_SERVING"
            if not self.driver.healthy():
                return "NOT_SERVING"
        except Exception:  # noqa: BLE001 — any probe failure is NOT_SERVING
            log.exception("healthcheck probe failed")
            return "NOT_SERVING"
        return "SERVING"

    # -- HTTP server ---------------------------------------------------------

    def start(self) -> None:
        hc = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 — http.server API
                if self.path in ("/healthz", "/healthz/"):
                    service = ""
                elif self.path.startswith("/healthz/"):
                    service = self.path[len("/healthz/"):]
                else:
                    self.send_error(404)
                    return
                try:
                    status = hc.check(service)
                except KeyError:
                    self.send_error(404, "unknown service")
                    return
                code = 200 if status == "SERVING" else 503
                body = (status + "\n").encode()
                self.send_response(code)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a) -> None:  # quiet
                pass

        self._httpd = http.server.ThreadingHTTPServer((self._host, self._port), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="healthcheck", daemon=True
        )
        self._thread.start()
        log.info("healthcheck serving at %s:%d", *self._httpd.server_address[:2])

    @property
    def port(self) -> int:
        assert self._httpd is not None
        return self._httpd.server_address[1]

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
