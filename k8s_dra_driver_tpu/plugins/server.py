"""DRA plugin endpoint — the kubelet plugin-socket analog.

The reference registers a gRPC socket with the kubelet via the
`kubeletplugin` helper and serves PrepareResourceClaims /
UnprepareResourceClaims over it (SURVEY.md §1 L3→kubelet;
/root/reference/cmd/gpu-kubelet-plugin/driver.go:131-149). Here the plugin
serves the same service over local HTTP and announces itself through a
registration file in the plugin dir — the kubelet-side discovery scan of
the plugin registration directory:

    {plugin_dir}/{driver_name}-registration.json   {"driver", "endpoint", "node"}

Routes:
    POST /v1/prepare     {"claims": [wire ResourceClaim, ...]}
                         -> {"results": {uid: {"cdi_device_ids": [...]}
                                             | {"error", "retryable"}}}
    POST /v1/unprepare   {"claim_uids": [...]} -> {"results": {uid: null|err}}
    GET  /healthz        {"healthy": bool} — the gRPC healthcheck analog
"""

from __future__ import annotations

import json
import logging
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from k8s_dra_driver_tpu.k8s.serialize import from_wire

log = logging.getLogger(__name__)

REGISTRATION_FILE = "registration.json"


def _is_retryable(err: Exception) -> bool:
    # Import here: computedomain pulls api types this module must not require.
    try:
        from k8s_dra_driver_tpu.plugins.computedomain.computedomain import RetryableError
        return isinstance(err, RetryableError)
    except ImportError:  # pragma: no cover
        return False


class DRAPluginServer:
    """Serves a driver's Prepare/Unprepare/health over local HTTP and writes
    the registration file kubelets discover."""

    def __init__(self, driver, plugin_dir: str, node_name: str,
                 host: str = "127.0.0.1", port: int = 0):
        self.driver = driver
        self.plugin_dir = plugin_dir
        self.node_name = node_name
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args: object) -> None:
                pass

            def _send(self, status: int, doc: dict) -> None:
                body = json.dumps(doc).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length", "0"))
                return json.loads(self.rfile.read(n) or b"{}")

            def do_GET(self) -> None:  # noqa: N802
                if self.path == "/healthz":
                    healthy = outer.driver.healthy()
                    self._send(200 if healthy else 503, {"healthy": healthy})
                else:
                    self._send(404, {"error": "NoRoute"})

            def do_POST(self) -> None:  # noqa: N802
                try:
                    if self.path == "/v1/prepare":
                        claims = [from_wire(d) for d in self._body().get("claims", [])]
                        res = outer.driver.prepare_resource_claims(claims)
                        out = {}
                        for uid, r in res.items():
                            if isinstance(r, Exception):
                                out[uid] = {"error": str(r),
                                            "retryable": _is_retryable(r)}
                            else:
                                ids = getattr(r, "cdi_device_ids", r)
                                out[uid] = {"cdi_device_ids": list(ids)}
                        self._send(200, {"results": out})
                    elif self.path == "/v1/unprepare":
                        uids = self._body().get("claim_uids", [])
                        res = outer.driver.unprepare_resource_claims(uids)
                        self._send(200, {"results": {
                            uid: (None if err is None else str(err))
                            for uid, err in res.items()
                        }})
                    else:
                        self._send(404, {"error": "NoRoute"})
                except Exception as e:  # noqa: BLE001 — wire boundary
                    log.exception("plugin request failed")
                    self._send(500, {"error": "Internal", "message": str(e)})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def endpoint(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    @property
    def registration_path(self) -> str:
        # Namespaced by driver name: both kubelet plugins may share one
        # plugin dir, and each driver gets its own registration (the
        # reference gives each driver its own plugin socket the same way).
        return os.path.join(
            self.plugin_dir, f"{self.driver.driver_name}-{REGISTRATION_FILE}"
        )

    def start(self) -> "DRAPluginServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="dra-plugin-server", daemon=True
        )
        self._thread.start()
        os.makedirs(self.plugin_dir, exist_ok=True)
        reg = {
            "driver": self.driver.driver_name,
            "endpoint": self.endpoint,
            "node": self.node_name,
        }
        tmp = self.registration_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(reg, f)
        os.replace(tmp, self.registration_path)
        return self

    def stop(self) -> None:
        try:
            os.unlink(self.registration_path)
        except FileNotFoundError:
            pass
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
