"""DRA kubelet plugins (L3): tpu + computedomain."""
