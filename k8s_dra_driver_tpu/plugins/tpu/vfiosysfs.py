"""Mock VFIO sysfs fixture tree — the passthrough analog of mock-NVML.

The reference tests its rebind logic against a live kernel only in the bats
tier; its CPU-only CI relies on the mock seam pattern
(/root/reference/internal/common/nvcaps.go:33-75). For VFIO we build a
fixture filesystem that mirrors the sysfs surfaces VfioPciManager touches
(/root/reference/cmd/gpu-kubelet-plugin/vfio-device.go:235-257, 319-352):

    {sysfs}/bus/pci/devices/{addr}/driver          -> ../../drivers/<name>
    {sysfs}/bus/pci/devices/{addr}/driver_override
    {sysfs}/bus/pci/devices/{addr}/iommu_group     -> iommu_groups/<n>
    {sysfs}/bus/pci/drivers/{name}/{bind,unbind}
    {sysfs}/bus/pci/drivers_probe
    {dev}/accel*, {dev}/vfio/<n>, {dev}/iommu

The kernel's *reactions* to writes (unbind drops the driver link, probe
binds per driver_override) are emulated inside VfioPciManager when it is
pointed at a non-/sys root — the same in-driver mock-seam approach the
reference uses for ALT_PROC_DEVICES_PATH.
"""

from __future__ import annotations

import os
from typing import Iterable

ACCEL_DRIVER_NAME = "accel-tpu"  # the fixture's stand-in for the TPU driver
IOMMU_GROUP_BASE = 10


def iommu_group_for(chip_index: int) -> int:
    return IOMMU_GROUP_BASE + chip_index


def build_vfio_sysfs(
    sysfs_root: str,
    dev_root: str,
    chips: Iterable,
    *,
    default_driver: str = ACCEL_DRIVER_NAME,
    with_vfio_driver: bool = True,
    with_iommufd: bool = False,
) -> None:
    """Create the fixture tree for ``chips`` (objects with .pci_address,
    .index, .dev_path). Idempotent."""
    drivers = os.path.join(sysfs_root, "bus", "pci", "drivers")
    devices = os.path.join(sysfs_root, "bus", "pci", "devices")
    groups = os.path.join(sysfs_root, "kernel", "iommu_groups")
    os.makedirs(devices, exist_ok=True)
    driver_names = [default_driver] + (["vfio-pci"] if with_vfio_driver else [])
    for name in driver_names:
        d = os.path.join(drivers, name)
        os.makedirs(d, exist_ok=True)
        for f in ("bind", "unbind"):
            open(os.path.join(d, f), "a").close()
    probe = os.path.join(sysfs_root, "bus", "pci", "drivers_probe")
    open(probe, "a").close()
    os.makedirs(os.path.join(dev_root, "vfio"), exist_ok=True)
    # The legacy IOMMU API container device is always present with vfio.
    open(os.path.join(dev_root, "vfio", "vfio"), "a").close()
    if with_iommufd:
        open(os.path.join(dev_root, "iommu"), "a").close()
    for chip in chips:
        ddir = os.path.join(devices, chip.pci_address)
        os.makedirs(ddir, exist_ok=True)
        open(os.path.join(ddir, "driver_override"), "a").close()
        # Fixture metadata: which driver the kernel would pick with no
        # override (real sysfs encodes this in modalias matching).
        with open(os.path.join(ddir, ".default_driver"), "w", encoding="utf-8") as f:
            f.write(default_driver)
        link = os.path.join(ddir, "driver")
        if not os.path.islink(link):
            os.symlink(os.path.join("..", "..", "drivers", default_driver), link)
        gdir = os.path.join(groups, str(iommu_group_for(chip.index)))
        os.makedirs(gdir, exist_ok=True)
        glink = os.path.join(ddir, "iommu_group")
        if not os.path.islink(glink):
            os.symlink(
                os.path.relpath(gdir, ddir), glink
            )
        # The accel node the workload would otherwise use.
        accel = os.path.join(dev_root, os.path.basename(chip.dev_path))
        open(accel, "a").close()
