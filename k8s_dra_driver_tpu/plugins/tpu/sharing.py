"""Chip-sharing bookkeeping: time-slicing intervals and premapped budgets.

The reference programs sharing into the device (NVML SetTimeSlice) or runs
an MPS control daemon (/root/reference/cmd/gpu-kubelet-plugin/
sharing.go:139-474). TPUs have neither knob: sharing is realized as runtime
environment handed to the workload (scheduler hints + premapped HBM
budgets), so this manager is authoritative bookkeeping — persisted next to
the checkpoint so rollback works across plugin restarts — plus the env
edits the CDI spec carries.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, Optional, Sequence, Tuple

from k8s_dra_driver_tpu.api.configs import MpsLikePremappedConfig

# Interval name -> microseconds hint handed to the runtime.
TIME_SLICE_US = {"Default": 0, "Short": 2000, "Medium": 10000, "Long": 50000}


def _pow2_floor(n: int) -> int:
    """Largest power of two <= n (libtpu's premapped buffer size must be
    a power of two); 0 stays 0."""
    return 1 << (n.bit_length() - 1) if n > 0 else 0


class SharingConflictError(Exception):
    """A sharing request contradicts existing records or chip capacity —
    the Prepare-time enforcement the reference does for MPS pinned-memory
    limits (/root/reference/api/nvidia.com/resource/v1beta1/validate.go:25-106)."""


class SharingManager:
    def __init__(self, plugin_dir: str,
                 hbm_by_chip: Optional[Dict[int, int]] = None):
        """``hbm_by_chip`` (chip index -> HBM bytes) bounds premapped
        budgets; chips absent from the map are unbounded (mock/test use)."""
        self._path = os.path.join(plugin_dir, "sharing.json")
        self._mu = threading.Lock()
        self._hbm = dict(hbm_by_chip or {})
        self._state: Dict[str, dict] = {}  # "claim_uid:chip" -> record
        self._load()

    def _load(self) -> None:
        try:
            with open(self._path, "r", encoding="utf-8") as f:
                self._state = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            self._state = {}

    def _save(self) -> None:
        tmp = self._path + ".tmp"
        os.makedirs(os.path.dirname(self._path) or ".", exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self._state, f, sort_keys=True)
        os.replace(tmp, self._path)

    @staticmethod
    def _key(claim_uid: str, chip: int) -> str:
        return f"{claim_uid}:{chip}"

    @staticmethod
    def _key_uid(key: str) -> str:
        return key.rsplit(":", 1)[0]

    def _check_mode_conflict(
        self, claim_uid: str, chips: Sequence[int], mode: str
    ) -> None:
        """A chip cannot carry timeslice and premapped records from
        different claims at once (a claim's own records may be rewritten by
        a more specific config — that is precedence, not a conflict)."""
        want = set(chips)
        for key, r in self._state.items():
            if (r["chip"] in want and r["mode"] != mode
                    and self._key_uid(key) != claim_uid):
                raise SharingConflictError(
                    f"chip {r['chip']}: claim {self._key_uid(key)} already "
                    f"shares it in {r['mode']} mode; cannot add {mode}"
                )

    def set_time_slice(self, claim_uid: str, chips: Sequence[int], interval: str) -> None:
        if interval not in TIME_SLICE_US:
            raise ValueError(f"unknown interval {interval!r}")
        with self._mu:
            self._check_mode_conflict(claim_uid, chips, "timeslice")
            for c in chips:
                self._state[self._key(claim_uid, c)] = {
                    "mode": "timeslice", "interval": interval, "chip": c,
                }
            self._save()

    def set_premapped(
        self, claim_uid: str, chips: Sequence[int], cfg: MpsLikePremappedConfig
    ) -> None:
        with self._mu:
            self._check_mode_conflict(claim_uid, chips, "premapped")
            budgets: Dict[int, int] = {}
            for c in chips:
                budget = cfg.per_chip_premapped_hbm_bytes.get(
                    c, cfg.default_premapped_hbm_bytes
                )
                if budget <= 0:
                    # Admission can't know which chip the allocator picks:
                    # a config whose per-chip overrides miss this chip and
                    # whose default is 0 surfaces here.
                    raise SharingConflictError(
                        f"chip {c}: premapped sharing with no budget (config "
                        f"covers other chips only; set a default)"
                    )
                cap = self._hbm.get(c)
                if cap is not None:
                    # Per-chip sum over every *other* claim's records plus
                    # this budget must fit the silicon (the pinned-memory
                    # bound of validate.go:25-106, enforced where the chip
                    # capacity is actually known).
                    others = sum(
                        r["bytes"]
                        for key, r in self._state.items()
                        if r["chip"] == c and r["mode"] == "premapped"
                        and self._key_uid(key) != claim_uid
                    )
                    if others + budget > cap:
                        raise SharingConflictError(
                            f"chip {c}: premapped budget {budget} + {others} "
                            f"already premapped exceeds HBM {cap}"
                        )
                budgets[c] = budget
            for c, budget in budgets.items():
                self._state[self._key(claim_uid, c)] = {
                    "mode": "premapped", "bytes": budget, "chip": c,
                }
            self._save()

    def clear(self, claim_uid: str, chips: Sequence[int]) -> None:
        with self._mu:
            for c in chips:
                self._state.pop(self._key(claim_uid, c), None)
            self._save()

    def reconcile(self, live_claim_uids) -> int:
        """Drop records of claims absent from ``live_claim_uids`` — orphans
        of a crash between the sharing write and the checkpoint write, which
        would otherwise count into capacity sums and mode-conflict checks
        forever (the sharing-side analog of destroy_unknown_partitions).
        Returns how many records were dropped."""
        live = set(live_claim_uids)
        with self._mu:
            doomed = [k for k in self._state if self._key_uid(k) not in live]
            for k in doomed:
                del self._state[k]
            if doomed:
                self._save()
            return len(doomed)

    def clear_claim(self, claim_uid: str) -> None:
        with self._mu:
            doomed = [k for k in self._state if k.startswith(f"{claim_uid}:")]
            for k in doomed:
                del self._state[k]
            if doomed:
                self._save()

    def records_for(self, chips: Sequence[int]) -> list:
        with self._mu:
            return [r for r in self._state.values() if r["chip"] in set(chips)]

    def env_for(self, chips: Sequence[int]) -> Dict[str, str]:
        """Runtime env for a device's chips (empty when unshared)."""
        recs = self.records_for(chips)
        env: Dict[str, str] = {}
        ts = [r for r in recs if r["mode"] == "timeslice" and r["interval"] != "Default"]
        if ts:
            env["TPU_TIMESLICE_US"] = str(
                max(TIME_SLICE_US[r["interval"]] for r in ts)
            )
        pm = [r for r in recs if r["mode"] == "premapped"]
        if pm:
            budget = min(r["bytes"] for r in pm)
            # Driver bookkeeping: the exact enforced budget (what Prepare
            # validated against HBM capacity).
            env["TPU_PREMAPPED_BUFFER_BYTES"] = str(budget)
            # The ACTUAL libtpu knob: TPU_PREMAPPED_BUFFER_SIZE sizes the
            # runtime's premapped host transfer buffer and must be a power
            # of two — round the budget down so the handed-off value is
            # one the runtime accepts. Whether the runtime honors it is
            # environment-dependent (remote/tunneled backends ignore
            # client env); ops/premapped_ab.py measures exactly that, and
            # docs/guides/sharing.md records the honest answer.
            env["TPU_PREMAPPED_BUFFER_SIZE"] = str(_pow2_floor(budget))
        return env
