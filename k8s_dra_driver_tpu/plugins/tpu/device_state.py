"""DeviceState: the checkpointed Prepare/Unprepare state machine.

Port of the *semantics* (not the code) of
/root/reference/cmd/gpu-kubelet-plugin/device_state.go — the crash
consistency corners are the hard part (SURVEY.md §7):

- idempotent Prepare: a PrepareCompleted claim returns its cached devices
  (device_state.go:309-316);
- overlap guard: preparing a claim whose devices are already held by a
  different claim fails before any mutation (1482-1520);
- stale PrepareStarted entries (plugin died mid-prepare) are rolled back
  before re-preparing (332-337, 612-700);
- PrepareStarted is checkpointed *before* touching devices, PrepareCompleted
  *after* the CDI spec exists (340-392);
- partial failures roll back device-by-device, then the claim entry is
  dropped (612-700);
- unprepare is idempotent and removes the CDI spec before the entry.

The pipeline is batch-amortized: ``prepare_batch``/``unprepare_batch`` run
every claim of one NodePrepareResources call through a single checkpoint
session — one cp flock acquire, one load, exactly two fsync'd writes (all
PrepareStarted entries in one, all PrepareCompleted in the other) — with
the per-claim CDI specs materialized concurrently between the two writes.
A crash anywhere in the batch still leaves per-claim PrepareStarted
tombstones on disk, so the existing stale-entry rollback recovers each
claim independently on restart: crash-consistency semantics are unchanged,
only the write amplification moved from O(claims) to O(1) per batch.

Config resolution follows GetOpaqueDeviceConfigs precedence
(1399-1463): class-sourced configs apply before claim-sourced, and
all-requests configs before request-specific ones, so the most specific
config wins by applying last.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field, replace
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from k8s_dra_driver_tpu.api.configs import (
    DeviceConfig,
    SharingConfig,
    SubsliceConfig,
    TpuConfig,
    VfioTpuConfig,
    nonstrict_decode,
    TPU_DRIVER_NAME,
)
from k8s_dra_driver_tpu.cdi import CDIHandler, ContainerEdits
from k8s_dra_driver_tpu.k8s.core import ResourceClaim
from k8s_dra_driver_tpu.pkg import featuregates as fg
from k8s_dra_driver_tpu.pkg import tracing
from k8s_dra_driver_tpu.pkg.bootid import read_boot_id
from k8s_dra_driver_tpu.pkg.flock import Flock
from k8s_dra_driver_tpu.plugins.checkpoint import (
    Checkpoint,
    CheckpointStore,
    FAULT_PRE_COMPLETED,
    FAULT_STARTED_PERSISTED,
    MIGRATION_CHECKPOINTED,
    PREPARE_COMPLETED,
    PREPARE_STARTED,
    PreparedClaim,
    PreparedDevice,
)
from k8s_dra_driver_tpu.plugins.tpu.allocatable import (
    AllocatableDevice,
    SubsliceDevice,
    TpuDevice,
    VfioDevice,
    enumerate_allocatable,
)
from k8s_dra_driver_tpu.pkg.partitioner import (
    NativePartitionClient,
    PartitionError,
    PartitionManager,
    StubPartitionClient,
    load_tpupart,
)
from k8s_dra_driver_tpu.plugins.tpu.sharing import SharingManager
from k8s_dra_driver_tpu.plugins.tpu.vfio import VfioPciManager
from k8s_dra_driver_tpu.tpulib.lib import TpuLib
from k8s_dra_driver_tpu.tpulib.types import ChipHealth, HostInventory, parse_topology

log = logging.getLogger(__name__)

# Bound on concurrent CDI spec writes in one batch (each is a small fsync'd
# YAML file; past ~8 writers the disk queue, not Python, is the limit).
CDI_MATERIALIZE_WORKERS = 8


class PrepareError(Exception):
    pass


class OverlapError(PrepareError):
    pass


class MigrationError(PrepareError):
    """migrate_out refused: the claim is not in a migratable state."""


# Crash-injection point fired by migrate_out after the MigrationCheckpoint
# state is persisted but before any device is released — the window the
# handshake exists to make safe.
FAULT_MIGRATION_CHECKPOINTED = "migrate:checkpointed"


# tpu_dra_device_health gauge encoding (per node, per chip/link id).
HEALTH_GAUGE_VALUES = {
    ChipHealth.HEALTHY: 0.0,
    ChipHealth.DEGRADED: 1.0,
    ChipHealth.UNHEALTHY: 2.0,
}

# Telemetry-derived link degradation: a link whose error-counter RATE
# (errors/s, window mean) crosses the threshold is reported DEGRADED into
# the existing taint machinery; it heals (back to HEALTHY) only after the
# rate falls below the hysteresis floor, so a rate hovering at the
# threshold doesn't flap taints every sample.
LINK_DEGRADE_ERRORS_PER_S = 1.0
LINK_HEAL_ERRORS_PER_S = 0.5


def link_id(a: int, b: int) -> str:
    """Stable per-host id for the ICI link between two local chips."""
    return f"{min(a, b)}-{max(a, b)}"


@dataclass
class HealthDelta:
    """One observed transition plus the devices it touches — what the
    driver turns into taints and DeviceDegraded/DeviceRecovered events."""

    kind: str                    # "chip" | "link"
    id: str                      # chip index or "a-b" link id
    health: ChipHealth
    affected_devices: List[str] = field(default_factory=list)


class DeviceHealthMonitor:
    """Per-chip and per-ICI-link health ledger for one node.

    The reference's device_health.go only models whole-GPU XID events; a
    TPU mesh additionally loses individual ICI links while both endpoint
    chips stay alive — a 2x2 host with a dead 0-1 link can still serve
    single-chip claims but no subslice spanning that link. The monitor
    keeps both layers, answers "which chips are schedulable" for the
    ResourceSlice taint pass, and exports ``tpu_dra_device_health``
    (0=healthy, 1=degraded, 2=unhealthy) on the shared registry so a
    scraper sees the failed link, not just its downstream taints."""

    def __init__(self, node_name: str, allocatable: Dict[str, "AllocatableDevice"],
                 metrics_registry=None, tpulib=None,
                 hbm_by_chip: Optional[Dict[int, int]] = None,
                 link_gbps: float = 45.0,
                 window_samples: Optional[int] = None,
                 state_path: Optional[str] = None):
        from k8s_dra_driver_tpu.pkg.metrics import Counter, Gauge, Registry
        from k8s_dra_driver_tpu.pkg.telemetry import DEFAULT_WINDOW_SAMPLES

        self.node_name = node_name
        self._allocatable = allocatable
        self._chips: Dict[int, ChipHealth] = {}
        self._links: Dict[Tuple[int, int], ChipHealth] = {}
        registry = metrics_registry or Registry()
        self.gauge = registry.register(Gauge(
            "tpu_dra_device_health",
            "Device health by node and chip/ICI-link "
            "(0=healthy, 1=degraded, 2=unhealthy).",
            ("node", "kind", "id"),
        ))
        # -- telemetry sampling state ------------------------------------
        # All sampling state lives under its own mutex: sample() never
        # takes the DeviceState mutex, the pu flock, or the checkpoint
        # flock, so a slow prepare batch can never stall the sampler (nor
        # the reverse).
        self.tpulib = tpulib
        self._hbm_by_chip = dict(hbm_by_chip or {})
        self._link_gbps = link_gbps
        self._tel_mu = threading.Lock()
        self._window = window_samples or DEFAULT_WINDOW_SAMPLES
        self._state_path = state_path
        self.state_save_interval_s = 30.0
        self._last_state_save: Optional[float] = None
        self._duty_series: Dict[int, "RingSeries"] = {}  # tpulint: guarded-by=_tel_mu
        self._hbm_series: Dict[int, "RingSeries"] = {}  # tpulint: guarded-by=_tel_mu
        self._power_series: Dict[int, "RingSeries"] = {}  # tpulint: guarded-by=_tel_mu
        self._link_util_series: Optional["RingSeries"] = None  # tpulint: guarded-by=_tel_mu
        self._link_err_rate: Dict[Tuple[int, int], "RingSeries"] = {}  # tpulint: guarded-by=_tel_mu
        self._last_link_counters: Dict[Tuple[int, int], Tuple[int, int, int]] = {}  # tpulint: guarded-by=_tel_mu
        self._last_sample_t: Optional[float] = None  # tpulint: guarded-by=_tel_mu
        # Lock-free publish for the prepare path: sample() swaps in a
        # fresh immutable snapshot (atomic attribute store under the
        # GIL), so last_sample() never touches _tel_mu — a mid-sample
        # prepare batch must not wait out a 192-series ring update just
        # to stamp span attributes.
        self._last_snapshot: Dict[str, Dict[int, float]] = {"duty": {}, "hbm": {}}
        self._seeded_stats: Dict[str, Dict] = {}  # tpulint: guarded-by=_tel_mu
        self._telemetry_degraded: set = set()  # tpulint: guarded-by=_tel_mu
        self.samples_taken = 0
        self.chip_hbm_used = registry.register(Gauge(
            "tpu_dra_chip_hbm_used_bytes",
            "HBM bytes in use per chip (last sample).", ("node", "chip")))
        self.chip_duty = registry.register(Gauge(
            "tpu_dra_chip_duty_cycle",
            "Compute duty cycle per chip, 0-1 (last sample).",
            ("node", "chip")))
        self.chip_power = registry.register(Gauge(
            "tpu_dra_chip_power_watts",
            "Power draw per chip in watts (last sample).", ("node", "chip")))
        self.ici_tx = registry.register(Counter(
            "tpu_dra_ici_link_tx_total",
            "Cumulative ICI link transmit bytes.", ("node", "link")))
        self.ici_rx = registry.register(Counter(
            "tpu_dra_ici_link_rx_total",
            "Cumulative ICI link receive bytes.", ("node", "link")))
        self.ici_errors = registry.register(Counter(
            "tpu_dra_ici_link_errors_total",
            "Cumulative ICI link error count.", ("node", "link")))

    # -- transitions ---------------------------------------------------------

    def set_chip(self, index: int, health: ChipHealth) -> Optional[HealthDelta]:
        prev = self._chips.get(index, ChipHealth.HEALTHY)
        if prev == health:
            return None
        if health == ChipHealth.HEALTHY:
            self._chips.pop(index, None)
        else:
            self._chips[index] = health
        self.gauge.set(self.node_name, "chip", str(index),
                       value=HEALTH_GAUGE_VALUES[health])
        return HealthDelta(kind="chip", id=str(index), health=health,
                           affected_devices=self._devices_touching({index}))

    def set_link(self, a: int, b: int, health: ChipHealth) -> Optional[HealthDelta]:
        key = (min(a, b), max(a, b))
        prev = self._links.get(key, ChipHealth.HEALTHY)
        if prev == health:
            return None
        if health == ChipHealth.HEALTHY:
            self._links.pop(key, None)
        else:
            self._links[key] = health
        self.gauge.set(self.node_name, "link", link_id(a, b),
                       value=HEALTH_GAUGE_VALUES[health])
        # A bad link breaks only devices that SPAN it (multi-chip subslices
        # and whole-host groups); its endpoint chips alone still work.
        return HealthDelta(kind="link", id=link_id(a, b), health=health,
                           affected_devices=self._devices_spanning(key))

    # -- queries -------------------------------------------------------------

    def unhealthy_chips(self) -> set:
        """Chips that must not be scheduled at all (chip-level fault)."""
        return set(self._chips)

    def broken_links(self) -> Dict[Tuple[int, int], ChipHealth]:
        return dict(self._links)

    def tainted_devices(self) -> Dict[str, str]:
        """device name -> "chip"|"link": every allocatable device that an
        unhealthy chip or a broken link makes unschedulable."""
        out: Dict[str, str] = {}
        bad_chips = self.unhealthy_chips()
        for name, dev in self._allocatable.items():
            if bad_chips & set(dev.chip_indices):
                out[name] = "chip"
        for key in self._links:
            for name in self._devices_spanning(key):
                out.setdefault(name, "link")
        return out

    def _devices_touching(self, chips: set) -> List[str]:
        return sorted(
            name for name, dev in self._allocatable.items()
            if chips & set(dev.chip_indices)
        )

    def _devices_spanning(self, link: Tuple[int, int]) -> List[str]:
        a, b = link
        return sorted(
            name for name, dev in self._allocatable.items()
            if a in dev.chip_indices and b in dev.chip_indices
        )

    # -- telemetry sampling ---------------------------------------------------

    def sample(self, now: Optional[float] = None) -> List[HealthDelta]:
        """Take one telemetry sample: read tpulib counters, push the ring
        buffers, publish the per-chip gauges and link counters, and
        threshold link error rates into DEGRADED/HEALTHY transitions
        (returned as HealthDeltas for the driver's taint/event chain).

        Never blocks on prepare-path locks — only ``_tel_mu`` and the
        tpulib's own counter lock are taken. A tpulib without counters
        (or one returning []) is a no-op."""
        if self.tpulib is None or not hasattr(self.tpulib, "read_counters"):
            return []
        counters = self.tpulib.read_counters(now=now)
        if not counters:
            return []
        transitions: List[Tuple[Tuple[int, int], ChipHealth]] = []
        with self._tel_mu:
            from k8s_dra_driver_tpu.pkg.telemetry import RingSeries

            t = counters[0].timestamp
            dt = (t - self._last_sample_t
                  if self._last_sample_t is not None else 0.0)
            self._last_sample_t = t
            link_utils: List[float] = []
            for c in counters:
                for series_map, value in (
                    (self._duty_series, c.duty_cycle),
                    (self._hbm_series, float(c.hbm_used_bytes)),
                    (self._power_series, c.power_watts),
                ):
                    series_map.setdefault(
                        c.index, RingSeries(self._window)).push(t, value)
                self.chip_duty.set(self.node_name, str(c.index),
                                   value=c.duty_cycle)
                self.chip_hbm_used.set(self.node_name, str(c.index),
                                       value=float(c.hbm_used_bytes))
                self.chip_power.set(self.node_name, str(c.index),
                                    value=c.power_watts)
                if c.hbm_total_bytes:
                    self._hbm_by_chip.setdefault(c.index, c.hbm_total_bytes)
                for lc in c.links:
                    key = (min(lc.a, lc.b), max(lc.a, lc.b))
                    lid = link_id(lc.a, lc.b)
                    prev = self._last_link_counters.get(key)
                    self._last_link_counters[key] = (
                        lc.tx_bytes, lc.rx_bytes, lc.errors)
                    if prev is None or dt <= 0:
                        continue
                    d_tx = max(0, lc.tx_bytes - prev[0])
                    d_err = max(0, lc.errors - prev[2])
                    self.ici_tx.inc(self.node_name, lid, by=float(d_tx))
                    self.ici_rx.inc(self.node_name, lid,
                                    by=float(max(0, lc.rx_bytes - prev[1])))
                    self.ici_errors.inc(self.node_name, lid, by=float(d_err))
                    cap_bps = self._link_gbps * 1e9 / 8.0
                    link_utils.append(min(1.0, (d_tx / dt) / cap_bps)
                                      if cap_bps else 0.0)
                    err_series = self._link_err_rate.setdefault(
                        key, RingSeries(self._window))
                    err_series.push(t, d_err / dt)
                    rate = err_series.stats().mean
                    degraded = key in self._telemetry_degraded
                    if not degraded and rate > LINK_DEGRADE_ERRORS_PER_S:
                        self._telemetry_degraded.add(key)
                        transitions.append((key, ChipHealth.DEGRADED))
                    elif degraded and rate < LINK_HEAL_ERRORS_PER_S:
                        self._telemetry_degraded.discard(key)
                        transitions.append((key, ChipHealth.HEALTHY))
            if link_utils:
                if self._link_util_series is None:
                    self._link_util_series = RingSeries(self._window)
                self._link_util_series.push(
                    t, sum(link_utils) / len(link_utils))
            self.samples_taken += 1
        self._last_snapshot = {
            "duty": {c.index: c.duty_cycle for c in counters},
            "hbm": {c.index: float(c.hbm_used_bytes) for c in counters},
        }
        deltas = []
        for (a, b), health in transitions:
            # A link the fabric already reported broken stays whatever the
            # watcher said; telemetry only drives its own degradations.
            # BOTH directions skip: a DEGRADED write would downgrade the
            # UNHEALTHY ledger entry, after which the error rate falling
            # would clear a link the fabric still reports dead. The
            # rate bookkeeping is undone so the degradation re-applies
            # if the fabric later heals while the rate is still high.
            if self._links.get((a, b)) == ChipHealth.UNHEALTHY:
                if health == ChipHealth.DEGRADED:
                    with self._tel_mu:
                        self._telemetry_degraded.discard((a, b))
                continue
            delta = self.set_link(a, b, health)
            if delta is not None:
                deltas.append(delta)
        return deltas

    def window_stats(self) -> Dict[str, Dict[int, "WindowStats"]]:
        """Snapshot of per-chip window statistics by signal — the rollup
        aggregator's input. Falls back to restart-seeded stats until the
        first live sample, so gauges and rollups never report zero just
        because the plugin restarted mid-window."""
        from k8s_dra_driver_tpu.pkg.telemetry import WindowStats

        with self._tel_mu:
            if not self._duty_series and self._seeded_stats:
                return {
                    sig: {int(i): WindowStats.from_dict(d)
                          for i, d in per_chip.items()}
                    for sig, per_chip in self._seeded_stats.items()
                    if sig in ("duty", "hbm", "power")
                }
            return {
                "duty": {i: s.stats() for i, s in self._duty_series.items()},
                "hbm": {i: s.stats() for i, s in self._hbm_series.items()},
                "power": {i: s.stats() for i, s in self._power_series.items()},
            }

    def last_sample(self) -> Dict[str, Dict[int, float]]:
        """Last-sampled duty/HBM per chip — the prepare-path span
        attributes' read. LOCK-FREE: reads the immutable snapshot
        sample() swaps in, so a prepare batch never waits on a sample
        in flight (bench_telemetry's 5% storm gate is exactly this
        edge); falls back to the restart seed before the first live
        sample."""
        snap = self._last_snapshot
        if snap["duty"]:
            return snap
        seeded = self._seeded_stats
        if seeded:
            return {
                "duty": {int(i): float(d.get("last", 0.0))
                         for i, d in seeded.get("duty", {}).items()},
                "hbm": {int(i): float(d.get("last", 0.0))
                        for i, d in seeded.get("hbm", {}).items()},
            }
        return snap

    def link_utilization(self) -> "WindowStats":
        from k8s_dra_driver_tpu.pkg.telemetry import WindowStats

        with self._tel_mu:
            if self._link_util_series is not None:
                return self._link_util_series.stats()
            seeded = self._seeded_stats.get("link_util")
            if seeded:
                return WindowStats.from_dict(seeded)
            return WindowStats()

    def hbm_totals(self) -> Dict[int, int]:
        with self._tel_mu:
            return dict(self._hbm_by_chip)

    # -- restart re-seed ------------------------------------------------------

    def telemetry_state(self) -> Dict:
        """Window metadata worth surviving a restart: last per-chip window
        stats + link utilization. Ring contents are NOT persisted (they
        refill within one window); what matters is that gauges and
        rollups keep reporting last-known values instead of zero."""
        with self._tel_mu:
            doc: Dict = {"t": self._last_sample_t}
            for sig, series in (("duty", self._duty_series),
                                ("hbm", self._hbm_series),
                                ("power", self._power_series)):
                doc[sig] = {str(i): s.stats().as_dict()
                            for i, s in series.items()}
            if self._link_util_series is not None:
                doc["link_util"] = self._link_util_series.stats().as_dict()
            return doc

    def save_telemetry_state(self, force: bool = False) -> None:
        """Persist the restart seed — throttled: the seed only has to be
        fresh to within one save interval (a restart then re-publishes
        values at most that stale), so the sampling loop doesn't pay a
        JSON dump + rename every tick."""
        if not self._state_path:
            return
        now = time.monotonic()
        if not force and self._last_state_save is not None and \
                now - self._last_state_save < self.state_save_interval_s:
            return
        self._last_state_save = now
        import json

        doc = self.telemetry_state()
        tmp = self._state_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, self._state_path)

    def load_telemetry_state(self) -> bool:
        """Re-seed window metadata from the persisted file (plugin
        restart): republishes the per-chip gauges at their last-known
        values and keeps window_stats() serving the previous window until
        live samples replace it. Returns True when a seed was loaded."""
        if not self._state_path or not os.path.exists(self._state_path):
            return False
        import json

        try:
            with open(self._state_path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            log.warning("unreadable telemetry seed %s; starting cold",
                        self._state_path)
            return False
        with self._tel_mu:
            self._seeded_stats = {
                sig: doc.get(sig, {}) for sig in ("duty", "hbm", "power")
            }
            if doc.get("link_util"):
                self._seeded_stats["link_util"] = doc["link_util"]
        for chip, stats in (doc.get("duty") or {}).items():
            self.chip_duty.set(self.node_name, str(chip),
                               value=float(stats.get("last", 0.0)))
        for chip, stats in (doc.get("hbm") or {}).items():
            self.chip_hbm_used.set(self.node_name, str(chip),
                                   value=float(stats.get("last", 0.0)))
        for chip, stats in (doc.get("power") or {}).items():
            self.chip_power.set(self.node_name, str(chip),
                                value=float(stats.get("last", 0.0)))
        return True


@dataclass
class PrepareResult:
    claim_uid: str
    cdi_device_ids: List[str] = field(default_factory=list)
    devices: List[PreparedDevice] = field(default_factory=list)


class DeviceState:
    def __init__(
        self,
        tpulib: TpuLib,
        plugin_dir: str,
        cdi_root: Optional[str] = None,
        gates: Optional[fg.FeatureGates] = None,
        driver_name: str = TPU_DRIVER_NAME,
        vfio: Optional[VfioPciManager] = None,
    ):
        self.gates = gates or fg.FeatureGates()
        self.driver_name = driver_name
        self.tpulib = tpulib
        self.inventory: HostInventory = tpulib.enumerate()
        self.allocatable: Dict[str, AllocatableDevice] = enumerate_allocatable(
            self.inventory,
            with_subslices=True,
            with_vfio=self.gates.enabled("PassthroughSupport"),
        )
        self.cdi = CDIHandler(cdi_root)
        self.sharing = SharingManager(
            plugin_dir,
            hbm_by_chip={c.index: c.hbm_bytes for c in self.inventory.chips},
        )
        self.vfio = vfio or VfioPciManager()
        self.plugin_dir = plugin_dir
        os.makedirs(plugin_dir, exist_ok=True)
        # ICIPartitioning is the base partitioner gate (the FM
        # partitioning analog): DynamicSubslice carves subslice partitions
        # through it (nvlib.go:971-1199) and VFIO passthrough groups
        # activate their isolating partition through it before binding
        # (device_state.go:1284-1289). The native flock'd on-disk ledger
        # survives plugin restarts (like FM service state); the stub
        # covers mock runs.
        self.partitions: Optional[PartitionManager] = None
        if self.gates.enabled("ICIPartitioning"):
            host_topology = self.inventory.host_topology
            ledger = os.path.join(plugin_dir, "partitions.json")
            if load_tpupart() is not None:
                client = NativePartitionClient(host_topology, ledger)
            elif getattr(tpulib, "is_mock", False) or os.environ.get(
                "ALT_TPU_TOPOLOGY"
            ):
                # Mock seam (injected MockTpuLib or the env selector): the
                # in-memory stub stands in for the platform, like the
                # reference's FM stubClient.
                client = StubPartitionClient()
            elif not self.gates.enabled("CrashOnICIFabricErrors"):
                log.error(
                    "ICIPartitioning enabled but libtpupart.so is missing: "
                    "using the in-memory stub — partitions are NOT "
                    "programmed into hardware and do NOT survive restarts"
                )
                client = StubPartitionClient()
            else:
                # Refuse to degrade silently (CrashOnICIFabricErrors
                # posture, reference CrashOnNVLinkFabricErrors).
                raise PartitionError(
                    "ICIPartitioning requires libtpupart.so on real nodes "
                    "(build native/, or set CrashOnICIFabricErrors=false "
                    "to degrade to the in-memory stub)"
                )
            self.partitions = PartitionManager(host_topology, client)
        self._mutex = threading.Lock()
        # In-memory mirror of the PREPARE_COMPLETED claim -> chip-set map,
        # under its OWN lock so telemetry rollup reads it without touching
        # the checkpoint flock or the prepare mutex (sampling must never
        # wait on a prepare batch). Whole-entry replacement keeps every
        # snapshot internally consistent — a reader sees a claim's full
        # chip set or nothing, never a torn half (tpusan scenario
        # telemetry-sample-vs-prepare pins this).
        self._claims_mu = threading.Lock()
        self._claim_chips: Dict[str, Tuple[str, str, Tuple[int, ...]]] = {}  # tpulint: guarded-by=_claims_mu
        # Crash-injection seam for the batched pipeline (see FAULT_* above).
        self.fault_hook: Optional[Callable[[str], None]] = None
        # Observability seam: called with the stale PreparedClaim entry
        # whenever a PrepareStarted leftover (plugin died mid-prepare) is
        # rolled back — the driver turns it into a CheckpointRecovered event.
        self.recovery_hook: Optional[Callable[[PreparedClaim], None]] = None

        def on_discard(uid: str) -> None:
            # Pre-reboot claim: its CDI spec and sharing records are stale.
            self.cdi.delete_claim_spec_file(uid)
            self.sharing.clear_claim(uid)

        self._store = CheckpointStore(
            plugin_dir, Flock, read_boot_id(), on_discard=on_discard
        )
        # Startup reconcile: a crash inside _prepare_devices leaves sharing
        # records whose claim never reached PREPARE_COMPLETED; they would
        # poison capacity sums and mode-conflict checks forever. Under the
        # node-global pu flock no prepare is in flight in any process, so
        # every non-COMPLETED entry's records are provably orphans (a live
        # overlapping old plugin mid-prepare would hold the lock).
        with Flock(os.path.join(plugin_dir, "pu.lock")).hold(timeout=10):
            completed = {
                uid for uid, e in self._store.get().claims.items()
                if e.state == PREPARE_COMPLETED
            }
            dropped = self.sharing.reconcile(completed)
        if dropped:
            log.warning("dropped %d orphaned sharing record(s) at startup", dropped)
        # Seed the telemetry mirror (and the mock's workload registry) from
        # the surviving checkpoint: restart must not zero per-claim load.
        for uid, entry in self._store.get().claims.items():
            if entry.state == PREPARE_COMPLETED:
                chips = tuple(sorted(
                    {i for d in entry.devices for i in d.chip_indices}))
                self._note_claim_telemetry(
                    uid, entry.name, entry.namespace, chips)

    # -- telemetry join surface ----------------------------------------------

    def _note_claim_telemetry(self, uid: str, name: str, namespace: str,
                              chips: Tuple[int, ...]) -> None:
        with self._claims_mu:
            self._claim_chips[uid] = (name, namespace, tuple(sorted(chips)))
        if hasattr(self.tpulib, "register_workload"):
            self.tpulib.register_workload(uid, chips)

    def _drop_claim_telemetry(self, uid: str) -> None:
        with self._claims_mu:
            self._claim_chips.pop(uid, None)
        if hasattr(self.tpulib, "unregister_workload"):
            self.tpulib.unregister_workload(uid)

    def prepared_chipsets(self) -> Dict[str, Tuple[str, str, Tuple[int, ...]]]:
        """uid -> (name, namespace, chips) for every PREPARE_COMPLETED
        claim — the rollup aggregator's join key, served from the mirror
        (no checkpoint load, no flock)."""
        with self._claims_mu:
            return dict(self._claim_chips)

    def _get_checkpoint(self) -> Checkpoint:
        return self._store.get()

    def _save_checkpoint(self, cp: Checkpoint) -> None:
        self._store.save(cp)  # tpulint: disable=lock-order -- one locked atomic write; test-seeding helper, never paired with _get_checkpoint on a live path

    # -- public state machine ----------------------------------------------

    def _fire_fault(self, point: str) -> None:
        if self.fault_hook is not None:
            self.fault_hook(point)

    def prepare(self, claim: ResourceClaim) -> PrepareResult:
        """Prepare one claim; returns CDI device ids for the kubelet."""
        res = self.prepare_batch([claim])[claim.uid]
        if isinstance(res, Exception):
            raise res
        return res

    def prepare_batch(
        self, claims: Sequence[ResourceClaim]
    ) -> Dict[str, "PrepareResult | Exception"]:
        # tpulint: holds=pu-flock (the plugin driver takes it per batch)
        """Prepare a whole NodePrepareResources batch under one checkpoint
        session: two fsync'd writes total (all PrepareStarted, then all
        PrepareCompleted), CDI specs materialized concurrently in between.
        Per-claim failures come back as inline exceptions — one bad claim
        never fails its batch siblings."""
        out: Dict[str, "PrepareResult | Exception"] = {}
        if not claims:
            return out
        with self._mutex:
            t0 = time.perf_counter()
            with self._store.session() as sess:
                cp = sess.checkpoint
                dirty = False
                pending: List[ResourceClaim] = []
                batch_chips: Dict[str, set] = {}  # uid -> chips wanted by siblings
                for claim in claims:
                    uid = claim.uid
                    if uid in batch_chips or uid in out:
                        continue  # duplicate uid in one batch: first wins
                    entry = cp.claims.get(uid)
                    if entry is not None and entry.state == PREPARE_COMPLETED:
                        out[uid] = PrepareResult(
                            claim_uid=uid,
                            cdi_device_ids=[i for d in entry.devices
                                            for i in d.cdi_device_ids],
                            devices=list(entry.devices),
                        )
                        continue
                    try:
                        if entry is not None and entry.state == PREPARE_STARTED:
                            # Inside the per-claim try: a poisoned stale
                            # entry fails only ITS claim, never the batch.
                            log.warning(
                                "claim %s has a stale PrepareStarted entry; rolling back", uid)
                            self._rollback(entry)
                            del cp.claims[uid]
                            self._drop_claim_telemetry(uid)
                            dirty = True
                            if self.recovery_hook is not None:
                                self.recovery_hook(entry)
                        elif (entry is not None
                                and entry.state == MIGRATION_CHECKPOINTED):
                            # Re-prepare of a mid-migration claim: either the
                            # rebalancer rolling the claim back to its source
                            # placement, or a plugin restart recovering a
                            # crashed migration. migrate_out already released
                            # the devices; the extra rollback is idempotent
                            # belt-and-braces for a crash inside the release.
                            log.info("claim %s has a MigrationCheckpoint "
                                     "entry; clearing and re-preparing", uid)
                            self._rollback(entry)
                            del cp.claims[uid]
                            self._drop_claim_telemetry(uid)
                            dirty = True
                        requested = self._allocated_device_names(claim)
                        want = self._validate_no_overlap(cp, uid, requested)
                        # Batch siblings are not in cp yet: they conflict too.
                        for other_uid, held in batch_chips.items():
                            both = want & held
                            if both:
                                raise OverlapError(
                                    f"devices overlap with batch sibling claim "
                                    f"{other_uid} on chips {sorted(both)}"
                                )
                    except Exception as e:  # noqa: BLE001 — per-claim contract
                        out[uid] = e
                        continue
                    batch_chips[uid] = want
                    cp.claims[uid] = PreparedClaim(
                        claim_uid=uid,
                        namespace=claim.namespace,
                        name=claim.name,
                        state=PREPARE_STARTED,
                        started_at=time.time(),
                    )
                    pending.append(claim)
                    dirty = True
                if not pending:
                    if dirty:
                        sess.save()
                    return out
                # Write #1: every PrepareStarted entry (and any stale-entry
                # removal) lands in ONE fsync'd write.
                sess.save()
                self._fire_fault(FAULT_STARTED_PERSISTED)

                # Device mutations stay sequential — they touch shared
                # managers (partitions, sharing, vfio sysfs) whose invariants
                # are ordering-sensitive; the parallel win is the CDI I/O.
                prepared_by_uid: Dict[str, List[PreparedDevice]] = {}
                for claim in pending:
                    try:
                        # _prepare_devices rolls back its own partial work.
                        prepared_by_uid[claim.uid] = self._prepare_devices(claim)
                    except Exception as e:  # noqa: BLE001 — per-claim contract
                        del cp.claims[claim.uid]
                        out[claim.uid] = e
                survivors = [c for c in pending if c.uid in prepared_by_uid]

                # Materialize per-claim CDI specs concurrently between the
                # two checkpoint writes (each spec is an independent fsync'd
                # file; edits are computed from now-quiescent device state).
                # Pool threads have no thread-local span context, so the
                # batch context is captured here and attached explicitly —
                # every per-claim CDI write is a child of the batch span.
                batch_ctx = tracing.current()

                def materialize(claim: ResourceClaim) -> List[PreparedDevice]:
                    with tracing.span("cdi.materialize", parent=batch_ctx,
                                      claim_uid=claim.uid):
                        prepared = prepared_by_uid[claim.uid]
                        per_dev = {d.name: self._edits_for(d) for d in prepared}
                        ids = self.cdi.create_claim_spec_file(
                            claim.uid, per_dev,
                            common_edits=self._common_edits(prepared),
                        )
                        id_by_name = dict(zip(sorted(per_dev), ids))
                        for d in prepared:
                            d.cdi_device_ids = [id_by_name[d.name]]
                        return prepared

                results: Dict[str, "List[PreparedDevice] | Exception"] = {}
                if len(survivors) == 1:
                    c = survivors[0]
                    try:
                        results[c.uid] = materialize(c)
                    except Exception as e:  # noqa: BLE001 — per-claim contract
                        results[c.uid] = e
                elif survivors:
                    workers = min(CDI_MATERIALIZE_WORKERS, len(survivors))
                    with ThreadPoolExecutor(
                        max_workers=workers, thread_name_prefix="cdi-spec"
                    ) as pool:
                        futs = {c.uid: pool.submit(materialize, c)
                                for c in survivors}
                        for uid, fut in futs.items():
                            try:
                                results[uid] = fut.result()
                            except Exception as e:  # noqa: BLE001
                                results[uid] = e

                for claim in survivors:
                    uid = claim.uid
                    got = results[uid]
                    if isinstance(got, Exception):
                        # Device work succeeded but the CDI write failed.
                        self._rollback_devices(uid, prepared_by_uid[uid])
                        self.cdi.delete_claim_spec_file(uid)
                        del cp.claims[uid]
                        out[uid] = got
                        continue
                    entry = cp.claims[uid]
                    entry.devices = got
                    entry.state = PREPARE_COMPLETED
                    entry.completed_at = time.time()
                    self._note_claim_telemetry(
                        uid, claim.name, claim.namespace,
                        tuple(sorted({i for d in got for i in d.chip_indices})))
                    out[uid] = PrepareResult(
                        claim_uid=uid,
                        cdi_device_ids=[i for d in got for i in d.cdi_device_ids],
                        devices=list(got),
                    )
                self._fire_fault(FAULT_PRE_COMPLETED)
                # Write #2: every PrepareCompleted transition in ONE write.
                sess.save()
            log.debug("t_prep_batch=%0.4fs n=%d", time.perf_counter() - t0,
                      len(claims))
        return out

    def unprepare(self, claim_uid: str) -> None:
        errs = self.unprepare_batch([claim_uid])
        err = errs.get(claim_uid)
        if err is not None:
            raise err

    def unprepare_batch(
        self, claim_uids: Sequence[str]
    ) -> Dict[str, Optional[Exception]]:
        # tpulint: holds=pu-flock (the plugin driver takes it per batch)
        """Unprepare a batch under one checkpoint session: one flock, one
        load, at most one fsync'd write for the whole batch."""
        out: Dict[str, Optional[Exception]] = {}
        if not claim_uids:
            return out
        with self._mutex:
            with self._store.session() as sess:
                cp = sess.checkpoint
                dirty = False
                for uid in claim_uids:
                    try:
                        entry = cp.claims.get(uid)
                        if entry is None:
                            self.cdi.delete_claim_spec_file(uid)  # belt and braces
                            out[uid] = None
                            continue
                        self._rollback(entry)
                        self.cdi.delete_claim_spec_file(uid)
                        del cp.claims[uid]
                        self._drop_claim_telemetry(uid)
                        dirty = True
                        out[uid] = None
                    except Exception as e:  # noqa: BLE001 — per-claim contract
                        out[uid] = e
                if dirty:
                    sess.save()
        return out

    def prepared_claims(self) -> Dict[str, PreparedClaim]:
        return dict(self._get_checkpoint().claims)

    # -- live-repack migration handshake -------------------------------------

    def migrate_out(self, claim_uid: str) -> PreparedClaim:
        # tpulint: holds=pu-flock (the plugin driver takes it per migration)
        """Checkpoint-aware unprepare for live claim migration: persist the
        ``MigrationCheckpoint`` state FIRST (one fsync'd write), then release
        the claim's devices (partitions, sharing records, vfio binds, CDI
        spec) while the entry — devices list included — survives on disk as
        the source-placement record.

        The ordering is the whole point: a crash anywhere after the write
        leaves a MigrationCheckpoint entry whose partitions are freed by
        ``destroy_unknown_partitions`` at restart (the entry is not
        PrepareCompleted, so nothing claims them) and whose next Prepare —
        the rollback-to-source path — clears the entry and prepares fresh.
        Leaked ICI partitions are impossible by construction. Returns a
        snapshot of the migration entry."""
        with self._mutex:
            with self._store.session() as sess:
                cp = sess.checkpoint
                entry = cp.claims.get(claim_uid)
                if entry is None:
                    raise MigrationError(
                        f"claim {claim_uid} has no checkpoint entry on this "
                        f"node; nothing to migrate")
                if entry.state != PREPARE_COMPLETED:
                    raise MigrationError(
                        f"claim {claim_uid} is {entry.state}, not "
                        f"{PREPARE_COMPLETED}; refusing to migrate")
                entry.state = MIGRATION_CHECKPOINTED
                entry.migration_started_at = time.time()
                sess.save()
                self._fire_fault(FAULT_MIGRATION_CHECKPOINTED)
                self._rollback(entry)
                self.cdi.delete_claim_spec_file(claim_uid)
                self._drop_claim_telemetry(claim_uid)
                return replace(entry, devices=list(entry.devices))

    def end_migration(self, claim_uid: str) -> None:
        # tpulint: holds=pu-flock (the plugin driver takes it per migration)
        """Complete a migration: drop the MigrationCheckpoint entry (the
        claim now lives on its target node). Idempotent; a no-op for claims
        in any other state — a re-prepare on this node (rollback-to-source)
        already cleared the entry through the prepare path."""
        with self._mutex:
            with self._store.session() as sess:
                cp = sess.checkpoint
                entry = cp.claims.get(claim_uid)
                if entry is not None and entry.state == MIGRATION_CHECKPOINTED:
                    del cp.claims[claim_uid]
                    sess.save()

    def migration_entries(self) -> Dict[str, PreparedClaim]:
        """Claims currently mid-migration off this node."""
        return {uid: e for uid, e in self._get_checkpoint().claims.items()
                if e.state == MIGRATION_CHECKPOINTED}

    # -- internals ----------------------------------------------------------

    def _allocated_device_names(self, claim: ResourceClaim) -> List[str]:
        if claim.allocation is None:
            raise PrepareError(f"claim {claim.key} has no allocation")
        names = [
            r.device for r in claim.allocation.devices if r.driver == self.driver_name
        ]
        if not names:
            raise PrepareError(
                f"claim {claim.key} allocation has no devices for driver {self.driver_name}"
            )
        for n in names:
            if n not in self.allocatable:
                raise PrepareError(f"claim {claim.key}: unknown device {n!r}")
        return names

    def _validate_no_overlap(
        self, cp: Checkpoint, uid: str, requested: Sequence[str]
    ) -> set:
        """No chip may be held by two claims (device_state.go:1482-1520).
        Overlap is computed on chip indices, so a subslice conflicts with
        its member chips even though the device names differ. Returns the
        claim's requested chip set (the batch pipeline reuses it for
        sibling-overlap checks — one derivation rule, not two)."""
        want: set = set()
        for name in requested:
            want |= set(self.allocatable[name].chip_indices)
        for other_uid, entry in cp.claims.items():
            if other_uid == uid:
                continue
            held = {i for d in entry.devices for i in d.chip_indices}
            both = want & held
            if both:
                raise OverlapError(
                    f"devices overlap with claim {other_uid} on chips {sorted(both)}"
                )
        return want

    def _prepare_devices(self, claim: ResourceClaim) -> List[PreparedDevice]:
        configs = self._resolve_configs(claim)
        results = [
            r for r in claim.allocation.devices  # type: ignore[union-attr]
            if r.driver == self.driver_name
        ]
        # Resolve the passthrough group BEFORE any sysfs mutation: config
        # resolution (iommu backend) and fabric isolation both have to
        # precede the vfio-pci bind — the reference activates the FM
        # partition for the whole group first and only then configures
        # each function (device_state.go:1254-1297).
        vfio_group = self._resolve_vfio_group(claim, results, configs)
        group_pid = ""
        if vfio_group is not None:
            group_pid = self._activate_vfio_partition(
                [self.allocatable[r.device] for r in vfio_group["results"]]
            )
        prepared: List[PreparedDevice] = []
        try:
            for result in results:
                dev = self.allocatable[result.device]
                extra: Dict[str, str] = {}
                if isinstance(dev, VfioDevice):
                    extra["iommu"] = vfio_group["iommu_mode"]  # type: ignore[index]
                    if vfio_group["api_device"]:  # type: ignore[index]
                        extra["api_device"] = "1"
                    if group_pid:
                        extra["partition"] = group_pid
                    try:
                        dev = self._ensure_vfio_bound(dev, vfio_group["iommu_mode"])  # type: ignore[index]
                    except Exception:
                        # A failed bind can strand the function driverless
                        # (unbound from accel, vfio probe failed); re-probe
                        # it back to the default driver before surfacing.
                        # The group partition is released by the outer
                        # rollback via the prepared entries (or below when
                        # nothing was prepared yet).
                        self._release_vfio(dev)
                        if (group_pid and self.partitions is not None
                                and not any(p.extra.get("partition") == group_pid
                                            for p in prepared)):
                            # No prepared entry carries the group partition
                            # yet, so the outer rollback won't release it.
                            self.partitions.deactivate(group_pid)
                        raise
                try:
                    if (isinstance(dev, SubsliceDevice)
                            and self.partitions is not None
                            and self.gates.enabled("DynamicSubslice")):
                        extra["partition"] = self._activate_partition(dev)
                    for cfg in configs.get(result.request, []):
                        self._apply_config(cfg, claim.uid, dev)
                except Exception:
                    # The in-flight device is not in `prepared` yet; undo its
                    # own partition/sharing/vfio before the outer rollback.
                    self.sharing.clear(claim.uid, tuple(dev.chip_indices))
                    if isinstance(dev, VfioDevice):
                        self._release_vfio(dev)
                    pid = extra.get("partition")
                    if pid and self.partitions is not None:
                        # The shared group partition is released by the
                        # outer rollback once a prepared sibling carries it
                        # (after that sibling's unbind — never while a group
                        # member is still bound to vfio-pci).
                        carried = pid == group_pid and any(
                            p.extra.get("partition") == pid for p in prepared
                        )
                        if not carried:
                            self.partitions.deactivate(pid)
                    raise
                prepared.append(
                    PreparedDevice(
                        name=dev.name,
                        device_type=dev.device_type,
                        chip_indices=list(dev.chip_indices),
                        request=result.request,
                        extra=extra,
                    )
                )
        except Exception:
            self._rollback_devices(claim.uid, prepared)
            raise
        return prepared

    def _resolve_vfio_group(self, claim: ResourceClaim, results,
                            configs) -> Optional[Dict]:
        """Resolve the claim's passthrough group and its effective IOMMU
        backend up front. Like the reference, a single VfioTpuConfig
        governs the whole group (device_state.go:1254-1263 assumes one
        vfio config per claim); conflicting configs are a PrepareError."""
        vfio_results = [
            r for r in results
            if isinstance(self.allocatable[r.device], VfioDevice)
        ]
        if not vfio_results:
            return None
        # Per request, the effective config is the LAST VfioTpuConfig in
        # apply order (most specific wins — a claim config overrides a
        # class default, exactly the GetOpaqueDeviceConfigs precedence).
        # The group stays consistent: different requests resolving to
        # different effective configs is the conflict.
        effective: Dict[str, VfioTpuConfig] = {}
        for r in vfio_results:
            for cfg in configs.get(r.request, []):
                if isinstance(cfg, VfioTpuConfig):
                    effective[r.request] = cfg
        distinct = {id(c): c for c in effective.values()}
        unique = list(distinct.values())
        if len(unique) > 1 and any(c != unique[0] for c in unique[1:]):
            raise PrepareError(
                "conflicting VfioTpuConfigs in one claim "
                "(one config governs the whole passthrough group)"
            )
        cfg = unique[0] if unique else VfioTpuConfig()
        if not self.gates.enabled("PassthroughSupport"):
            raise PrepareError("VFIO passthrough requires PassthroughSupport gate")
        return {
            "results": vfio_results,
            "iommu_mode": self._resolve_iommu_mode(cfg),
            "api_device": cfg.enable_api_device,
        }

    def _resolve_iommu_mode(self, cfg: VfioTpuConfig) -> str:
        """auto/legacy/iommufd -> the backend actually used. ``iommufd``
        hard-requires /dev/iommu; ``auto`` prefers it when present (the
        PreferIommuFD posture, vfio-cdi.go:52-66)."""
        if cfg.iommu_mode == "legacy":
            return "legacy"
        available = self.vfio.iommufd_available()
        if cfg.iommu_mode == "iommufd":
            if not available:
                raise PrepareError(
                    "iommu_mode=iommufd but the node has no /dev/iommu "
                    "(iommufd backend unavailable)"
                )
            return "iommufd"
        return "iommufd" if available else "legacy"

    def _activate_vfio_partition(self, devs: Sequence[AllocatableDevice]) -> str:
        """Isolate the passthrough group on the ICI mesh BEFORE binding to
        vfio-pci (reference device_state.go:1284-1289: fabric partition
        activation precedes Configure). Whole-host passthrough needs no
        carving — nothing else shares the mesh; a strict-subset group that
        matches no legal partition refuses activation like the reference's
        'does not match any FM partition' error."""
        if self.partitions is None or not self.gates.enabled("ICIPartitioning"):
            return ""
        chips = tuple(sorted({i for d in devs for i in d.chip_indices}))
        if len(chips) == len(self.inventory.chips):
            return ""
        partition = self.partitions.partition_for_chips(chips)
        if partition is None:
            raise PrepareError(
                f"passthrough group (chips {list(chips)}) matches no legal "
                f"ICI partition on {self.inventory.host_topology}; refusing "
                f"activation"
            )
        try:
            self.partitions.activate(partition.id)
        except PartitionError as e:
            raise PrepareError(
                f"vfio partition activate {partition.id}: {e}") from e
        return partition.id

    def _resolve_configs(self, claim: ResourceClaim) -> Dict[str, List[DeviceConfig]]:
        """request name -> configs in apply order (most specific last)."""
        buckets: List[Tuple[int, List[str], DeviceConfig]] = []
        for cc in claim.config:
            if cc.opaque is None or cc.opaque.driver != self.driver_name:
                continue
            cfg = nonstrict_decode(cc.opaque.parameters)
            cfg.validate()
            source_rank = 0 if cc.source == "class" else 1
            specific_rank = 0 if not cc.requests else 1
            buckets.append((source_rank * 2 + specific_rank, cc.requests, cfg))
        buckets.sort(key=lambda b: b[0])
        out: Dict[str, List[DeviceConfig]] = {}
        request_names = {r.request for r in (claim.allocation.devices if claim.allocation else [])}
        for _, requests, cfg in buckets:
            targets = requests or sorted(request_names)
            for r in targets:
                out.setdefault(r, []).append(cfg)
        return out

    def _apply_config(self, cfg: DeviceConfig, claim_uid: str, dev: AllocatableDevice) -> None:
        if isinstance(cfg, TpuConfig):
            if cfg.sharing is not None:
                self._apply_sharing(cfg.sharing, claim_uid, dev)
        elif isinstance(cfg, SubsliceConfig):
            if not isinstance(dev, SubsliceDevice):
                raise PrepareError(
                    f"SubsliceConfig targets non-subslice device {dev.name}"
                )
            if cfg.profile and cfg.profile != dev.placement.profile:
                raise PrepareError(
                    f"config profile {cfg.profile} != allocated {dev.placement.profile}"
                )
            if cfg.sharing is not None:
                self._apply_sharing(cfg.sharing, claim_uid, dev)
        elif isinstance(cfg, VfioTpuConfig):
            if not self.gates.enabled("PassthroughSupport"):
                raise PrepareError("VfioTpuConfig requires PassthroughSupport gate")
            if not isinstance(dev, VfioDevice):
                raise PrepareError(f"VfioTpuConfig targets non-vfio device {dev.name}")
        else:
            raise PrepareError(f"config kind {cfg.kind} not valid for driver {self.driver_name}")

    def _apply_sharing(self, sharing: SharingConfig, claim_uid: str, dev: AllocatableDevice) -> None:
        if sharing.strategy == "TimeSlicing":
            if not self.gates.enabled("TimeSlicingSettings") and (
                sharing.time_slicing and sharing.time_slicing.interval != "Default"
            ):
                raise PrepareError("TimeSlicingSettings feature gate is disabled")
            self.sharing.set_time_slice(
                claim_uid, dev.chip_indices,
                sharing.time_slicing.interval if sharing.time_slicing else "Default",
            )
        else:
            if not self.gates.enabled("PremappedBufferSharing"):
                raise PrepareError("PremappedBufferSharing feature gate is disabled")
            assert sharing.premapped is not None
            self.sharing.set_premapped(
                claim_uid, dev.chip_indices, sharing.premapped
            )

    def _ensure_vfio_bound(self, dev: VfioDevice, iommu_mode: str = "legacy") -> VfioDevice:
        """Rebind the chip's PCI function to vfio-pci at Prepare time
        (reference device_state.go:1254-1297, vfio-device.go:235-257). A
        device whose group path is already known (inventory pre-bound, or a
        prior prepare) is left alone — unless the iommufd backend needs a
        cdev the cached state lacks."""
        if dev.vfio_group_path and (iommu_mode != "iommufd" or dev.vfio_cdev_path):
            return dev
        group_path = dev.vfio_group_path or self.vfio.bind_to_vfio(
            dev.chip.pci_address, dev_path=dev.chip.dev_path
        )
        cdev_path = ""
        if iommu_mode == "iommufd":
            cdev_path = self.vfio.iommufd_cdev(dev.chip.pci_address)
            if not cdev_path:
                # Bound, but the kernel exposes no per-device cdev: the
                # iommufd backend can't serve this function
                # (vfio-cdi.go:100-106 'missing iommufd cdev').
                raise PrepareError(
                    f"{dev.chip.pci_address}: bound to vfio-pci but no "
                    f"iommufd cdev under vfio-dev/ (kernel lacks "
                    f"VFIO_DEVICE_CDEV?)"
                )
        dev = replace(dev, vfio_group_path=group_path, vfio_cdev_path=cdev_path)
        self.allocatable[dev.name] = dev
        return dev

    def _activate_partition(self, dev: SubsliceDevice) -> str:
        """Carve the subslice's ICI partition (the createMigDevice leg of the
        MIG transaction, nvlib.go:971-1199). Idempotent via the manager; an
        overlap with a live partition is a PrepareError like any other
        device conflict."""
        assert self.partitions is not None
        partition = self.partitions.partition_for_chips(tuple(dev.chip_indices))
        if partition is None:
            raise PrepareError(
                f"no legal ICI partition for subslice {dev.name} "
                f"(chips {dev.chip_indices}) on {self.inventory.host_topology}"
            )
        try:
            self.partitions.activate(partition.id)
        except PartitionError as e:
            raise PrepareError(f"partition activate {partition.id}: {e}") from e
        return partition.id

    def destroy_unknown_partitions(self) -> int:
        """Startup reconcile (the DestroyUnknownMIGDevices analog,
        driver.go:110 + nvlib.go:429-464): deactivate ledger partitions no
        PrepareCompleted claim holds — leftovers of a crash between
        activation and the checkpoint write. Returns how many were freed.
        Caller must hold the node-global pu flock: an overlapping old
        plugin process mid-prepare has activated its partition but not yet
        checkpointed it, and without the lock this would free it."""
        if self.partitions is None:
            return 0
        with self._mutex:
            held = {
                d.extra.get("partition")
                for entry in self._get_checkpoint().claims.values()
                if entry.state == PREPARE_COMPLETED
                for d in entry.devices
            }
            freed = 0
            for p in self.partitions.active_partitions():
                if p.id not in held:
                    log.warning("freeing unknown ICI partition %s", p.id)
                    self.partitions.deactivate(p.id)
                    freed += 1
            return freed

    def _release_vfio(self, dev: VfioDevice) -> None:
        """Return the function to the accel driver (vfio-device.go unbind
        path) and clear the cached group/cdev paths so a later prepare
        re-binds — after the unbind the old /dev/vfio nodes are gone even
        for chips the inventory reported pre-bound."""
        try:
            self.vfio.unbind_from_vfio(dev.chip.pci_address)
        except Exception:  # noqa: BLE001 — best effort
            log.exception("vfio unbind rollback failed")
        self.allocatable[dev.name] = replace(
            dev, vfio_group_path="", vfio_cdev_path="")

    def _rollback_device(self, claim_uid: str, d: PreparedDevice,
                         release_partition: bool = True) -> None:
        """Reverse of prepare order: sharing records, then the vfio unbind,
        then the partition release (the group's ICI partition was activated
        BEFORE the bind, so it is released after the unbind — mirroring the
        reference's deactivateFabricPartition on unprepare). Claim-level
        rollback passes release_partition=False and releases partitions
        AFTER every device unbound: a multi-chip passthrough group's
        shared partition must never drop while a sibling is still bound."""
        try:
            self.sharing.clear(claim_uid, tuple(d.chip_indices))
            dev = self.allocatable.get(d.name)
            if isinstance(dev, VfioDevice):
                self._release_vfio(dev)
            pid = d.extra.get("partition")
            if release_partition and pid and self.partitions is not None:
                self.partitions.deactivate(pid)
        except Exception:  # noqa: BLE001 — rollback is best effort
            log.exception("rollback of %s for claim %s failed", d.name, claim_uid)

    def _rollback_devices(self, claim_uid: str,
                          devices: Sequence[PreparedDevice]) -> None:
        """Roll back a set of prepared devices: every unbind first, then
        each distinct partition exactly once."""
        for d in devices:
            self._rollback_device(claim_uid, d, release_partition=False)
        if self.partitions is not None:
            pids = dict.fromkeys(
                d.extra.get("partition") for d in devices
                if d.extra.get("partition"))
            for pid in pids:
                try:
                    self.partitions.deactivate(pid)
                except Exception:  # noqa: BLE001 — rollback is best effort
                    log.exception("partition release %s for claim %s failed",
                                  pid, claim_uid)

    def _rollback(self, entry: PreparedClaim) -> None:
        self._rollback_devices(entry.claim_uid, entry.devices)
        self.sharing.clear_claim(entry.claim_uid)

    # -- CDI edits ----------------------------------------------------------

    def _edits_for(self, d: PreparedDevice) -> ContainerEdits:
        dev = self.allocatable[d.name]
        edits = ContainerEdits()
        if isinstance(dev, VfioDevice):
            # Backend-selected node (vfio-cdi.go:89-118): the iommufd
            # per-device cdev when that backend is active, the legacy
            # group fd otherwise.
            if d.extra.get("iommu") == "iommufd" and dev.vfio_cdev_path:
                edits.device_nodes.append(dev.vfio_cdev_path)
            elif dev.vfio_group_path:
                edits.device_nodes.append(dev.vfio_group_path)
            edits.env["TPU_VFIO_PCI_ADDRESS"] = dev.chip.pci_address
            edits.env["TPU_VFIO_IOMMU_MODE"] = d.extra.get("iommu", "legacy")
            return edits
        chips = (
            (dev.chip,) if isinstance(dev, TpuDevice) else dev.chips  # type: ignore[union-attr]
        )
        for chip in chips:
            edits.device_nodes.append(chip.dev_path)
        indices = ",".join(str(c.index) for c in chips)
        edits.env["TPU_VISIBLE_CHIPS"] = indices
        edits.env["TPU_VISIBLE_DEVICES"] = indices
        if isinstance(dev, SubsliceDevice):
            shape = parse_topology(dev.placement.profile)
            shape3 = tuple(shape) + (1,) * (3 - len(shape))
            edits.env["TPU_CHIPS_PER_PROCESS_BOUNDS"] = ",".join(map(str, shape3))
            edits.env["TPU_PROCESS_BOUNDS"] = "1,1,1"
        sharing_env = self.sharing.env_for(dev.chip_indices)
        edits.env.update(sharing_env)
        return edits

    def _common_edits(self, prepared: List[PreparedDevice]) -> ContainerEdits:
        inv = self.inventory
        edits = ContainerEdits()
        edits.env["TPU_ACCELERATOR_TYPE"] = inv.accelerator_type
        edits.env["TPU_SKIP_MDS_QUERY"] = "true"
        # The IOMMU API device, once per claim when the vfio config asked
        # for it: /dev/iommu (iommufd backend) or the legacy /dev/vfio/vfio
        # container (vfio-cdi.go:52-81 GetCommonEdits).
        api_vfio = [d for d in prepared if d.extra.get("api_device")]
        if api_vfio:
            edits.device_nodes.append(
                self.vfio.api_device_path(api_vfio[0].extra.get("iommu", "legacy"))
            )
        # Env merge across devices is last-wins, so a multi-function group
        # also gets the full address list claim-wide (the per-device
        # TPU_VFIO_PCI_ADDRESS alone can only name one function).
        vfio_devs = [
            self.allocatable[d.name] for d in prepared
            if isinstance(self.allocatable.get(d.name), VfioDevice)
        ]
        if vfio_devs:
            edits.env["TPU_VFIO_PCI_ADDRESSES"] = ",".join(
                d.chip.pci_address
                for d in sorted(vfio_devs, key=lambda v: v.chip.index)
            )
        all_chips = sorted({i for d in prepared for i in d.chip_indices})
        whole_host = len(all_chips) == len(inv.chips)
        if whole_host:
            # Whole-host claim: expose the real slice identity so multi-host
            # JAX initializes over ICI (single-host slices get worker 0/1-host).
            edits.env["TPU_TOPOLOGY"] = inv.slice_topology
            edits.env["TPU_WORKER_ID"] = str(inv.worker_id)
            edits.env["TPU_HOST_BOUNDS"] = inv.host_topology
        else:
            # Partial host: the workload sees only its chips.
            edits.env["TPU_TOPOLOGY"] = ""
            edits.env["TPU_WORKER_ID"] = "0"
        return edits
