"""VFIO passthrough manager: driver rebinding for untrusted workloads.

Reference: /root/reference/cmd/gpu-kubelet-plugin/vfio-device.go — sysfs
unbind from the accel driver / bind to vfio-pci (235-257), IOMMU(fd)
detection (319-352), wait-until-free (85-116). Roots are injectable
(ALT_TPU_SYSFS_ROOT / ALT_TPU_DEV_ROOT) so tests drive fixture trees; the
PassthroughSupport feature gate guards the whole path.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

log = logging.getLogger(__name__)

VFIO_PCI_DRIVER = "vfio-pci"


class VfioError(Exception):
    pass


class VfioPciManager:
    def __init__(
        self,
        sysfs_root: Optional[str] = None,
        dev_root: Optional[str] = None,
        fixture_kernel: bool = False,
    ):
        """``fixture_kernel=True`` points the manager at a mock sysfs tree
        (vfiosysfs.build_vfio_sysfs) and emulates the kernel's reactions to
        writes in-process — the ALT_PROC_DEVICES_PATH-style seam (reference
        internal/common/nvcaps.go:33-75). It must stay False against any
        *real* sysfs, relocated or not (e.g. /host/sys in a containerized
        plugin), where the kernel itself reacts. The real plugin binaries
        opt in via ALT_TPU_VFIO_FIXTURE=1 (explicit — never inferred from
        the sysfs path, which legitimately differs in containers)."""
        self.sysfs_root = sysfs_root or os.environ.get("ALT_TPU_SYSFS_ROOT", "/sys")
        self.dev_root = dev_root or os.environ.get("ALT_TPU_DEV_ROOT", "/dev")
        self._fixture_kernel_on = (
            fixture_kernel or os.environ.get("ALT_TPU_VFIO_FIXTURE") == "1"
        )

    # -- sysfs paths ----------------------------------------------------------

    def _pci_dir(self, pci_address: str) -> str:
        return os.path.join(self.sysfs_root, "bus", "pci", "devices", pci_address)

    def _driver_link(self, pci_address: str) -> str:
        return os.path.join(self._pci_dir(pci_address), "driver")

    def current_driver(self, pci_address: str) -> str:
        link = self._driver_link(pci_address)
        if not os.path.islink(link):
            return ""  # unbound (realpath on a dangling path is identity)
        try:
            return os.path.basename(os.path.realpath(link))
        except OSError:
            return ""

    def iommu_group(self, pci_address: str) -> str:
        link = os.path.join(self._pci_dir(pci_address), "iommu_group")
        if not os.path.islink(link):
            return ""
        try:
            return os.path.basename(os.path.realpath(link))
        except OSError:
            return ""

    def iommufd_available(self) -> bool:
        return os.path.exists(os.path.join(self.dev_root, "iommu"))

    def iommufd_cdev(self, pci_address: str) -> str:
        """The device's IOMMUFD cdev path (/dev/vfio/devices/vfioN), or ""
        when the kernel exposes none. The kernel publishes the cdev name
        under the device's sysfs vfio-dev/ directory once it is bound to
        vfio-pci with iommufd support (the nvpci IommuFD lookup the
        reference relies on, vfio-cdi.go:96-106)."""
        vdir = os.path.join(self._pci_dir(pci_address), "vfio-dev")
        try:
            names = sorted(os.listdir(vdir))
        except OSError:
            return ""
        for name in names:
            if name.startswith("vfio") and name[4:].isdigit():
                return os.path.join(self.dev_root, "vfio", "devices", name)
        return ""

    def api_device_path(self, iommu_mode: str) -> str:
        """The IOMMU API container device: /dev/iommu for the iommufd
        backend, the legacy /dev/vfio/vfio container otherwise
        (vfio-cdi.go:52-81)."""
        if iommu_mode == "iommufd":
            return os.path.join(self.dev_root, "iommu")
        return os.path.join(self.dev_root, "vfio", "vfio")

    # -- rebinding -------------------------------------------------------------

    def _write(self, path: str, value: str) -> None:
        try:
            with open(path, "w", encoding="utf-8") as f:
                f.write(value)
        except OSError as e:
            raise VfioError(f"write {value!r} to {path}: {e}") from None
        if self._fixture_kernel_on:
            self._fixture_kernel(path, value)

    def _fixture_kernel(self, path: str, value: str) -> None:
        """Emulate what the kernel does in response to a sysfs write."""
        devices = os.path.join(self.sysfs_root, "bus", "pci", "devices")
        addr = value.strip()
        if path.endswith(os.path.join("driver", "unbind")):
            link = os.path.join(devices, addr, "driver")
            if os.path.islink(link):
                was_vfio = os.path.basename(os.path.realpath(link)) == VFIO_PCI_DRIVER
                os.unlink(link)
                if was_vfio:
                    # Leaving vfio-pci removes the group's /dev/vfio node
                    # once no member device remains bound (single-function
                    # fixture: always) — and the iommufd cdev with it.
                    node = os.path.join(
                        self.dev_root, "vfio", self.iommu_group(addr)
                    )
                    if os.path.exists(node):
                        os.unlink(node)
                    self._fixture_drop_cdev(addr)
        elif path.endswith("drivers_probe"):
            link = os.path.join(devices, addr, "driver")
            if os.path.islink(link):
                return  # already bound; probe is a no-op
            try:
                with open(os.path.join(devices, addr, "driver_override"),
                          encoding="utf-8") as f:
                    override = f.read().strip()
            except OSError:
                override = ""
            if not override:
                try:
                    with open(os.path.join(devices, addr, ".default_driver"),
                              encoding="utf-8") as f:
                        override = f.read().strip()
                except OSError:
                    return  # no matching driver: device stays unbound
            drv_dir = os.path.join(self.sysfs_root, "bus", "pci", "drivers", override)
            if not os.path.isdir(drv_dir):
                return  # driver not loaded: probe finds nothing
            os.symlink(os.path.join("..", "..", "drivers", override), link)
            if override == VFIO_PCI_DRIVER:
                group = self.iommu_group(addr)
                if group:
                    vdir = os.path.join(self.dev_root, "vfio")
                    os.makedirs(vdir, exist_ok=True)
                    open(os.path.join(vdir, group), "a").close()
                    if self.iommufd_available():
                        # An iommufd-capable kernel also publishes the
                        # per-device cdev: sysfs vfio-dev/vfioN plus the
                        # /dev/vfio/devices/vfioN node (group number doubles
                        # as a unique N in the single-function fixture).
                        name = f"vfio{group}"
                        os.makedirs(
                            os.path.join(devices, addr, "vfio-dev", name),
                            exist_ok=True)
                        cdev_dir = os.path.join(vdir, "devices")
                        os.makedirs(cdev_dir, exist_ok=True)
                        open(os.path.join(cdev_dir, name), "a").close()

    def _fixture_drop_cdev(self, addr: str) -> None:
        import shutil

        vdir = os.path.join(self._pci_dir(addr), "vfio-dev")
        try:
            names = os.listdir(vdir)
        except OSError:
            return
        for name in names:
            node = os.path.join(self.dev_root, "vfio", "devices", name)
            if os.path.exists(node):
                os.unlink(node)
        shutil.rmtree(vdir, ignore_errors=True)

    def wait_device_free(self, dev_path: str, timeout_s: float = 10.0) -> None:
        """Refuse to yank a device out from under a running workload: wait
        for its node to be openable (reference GPU-free wait, 85-116)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                fd = os.open(dev_path, os.O_RDONLY | os.O_NONBLOCK)
                os.close(fd)
                return
            except OSError as e:
                import errno

                if e.errno == errno.ENOENT:
                    return  # already unbound
                if e.errno not in (errno.EBUSY,):
                    return  # not busy — permission etc.; binding may proceed
            time.sleep(0.2)
        raise VfioError(f"{dev_path} still busy after {timeout_s}s")

    def ensure_vfio_module(self) -> None:
        """Best-effort `modprobe vfio-pci` when the driver isn't loaded
        (reference vfio-device.go:292-317 modprobes through a chroot to
        the host root, since the plugin container has no modules). The
        host root comes from TPU_DRA_HOST_ROOT; failures are swallowed —
        the post-probe verification in bind_to_vfio errors loudly anyway,
        with a message naming the real problem."""
        drv = os.path.join(self.sysfs_root, "bus", "pci", "drivers",
                           VFIO_PCI_DRIVER)
        if os.path.isdir(drv) or self._fixture_kernel_on:
            return
        import subprocess

        host_root = os.environ.get("TPU_DRA_HOST_ROOT", "")
        cmd = (["chroot", host_root] if host_root else []) + [
            "modprobe", VFIO_PCI_DRIVER]
        try:
            out = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=30, check=False)
            if out.returncode != 0:
                # The root cause (blacklisted module, missing chroot
                # tooling) must be in the logs — bind's post-probe error
                # is generic.
                log.warning("modprobe %s exited %d: %s", VFIO_PCI_DRIVER,
                            out.returncode, out.stderr.strip()[-400:])
        except (OSError, subprocess.TimeoutExpired) as e:  # noqa: PERF203
            log.warning("modprobe %s failed: %s", VFIO_PCI_DRIVER, e)

    def bind_to_vfio(self, pci_address: str, dev_path: Optional[str] = None) -> str:
        """Unbind from the current driver, bind to vfio-pci; returns the
        /dev/vfio/<group> path. When dev_path is given, waits for the accel
        node to be free first so a running workload isn't yanked off the
        device (reference vfio-device.go:85-116)."""
        cur = self.current_driver(pci_address)
        if cur == VFIO_PCI_DRIVER:
            group = self.iommu_group(pci_address)
            return os.path.join(self.dev_root, "vfio", group)
        self.ensure_vfio_module()
        if cur:
            if dev_path:
                self.wait_device_free(dev_path)
            self._write(
                os.path.join(self._driver_link(pci_address), "unbind"), pci_address
            )
        override = os.path.join(self._pci_dir(pci_address), "driver_override")
        self._write(override, VFIO_PCI_DRIVER)
        probe = os.path.join(self.sysfs_root, "bus", "pci", "drivers_probe")
        self._write(probe, pci_address)
        if self.current_driver(pci_address) != VFIO_PCI_DRIVER:
            # Probe found no vfio-pci (module not loaded, device blocked):
            # surface it here so Prepare can roll the device back instead of
            # handing the workload a half-bound function.
            raise VfioError(
                f"{pci_address}: not bound to {VFIO_PCI_DRIVER} after probe "
                f"(current driver: {self.current_driver(pci_address) or 'none'})"
            )
        group = self.iommu_group(pci_address)
        if not group:
            raise VfioError(f"{pci_address}: no IOMMU group after vfio bind")
        return os.path.join(self.dev_root, "vfio", group)

    def unbind_from_vfio(self, pci_address: str) -> None:
        """Return the device to the default (accel) driver. Also recovers a
        driverless device (failed vfio bind left it unbound): clearing the
        override and re-probing rebinds the default driver."""
        cur = self.current_driver(pci_address)
        if cur and cur != VFIO_PCI_DRIVER:
            return  # already on a non-vfio driver: idempotent
        if cur == VFIO_PCI_DRIVER:
            self._write(
                os.path.join(self._driver_link(pci_address), "unbind"), pci_address
            )
        override = os.path.join(self._pci_dir(pci_address), "driver_override")
        self._write(override, "\n")
        self._write(os.path.join(self.sysfs_root, "bus", "pci", "drivers_probe"),
                    pci_address)
