"""VFIO passthrough manager: driver rebinding for untrusted workloads.

Reference: /root/reference/cmd/gpu-kubelet-plugin/vfio-device.go — sysfs
unbind from the accel driver / bind to vfio-pci (235-257), IOMMU(fd)
detection (319-352), wait-until-free (85-116). Roots are injectable
(ALT_TPU_SYSFS_ROOT / ALT_TPU_DEV_ROOT) so tests drive fixture trees; the
PassthroughSupport feature gate guards the whole path.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

log = logging.getLogger(__name__)

VFIO_PCI_DRIVER = "vfio-pci"


class VfioError(Exception):
    pass


class VfioPciManager:
    def __init__(self, sysfs_root: Optional[str] = None, dev_root: Optional[str] = None):
        self.sysfs_root = sysfs_root or os.environ.get("ALT_TPU_SYSFS_ROOT", "/sys")
        self.dev_root = dev_root or os.environ.get("ALT_TPU_DEV_ROOT", "/dev")

    # -- sysfs paths ----------------------------------------------------------

    def _pci_dir(self, pci_address: str) -> str:
        return os.path.join(self.sysfs_root, "bus", "pci", "devices", pci_address)

    def _driver_link(self, pci_address: str) -> str:
        return os.path.join(self._pci_dir(pci_address), "driver")

    def current_driver(self, pci_address: str) -> str:
        try:
            return os.path.basename(os.path.realpath(self._driver_link(pci_address)))
        except OSError:
            return ""

    def iommu_group(self, pci_address: str) -> str:
        link = os.path.join(self._pci_dir(pci_address), "iommu_group")
        try:
            return os.path.basename(os.path.realpath(link))
        except OSError:
            return ""

    def iommufd_available(self) -> bool:
        return os.path.exists(os.path.join(self.dev_root, "iommu"))

    # -- rebinding -------------------------------------------------------------

    def _write(self, path: str, value: str) -> None:
        try:
            with open(path, "w", encoding="utf-8") as f:
                f.write(value)
        except OSError as e:
            raise VfioError(f"write {value!r} to {path}: {e}") from None

    def wait_device_free(self, dev_path: str, timeout_s: float = 10.0) -> None:
        """Refuse to yank a device out from under a running workload: wait
        for its node to be openable (reference GPU-free wait, 85-116)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                fd = os.open(dev_path, os.O_RDONLY | os.O_NONBLOCK)
                os.close(fd)
                return
            except OSError as e:
                import errno

                if e.errno == errno.ENOENT:
                    return  # already unbound
                if e.errno not in (errno.EBUSY,):
                    return  # not busy — permission etc.; binding may proceed
            time.sleep(0.2)
        raise VfioError(f"{dev_path} still busy after {timeout_s}s")

    def bind_to_vfio(self, pci_address: str, dev_path: Optional[str] = None) -> str:
        """Unbind from the current driver, bind to vfio-pci; returns the
        /dev/vfio/<group> path. When dev_path is given, waits for the accel
        node to be free first so a running workload isn't yanked off the
        device (reference vfio-device.go:85-116)."""
        cur = self.current_driver(pci_address)
        if cur == VFIO_PCI_DRIVER:
            group = self.iommu_group(pci_address)
            return os.path.join(self.dev_root, "vfio", group)
        if cur:
            if dev_path:
                self.wait_device_free(dev_path)
            self._write(
                os.path.join(self._driver_link(pci_address), "unbind"), pci_address
            )
        override = os.path.join(self._pci_dir(pci_address), "driver_override")
        self._write(override, VFIO_PCI_DRIVER)
        probe = os.path.join(self.sysfs_root, "bus", "pci", "drivers_probe")
        self._write(probe, pci_address)
        group = self.iommu_group(pci_address)
        if not group:
            raise VfioError(f"{pci_address}: no IOMMU group after vfio bind")
        return os.path.join(self.dev_root, "vfio", group)

    def unbind_from_vfio(self, pci_address: str) -> None:
        """Return the device to the default (accel) driver."""
        if self.current_driver(pci_address) != VFIO_PCI_DRIVER:
            return  # idempotent
        self._write(
            os.path.join(self._driver_link(pci_address), "unbind"), pci_address
        )
        override = os.path.join(self._pci_dir(pci_address), "driver_override")
        self._write(override, "\n")
        self._write(os.path.join(self.sysfs_root, "bus", "pci", "drivers_probe"),
                    pci_address)
