"""TpuDriver — the tpu-kubelet-plugin binary's core.

The reference driver's lifecycle (SURVEY.md §3.1-3.2,
/root/reference/cmd/gpu-kubelet-plugin/driver.go): construct DeviceState,
publish ResourceSlices, serve Prepare/Unprepare under the node-global pu
flock with metrics, watch device health into taints + republish, and run the
periodic stale-claim cleanup loop.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional

from k8s_dra_driver_tpu.api.configs import TPU_DRIVER_NAME
from k8s_dra_driver_tpu.k8s import APIServer, NotFoundError
from k8s_dra_driver_tpu.k8s.core import RESOURCE_CLAIM, ResourceClaim
from k8s_dra_driver_tpu.k8s.core import DeviceTaint
from k8s_dra_driver_tpu.pkg import featuregates as fg
from k8s_dra_driver_tpu.pkg import tracing
from k8s_dra_driver_tpu.pkg.flock import Flock, FlockTimeoutError
from k8s_dra_driver_tpu.pkg.metrics import DRARequestMetrics, Registry
from k8s_dra_driver_tpu.plugins.tpu.device_state import DeviceState, PrepareResult
from k8s_dra_driver_tpu.plugins.tpu.deviceinfo import (
    build_resource_slice,
    create_or_update_slice,
)
from k8s_dra_driver_tpu.tpulib.lib import TpuLib
from k8s_dra_driver_tpu.tpulib.types import ChipHealth

log = logging.getLogger(__name__)

PU_LOCK_TIMEOUT_S = 10.0  # reference budget (driver.go:388,430)
CLEANUP_INTERVAL_S = 600.0  # reference 10 min (cleanup.go:34-36)

UNHEALTHY_TAINT_KEY = "tpu.google.com/unhealthy"


class TpuDriver:
    def __init__(
        self,
        api: APIServer,
        node_name: str,
        tpulib: TpuLib,
        plugin_dir: str,
        cdi_root: Optional[str] = None,
        gates: Optional[fg.FeatureGates] = None,
        metrics_registry: Optional[Registry] = None,
        cleanup_interval_s: float = CLEANUP_INTERVAL_S,
        driver_name: str = TPU_DRIVER_NAME,
        ignored_health_states: frozenset = frozenset(),
        vfio=None,
    ):
        self.api = api
        self.node_name = node_name
        self.driver_name = driver_name
        self.gates = gates or fg.FeatureGates()
        self.state = DeviceState(
            tpulib, plugin_dir, cdi_root=cdi_root, gates=self.gates,
            driver_name=driver_name, vfio=vfio,
        )
        self.metrics = DRARequestMetrics(
            driver=driver_name, registry=metrics_registry or Registry()
        )
        self._pu_lock = Flock(os.path.join(plugin_dir, "pu.lock"))
        self._pool_generation = 1
        # Serializes slice publishes between the main thread and the health
        # watcher's callback thread (taint loss via last-writer-wins and a
        # racy generation increment otherwise).
        self._publish_mu = threading.Lock()
        self._tainted_chips: Dict[int, ChipHealth] = {}
        # Health states the operator declared benign — events in this set
        # never (un)taint (the reference's user-extendable benign-XID skip
        # list, device_health.go:394-443 / --additional-xids-to-ignore).
        self._ignored_health_states = frozenset(ignored_health_states)
        self._cleanup_interval = cleanup_interval_s
        self._stop = threading.Event()
        self._cleanup_thread: Optional[threading.Thread] = None
        self._registered = False

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self.gates.enabled("DynamicSubslice"):
            # Free ICI partitions no checkpointed claim holds — leftovers of
            # a crash mid-prepare (DestroyUnknownMIGDevices analog,
            # reference driver.go:110). Under the pu flock: a rolling
            # restart's overlapping old process may be mid-prepare.
            with self._pu_lock.hold(timeout=PU_LOCK_TIMEOUT_S):
                self.state.destroy_unknown_partitions()
        if self.gates.enabled("TPUDeviceHealthCheck") and hasattr(
            self.state.tpulib, "watch_health"
        ):
            self.state.tpulib.watch_health(self._on_health_event)
        self.publish_resources()
        self._cleanup_thread = threading.Thread(
            target=self._cleanup_loop, name="checkpoint-cleanup", daemon=True
        )
        self._cleanup_thread.start()
        self._registered = True

    def shutdown(self) -> None:
        self._stop.set()
        if hasattr(self.state.tpulib, "stop_health_watch"):
            self.state.tpulib.stop_health_watch()
        if self._cleanup_thread:
            self._cleanup_thread.join(timeout=5)
        self._registered = False

    def healthy(self) -> bool:
        """gRPC healthcheck analog (health.go:39-148)."""
        return self._registered and not self._stop.is_set()

    # -- ResourceSlice publishing -------------------------------------------

    def publish_resources(self) -> None:
        with self._publish_mu:
            rs = build_resource_slice(
                self.node_name,
                self.driver_name,
                self.state.allocatable,
                self.state.inventory,
                pool_generation=self._pool_generation,
            )
            self._pool_generation += 1
            # Apply current taints before publishing.
            for dev in rs.devices:
                chips = self.state.allocatable[dev.name].chip_indices
                if any(c in self._tainted_chips for c in chips):
                    dev.taints.append(
                        DeviceTaint(key=UNHEALTHY_TAINT_KEY, value="true",
                                    effect="NoSchedule")
                    )
            create_or_update_slice(self.api, rs)

    # -- health -> taints ----------------------------------------------------

    def _on_health_event(self, chip_index: int, health: ChipHealth) -> None:
        if health in self._ignored_health_states:
            log.info("chip %d health -> %s (ignored by operator config)",
                     chip_index, health.value)
            return
        log.warning("chip %d health -> %s", chip_index, health.value)
        if health == ChipHealth.HEALTHY:
            self._tainted_chips.pop(chip_index, None)
        else:
            self._tainted_chips[chip_index] = health
        self.publish_resources()

    # -- DRA service --------------------------------------------------------

    def prepare_resource_claims(
        self, claims: List[ResourceClaim]
    ) -> Dict[str, PrepareResult | Exception]:
        """Batch-amortized prepare: ONE pu flock acquire and ONE checkpoint
        session (two fsyncs) for the whole NodePrepareResources call; the
        state machine returns per-claim results/exceptions inline, so a bad
        claim never fails its siblings."""
        if not claims:
            return {}
        out: Dict[str, PrepareResult | Exception] = {}
        with self.metrics.track_batch("PrepareResourceClaims", len(claims)), \
                tracing.span(
                    "dra.prepare_batch", driver=self.driver_name,
                    batch_size=len(claims),
                    claim_uids=[c.uid for c in claims]) as sp:
            try:
                with self._pu_lock.hold(timeout=PU_LOCK_TIMEOUT_S,
                                        trace_name="pu_flock"):
                    out = self.state.prepare_batch(claims)
            except (Exception, FlockTimeoutError) as e:  # noqa: BLE001
                # Whole-batch failure (lock timeout, checkpoint corruption):
                # every claim reports it.
                log.warning("prepare batch of %d failed: %s", len(claims), e)
                out = {c.uid: e for c in claims}
            failed = sum(1 for r in out.values() if isinstance(r, Exception))
            sp.attrs["failed_claims"] = failed
        self.metrics.record_claim_errors("PrepareResourceClaims", failed)
        for claim in claims:
            r = out.get(claim.uid)
            if isinstance(r, Exception):
                log.warning("prepare %s failed: %s", claim.key, r)
        return out

    def unprepare_resource_claims(self, claim_uids: List[str]) -> Dict[str, Optional[Exception]]:
        if not claim_uids:
            return {}
        out: Dict[str, Optional[Exception]] = {}
        with self.metrics.track_batch("UnprepareResourceClaims", len(claim_uids)), \
                tracing.span(
                    "dra.unprepare_batch", driver=self.driver_name,
                    batch_size=len(claim_uids),
                    claim_uids=list(claim_uids)) as sp:
            try:
                with self._pu_lock.hold(timeout=PU_LOCK_TIMEOUT_S,
                                        trace_name="pu_flock"):
                    out = self.state.unprepare_batch(claim_uids)
            except (Exception, FlockTimeoutError) as e:  # noqa: BLE001
                log.warning("unprepare batch of %d failed: %s", len(claim_uids), e)
                out = {uid: e for uid in claim_uids}
            failed = sum(1 for r in out.values() if r is not None)
            sp.attrs["failed_claims"] = failed
        self.metrics.record_claim_errors("UnprepareResourceClaims", failed)
        for uid, err in out.items():
            if err is not None:
                log.warning("unprepare %s failed: %s", uid, err)
        return out

    # -- stale-claim cleanup -------------------------------------------------

    def cleanup_stale_claims(self) -> int:
        """Unprepare claims whose ResourceClaim no longer exists
        (cleanup.go:149-259). Returns how many were cleaned. The whole
        sweep is one unprepare batch: one flock, one checkpoint write."""
        stale = []
        for uid, entry in self.state.prepared_claims().items():
            obj = self.api.try_get(RESOURCE_CLAIM, entry.name, entry.namespace)
            if obj is not None and obj.uid == uid:
                continue
            log.info("cleaning stale claim %s/%s uid=%s", entry.namespace, entry.name, uid)
            stale.append(uid)
        if not stale:
            return 0
        cleaned = 0
        try:
            with tracing.span("dra.stale_cleanup", driver=self.driver_name,
                              claim_uids=list(stale)), \
                    self._pu_lock.hold(timeout=PU_LOCK_TIMEOUT_S,
                                       trace_name="pu_flock"):
                errs = self.state.unprepare_batch(stale)
        except Exception:  # noqa: BLE001
            log.exception("stale cleanup batch of %d failed", len(stale))
            return 0
        for uid, err in errs.items():
            if err is None:
                cleaned += 1
            else:
                log.error("stale cleanup of %s failed: %s", uid, err)
        return cleaned

    def _cleanup_loop(self) -> None:
        while not self._stop.wait(self._cleanup_interval):
            try:
                self.cleanup_stale_claims()
            except Exception:  # noqa: BLE001
                log.exception("cleanup pass failed")
