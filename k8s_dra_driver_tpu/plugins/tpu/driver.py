"""TpuDriver — the tpu-kubelet-plugin binary's core.

The reference driver's lifecycle (SURVEY.md §3.1-3.2,
/root/reference/cmd/gpu-kubelet-plugin/driver.go): construct DeviceState,
publish ResourceSlices, serve Prepare/Unprepare under the node-global pu
flock with metrics, watch device health into taints + republish, and run the
periodic stale-claim cleanup loop.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional

from k8s_dra_driver_tpu.api.configs import TPU_DRIVER_NAME
from k8s_dra_driver_tpu.k8s import APIServer, NotFoundError
from k8s_dra_driver_tpu.k8s.core import RESOURCE_CLAIM, ResourceClaim
from k8s_dra_driver_tpu.k8s.core import (
    DeviceTaint,
    ICI_LINK_TAINT_KEY,
    UNHEALTHY_TAINT_KEY,
)
from k8s_dra_driver_tpu.pkg import featuregates as fg
from k8s_dra_driver_tpu.pkg import tracing
from k8s_dra_driver_tpu.pkg.events import (
    EventRecorder,
    REASON_CHECKPOINT_RECOVERED,
    REASON_DEVICE_DEGRADED,
    REASON_DEVICE_RECOVERED,
    REASON_PREPARE_FAILED,
    REASON_PREPARED_DEVICES,
    REASON_UNPREPARE_FAILED,
)
from k8s_dra_driver_tpu.pkg.flock import Flock, FlockTimeoutError
from k8s_dra_driver_tpu.pkg.metrics import DRARequestMetrics, Registry
from k8s_dra_driver_tpu.plugins.tpu.device_state import (
    DeviceHealthMonitor,
    DeviceState,
    PrepareResult,
)
from k8s_dra_driver_tpu.plugins.tpu.deviceinfo import (
    build_resource_slice,
    create_or_update_slice,
)
from k8s_dra_driver_tpu.tpulib.lib import TpuLib
from k8s_dra_driver_tpu.tpulib.types import ChipHealth

log = logging.getLogger(__name__)

PU_LOCK_TIMEOUT_S = 10.0  # reference budget (driver.go:388,430)
CLEANUP_INTERVAL_S = 600.0  # reference 10 min (cleanup.go:34-36)

# UNHEALTHY_TAINT_KEY / ICI_LINK_TAINT_KEY moved to k8s.core (canonical
# home next to DeviceTaint, shared with the controller's mesh compiler);
# re-imported above so existing plugin-side call sites keep working.


class TpuDriver:
    def __init__(
        self,
        api: APIServer,
        node_name: str,
        tpulib: TpuLib,
        plugin_dir: str,
        cdi_root: Optional[str] = None,
        gates: Optional[fg.FeatureGates] = None,
        metrics_registry: Optional[Registry] = None,
        cleanup_interval_s: float = CLEANUP_INTERVAL_S,
        driver_name: str = TPU_DRIVER_NAME,
        ignored_health_states: frozenset = frozenset(),
        vfio=None,
        telemetry_interval_s: float = 0.0,
    ):
        self.api = api
        self.node_name = node_name
        self.driver_name = driver_name
        self.gates = gates or fg.FeatureGates()
        self.state = DeviceState(
            tpulib, plugin_dir, cdi_root=cdi_root, gates=self.gates,
            driver_name=driver_name, vfio=vfio,
        )
        registry = metrics_registry or Registry()
        self.metrics = DRARequestMetrics(driver=driver_name, registry=registry)
        self.recorder = EventRecorder(api, "tpu-kubelet-plugin",
                                      metrics_registry=registry)
        inv = self.state.inventory
        self.health = DeviceHealthMonitor(
            node_name, self.state.allocatable, metrics_registry=registry,
            tpulib=tpulib,
            hbm_by_chip={c.index: c.hbm_bytes for c in inv.chips},
            link_gbps=(inv.links[0].gbps if inv.links else 45.0),
            state_path=os.path.join(plugin_dir, "telemetry.json"),
        )
        # interval <= 0 disables the sampling thread (unit tests, and the
        # sim — which drives sample_telemetry() synchronously per pass so
        # its telemetry is deterministic). The thread never runs under the
        # pu flock or the DeviceState mutex.
        self._telemetry_interval = telemetry_interval_s
        self._telemetry_thread: Optional[threading.Thread] = None
        self._pu_lock = Flock(os.path.join(plugin_dir, "pu.lock"))
        self._pool_generation = 1
        # Serializes slice publishes between the main thread and the health
        # watcher's callback thread (taint loss via last-writer-wins and a
        # racy generation increment otherwise).
        self._publish_mu = threading.Lock()
        # CheckpointRecovered events: DeviceState reports each stale
        # PrepareStarted rollback; the claim's Event is recorded against
        # the object the checkpoint remembers.
        self.state.recovery_hook = self._on_checkpoint_recovery
        # Health states the operator declared benign — events in this set
        # never (un)taint (the reference's user-extendable benign-XID skip
        # list, device_health.go:394-443 / --additional-xids-to-ignore).
        self._ignored_health_states = frozenset(ignored_health_states)
        self._cleanup_interval = cleanup_interval_s
        self._stop = threading.Event()
        self._cleanup_thread: Optional[threading.Thread] = None
        self._registered = False

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self.gates.enabled("DynamicSubslice"):
            # Free ICI partitions no checkpointed claim holds — leftovers of
            # a crash mid-prepare (DestroyUnknownMIGDevices analog,
            # reference driver.go:110). Under the pu flock: a rolling
            # restart's overlapping old process may be mid-prepare.
            with self._pu_lock.hold(timeout=PU_LOCK_TIMEOUT_S):
                self.state.destroy_unknown_partitions()
        if self.gates.enabled("TPUDeviceHealthCheck"):
            # Seed from the enumerated snapshot: chips and ICI links already
            # unhealthy at plugin start must be tainted by the FIRST
            # publish, not only after their next transition event (a
            # restart must not silently clear taints on a broken fabric).
            for chip in self.state.inventory.chips:
                if (chip.health != ChipHealth.HEALTHY
                        and chip.health not in self._ignored_health_states):
                    delta = self.health.set_chip(chip.index, chip.health)
                    if delta is not None:
                        self._record_health_event(delta)
            if hasattr(self.state.tpulib, "link_health"):
                for (a, b), health in self.state.tpulib.link_health().items():
                    if (health != ChipHealth.HEALTHY
                            and health not in self._ignored_health_states):
                        delta = self.health.set_link(a, b, health)
                        if delta is not None:
                            self._record_health_event(delta)
            if hasattr(self.state.tpulib, "watch_health"):
                self.state.tpulib.watch_health(self._on_health_event)
            if hasattr(self.state.tpulib, "watch_link_health"):
                self.state.tpulib.watch_link_health(self._on_link_health_event)
        # Telemetry restart re-seed: last-known window metadata republishes
        # the per-chip gauges before the first live sample, so a restarted
        # plugin never reports a zero fleet until its window refills.
        self.health.load_telemetry_state()
        self.publish_resources()
        if self._telemetry_interval > 0:
            self._telemetry_thread = threading.Thread(
                target=self._telemetry_loop, name="telemetry-sampler",
                daemon=True)
            self._telemetry_thread.start()
        if self._cleanup_interval > 0:
            # interval <= 0 disables the timer thread entirely: a sim
            # running thousands of in-process plugins cannot afford one
            # thread per node (the container's thread/PID cap, not memory,
            # is what limits cluster size), and its event-driven GC pass
            # performs this same sweep deterministically.
            self._cleanup_thread = threading.Thread(
                target=self._cleanup_loop, name="checkpoint-cleanup", daemon=True
            )
            self._cleanup_thread.start()
        self._registered = True

    def shutdown(self) -> None:
        self._stop.set()
        if hasattr(self.state.tpulib, "stop_health_watch"):
            self.state.tpulib.stop_health_watch()
        if self._cleanup_thread:
            self._cleanup_thread.join(timeout=5)
        if self._telemetry_thread:
            self._telemetry_thread.join(timeout=5)
        # Final seed write (thread or externally-driven sampling alike) so
        # a restart republishes the freshest window, not one a whole
        # throttle interval old.
        if self.health.samples_taken:
            self.health.save_telemetry_state(force=True)
        self._registered = False

    # -- telemetry sampling ---------------------------------------------------

    def _telemetry_loop(self) -> None:
        while not self._stop.wait(self._telemetry_interval):
            try:
                self.sample_telemetry()
            except Exception:  # noqa: BLE001 — sampling must not kill the plugin
                log.exception("telemetry sample failed")

    def sample_telemetry(self, now: Optional[float] = None) -> int:
        """One sampling tick: read counters into the ring buffers/gauges,
        persist the window-metadata seed, and feed any telemetry-derived
        link-degradation transition through the same taint/event chain
        the health watcher uses. Returns the number of health deltas."""
        deltas = self.health.sample(now=now)
        self.health.save_telemetry_state()
        for delta in deltas:
            self._record_health_event(delta)
        if deltas:
            self.publish_resources()
        return len(deltas)

    def healthy(self) -> bool:
        """gRPC healthcheck analog (health.go:39-148)."""
        return self._registered and not self._stop.is_set()

    # -- ResourceSlice publishing -------------------------------------------

    def publish_resources(self) -> None:
        with self._publish_mu:
            rs = build_resource_slice(
                self.node_name,
                self.driver_name,
                self.state.allocatable,
                self.state.inventory,
                pool_generation=self._pool_generation,
            )
            self._pool_generation += 1
            # Apply current health taints before publishing: chip-level
            # faults under the silicon key, link-spanning devices under the
            # fabric key (both NoSchedule — the allocator skips either).
            tainted = self.health.tainted_devices()
            for dev in rs.devices:
                cause = tainted.get(dev.name)
                if cause == "chip":
                    dev.taints.append(
                        DeviceTaint(key=UNHEALTHY_TAINT_KEY, value="true",
                                    effect="NoSchedule")
                    )
                elif cause == "link":
                    dev.taints.append(
                        DeviceTaint(key=ICI_LINK_TAINT_KEY, value="true",
                                    effect="NoSchedule")
                    )
            create_or_update_slice(self.api, rs)

    # -- health -> taints + events -------------------------------------------

    def _node_ref(self):
        node = self.api.try_get("Node", self.node_name)
        if node is not None:
            return node
        from k8s_dra_driver_tpu.k8s.core import ObjectReference

        return ObjectReference(kind="Node", name=self.node_name)

    def _record_health_event(self, delta) -> None:
        what = (f"chip {delta.id}" if delta.kind == "chip"
                else f"ICI link {delta.id}")
        devs = ",".join(delta.affected_devices) or "none"
        if delta.health == ChipHealth.HEALTHY:
            self.recorder.normal(
                self._node_ref(), REASON_DEVICE_RECOVERED,
                f"{what} on {self.node_name} recovered; "
                f"untainted devices: {devs}")
        else:
            self.recorder.warning(
                self._node_ref(), REASON_DEVICE_DEGRADED,
                f"{what} on {self.node_name} is {delta.health.value}; "
                f"tainted devices: {devs}")

    def _on_health_event(self, chip_index: int, health: ChipHealth) -> None:
        if health in self._ignored_health_states:
            log.info("chip %d health -> %s (ignored by operator config)",
                     chip_index, health.value)
            return
        log.warning("chip %d health -> %s", chip_index, health.value)
        delta = self.health.set_chip(chip_index, health)
        if delta is None:
            return
        self._record_health_event(delta)
        self.publish_resources()

    def _on_link_health_event(self, a: int, b: int, health: ChipHealth) -> None:
        if health in self._ignored_health_states:
            log.info("link %d-%d health -> %s (ignored by operator config)",
                     a, b, health.value)
            return
        log.warning("link %d-%d health -> %s", a, b, health.value)
        delta = self.health.set_link(a, b, health)
        if delta is None:
            return
        self._record_health_event(delta)
        self.publish_resources()

    def _on_checkpoint_recovery(self, entry) -> None:
        from k8s_dra_driver_tpu.k8s.core import ObjectReference

        ref = ObjectReference(kind=RESOURCE_CLAIM, name=entry.name,
                              namespace=entry.namespace, uid=entry.claim_uid)
        self.recorder.warning(
            ref, REASON_CHECKPOINT_RECOVERED,
            f"rolled back stale PrepareStarted checkpoint entry on "
            f"{self.node_name} (plugin restarted mid-prepare)")

    # -- DRA service --------------------------------------------------------

    def prepare_resource_claims(
        self, claims: List[ResourceClaim]
    ) -> Dict[str, PrepareResult | Exception]:
        """Batch-amortized prepare: ONE pu flock acquire and ONE checkpoint
        session (two fsyncs) for the whole NodePrepareResources call; the
        state machine returns per-claim results/exceptions inline, so a bad
        claim never fails its siblings."""
        if not claims:
            return {}
        out: Dict[str, PrepareResult | Exception] = {}
        with self.metrics.track_batch("PrepareResourceClaims", len(claims)), \
                tracing.span(
                    "dra.prepare_batch", driver=self.driver_name,
                    batch_size=len(claims),
                    claim_uids=[c.uid for c in claims]) as sp:
            try:
                with self._pu_lock.hold(timeout=PU_LOCK_TIMEOUT_S,
                                        trace_name="pu_flock"):
                    out = self.state.prepare_batch(claims)
            except (Exception, FlockTimeoutError) as e:  # noqa: BLE001
                # Whole-batch failure (lock timeout, checkpoint corruption):
                # every claim reports it.
                log.warning("prepare batch of %d failed: %s", len(claims), e)
                out = {c.uid: e for c in claims}
            failed = sum(1 for r in out.values() if isinstance(r, Exception))
            sp.attrs["failed_claims"] = failed
            # Telemetry context: which chips each claim landed on and what
            # those chips were doing (duty/HBM, last sample) at prepare
            # time — the attributes that let a trace answer "what was the
            # chip doing when this claim arrived".
            chip_sets = {
                uid: sorted({i for d in r.devices for i in d.chip_indices})
                for uid, r in out.items() if isinstance(r, PrepareResult)
            }
            if chip_sets:
                sp.attrs["chip_sets"] = chip_sets
                last = self.health.last_sample()
                touched = sorted({i for c in chip_sets.values() for i in c})
                sp.attrs["duty_at_prepare"] = {
                    str(i): round(last["duty"][i], 4)
                    for i in touched if i in last["duty"]}
                sp.attrs["hbm_at_prepare"] = {
                    str(i): int(last["hbm"][i])
                    for i in touched if i in last["hbm"]}
        self.metrics.record_claim_errors("PrepareResourceClaims", failed)
        for claim in claims:
            r = out.get(claim.uid)
            if isinstance(r, Exception):
                log.warning("prepare %s failed: %s", claim.key, r)
                self.recorder.warning(
                    claim, REASON_PREPARE_FAILED,
                    f"prepare on {self.node_name} failed: {r}")
            elif r is not None:
                devs = ",".join(d.name for d in r.devices)
                self.recorder.normal(
                    claim, REASON_PREPARED_DEVICES,
                    f"prepared [{devs}] on {self.node_name}")
        return out

    def unprepare_resource_claims(self, claim_uids: List[str]) -> Dict[str, Optional[Exception]]:
        if not claim_uids:
            return {}
        out: Dict[str, Optional[Exception]] = {}
        with self.metrics.track_batch("UnprepareResourceClaims", len(claim_uids)), \
                tracing.span(
                    "dra.unprepare_batch", driver=self.driver_name,
                    batch_size=len(claim_uids),
                    claim_uids=list(claim_uids)) as sp:
            held = self.state.prepared_chipsets()
            chip_sets = {uid: list(held[uid][2]) for uid in claim_uids
                         if uid in held}
            if chip_sets:
                sp.attrs["chip_sets"] = chip_sets
            try:
                with self._pu_lock.hold(timeout=PU_LOCK_TIMEOUT_S,
                                        trace_name="pu_flock"):
                    out = self.state.unprepare_batch(claim_uids)
            except (Exception, FlockTimeoutError) as e:  # noqa: BLE001
                log.warning("unprepare batch of %d failed: %s", len(claim_uids), e)
                out = {uid: e for uid in claim_uids}
            failed = sum(1 for r in out.values() if r is not None)
            sp.attrs["failed_claims"] = failed
        self.metrics.record_claim_errors("UnprepareResourceClaims", failed)
        if failed:
            from k8s_dra_driver_tpu.k8s.core import ObjectReference

            # Failed entries survive in the checkpoint, so the claim's
            # name/namespace can be resolved lazily — the common all-success
            # path pays no extra flock/load. A uid-only ref would file the
            # Event in namespace "" where describe/get never look.
            known = self.state.prepared_claims()
            for uid, err in out.items():
                if err is None:
                    continue
                log.warning("unprepare %s failed: %s", uid, err)
                entry = known.get(uid)
                self.recorder.warning(
                    ObjectReference(kind=RESOURCE_CLAIM,
                                    name=entry.name if entry else "",
                                    namespace=entry.namespace if entry else "",
                                    uid=uid),
                    REASON_UNPREPARE_FAILED,
                    f"unprepare on {self.node_name} failed: {err}")
        return out

    # -- live-repack migration -----------------------------------------------

    def migrate_claim_out(self, claim_uid: str):
        """Checkpoint-aware unprepare for live migration: one pu flock hold
        around the DeviceState MigrationCheckpoint handshake. Returns the
        migration entry snapshot (the source-placement record)."""
        with tracing.span("dra.migrate_out", driver=self.driver_name,
                          claim_uid=claim_uid), \
                self._pu_lock.hold(timeout=PU_LOCK_TIMEOUT_S,
                                   trace_name="pu_flock"):
            return self.state.migrate_out(claim_uid)

    def migrate_claim_end(self, claim_uid: str) -> None:
        """Drop the MigrationCheckpoint entry once the claim is prepared on
        its target node (or the rollback re-prepare cleared it)."""
        with tracing.span("dra.migrate_end", driver=self.driver_name,
                          claim_uid=claim_uid), \
                self._pu_lock.hold(timeout=PU_LOCK_TIMEOUT_S,
                                   trace_name="pu_flock"):
            self.state.end_migration(claim_uid)

    # -- stale-claim cleanup -------------------------------------------------

    def cleanup_stale_claims(self) -> int:
        """Unprepare claims whose ResourceClaim no longer exists
        (cleanup.go:149-259). Returns how many were cleaned. The whole
        sweep is one unprepare batch: one flock, one checkpoint write."""
        stale = []
        for uid, entry in self.state.prepared_claims().items():
            obj = self.api.try_get(RESOURCE_CLAIM, entry.name, entry.namespace)
            if obj is not None and obj.uid == uid:
                continue
            log.info("cleaning stale claim %s/%s uid=%s", entry.namespace, entry.name, uid)
            stale.append(uid)
        if not stale:
            return 0
        cleaned = 0
        try:
            with tracing.span("dra.stale_cleanup", driver=self.driver_name,
                              claim_uids=list(stale)), \
                    self._pu_lock.hold(timeout=PU_LOCK_TIMEOUT_S,
                                       trace_name="pu_flock"):
                errs = self.state.unprepare_batch(stale)
        except Exception:  # noqa: BLE001
            log.exception("stale cleanup batch of %d failed", len(stale))
            return 0
        for uid, err in errs.items():
            if err is None:
                cleaned += 1
            else:
                log.error("stale cleanup of %s failed: %s", uid, err)
        return cleaned

    def _cleanup_loop(self) -> None:
        while not self._stop.wait(self._cleanup_interval):
            try:
                self.cleanup_stale_claims()
            except Exception:  # noqa: BLE001
                log.exception("cleanup pass failed")
