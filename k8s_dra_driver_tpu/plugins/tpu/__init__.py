"""tpu-kubelet-plugin — per-node DRA plugin for driver ``tpu.google.com``.

Role of the reference's gpu-kubelet-plugin (SURVEY.md §2.1, §2.4):
enumerate chips/subslices/VFIO devices via tpulib, publish ResourceSlices
with KEP-4815 counters, Prepare/Unprepare claims through a crash-consistent
checkpointed state machine, inject devices via CDI.
"""
