"""AllocatableDevice -> ResourceSlice Device conversion.

Reference analog: GpuInfo/MigDeviceInfo -> resourceapi.Device with
attributes (/root/reference/cmd/gpu-kubelet-plugin/deviceinfo.go:170-328)
plus the KEP-4815 per-host CounterSet for subslice exclusivity
(partitions.go:53-246): every chip is a counter; a chip device consumes its
own counter and a subslice consumes all of its chips' counters, so the
scheduler can never hand out overlapping silicon.
"""

from __future__ import annotations

from typing import Dict, List

from k8s_dra_driver_tpu.k8s.core import (
    Counter,
    CounterSet,
    Device,
    DeviceCounterConsumption,
    ResourcePool,
    ResourceSlice,
)
from k8s_dra_driver_tpu.k8s.objects import new_meta
from k8s_dra_driver_tpu.plugins.tpu.allocatable import (
    AllocatableDevice,
    SubsliceDevice,
    TpuDevice,
    VfioDevice,
)
from k8s_dra_driver_tpu.pkg import placement
from k8s_dra_driver_tpu.tpulib.types import HostInventory

HOST_COUNTER_SET = "tpu-host-chips"


def chip_counter_name(index: int) -> str:
    return f"chip-{index}"


def _host_grid_attrs(inv: HostInventory) -> Dict[str, str]:
    """Host-grid coordinates for topology-aware domain placement: where
    this host's chip block sits in the slice's grid of hosts
    (``hostCoord``) and the grid's dimensions (``hostGrid``), both in
    host units. The scheduler's host-set planner groups by ``iciDomain``
    and packs ComputeDomains onto grid-contiguous blocks using exactly
    these. Omitted when the host shape doesn't tile the slice (defensive:
    enumeration should never produce that)."""
    try:
        grid = placement.host_grid_dims(inv.slice_topology, inv.host_topology)
        coord = placement.host_grid_coord(inv.slice_topology,
                                          inv.host_topology, inv.worker_id)
    except (ValueError, TypeError):
        return {}
    return {
        "tpu.google.com/hostGrid": "x".join(str(d) for d in grid),
        "tpu.google.com/hostCoord": "x".join(str(c) for c in coord),
    }


def device_to_api(dev: AllocatableDevice, inv: HostInventory) -> Device:
    common = {
        "tpu.google.com/gen": inv.gen.value,
        "tpu.google.com/acceleratorType": inv.accelerator_type,
        "tpu.google.com/iciDomain": inv.ici_domain,
        "tpu.google.com/sliceTopology": inv.slice_topology,
        "tpu.google.com/hostTopology": inv.host_topology,
        "tpu.google.com/workerId": inv.worker_id,
        "type": dev.device_type,
    }
    common.update(_host_grid_attrs(inv))
    if isinstance(dev, TpuDevice):
        c = dev.chip
        attrs = {
            **common,
            "uuid": c.uuid,
            "index": c.index,
            "coords": "x".join(str(v) for v in c.coords),
            "numaNode": c.numa_node,
            "serial": c.serial,
        }
        capacity = {"hbm": c.hbm_bytes, "cores": c.cores}
    elif isinstance(dev, SubsliceDevice):
        attrs = {
            **common,
            "profile": dev.placement.profile,
            "chips": ",".join(str(i) for i in dev.chip_indices),
        }
        capacity = {
            "hbm": sum(c.hbm_bytes for c in dev.chips),
            "cores": sum(c.cores for c in dev.chips),
            "chips": len(dev.chips),
        }
    elif isinstance(dev, VfioDevice):
        c = dev.chip
        attrs = {
            **common,
            "uuid": c.uuid,
            "index": c.index,
            "pciAddress": c.pci_address,
        }
        capacity = {"hbm": c.hbm_bytes}
    else:  # pragma: no cover
        raise TypeError(f"unknown device {dev}")
    return Device(
        name=dev.name,
        attributes=attrs,
        capacity=capacity,
        consumes_counters=[
            DeviceCounterConsumption(
                counter_set=HOST_COUNTER_SET,
                counters={chip_counter_name(i): Counter(1) for i in dev.chip_indices},
            )
        ],
    )


def create_or_update_slice(api, rs: ResourceSlice) -> None:
    """Publish a ResourceSlice: create, or overwrite the existing one."""
    existing = api.try_get(rs.kind, rs.meta.name)
    if existing is None:
        api.create(rs)
    else:
        rs.meta = existing.meta
        api.update(rs)


def build_resource_slice(
    node_name: str,
    driver: str,
    devices: Dict[str, AllocatableDevice],
    inv: HostInventory,
    pool_generation: int = 1,
) -> ResourceSlice:
    """One ResourceSlice advertising every allocatable device on this node."""
    api_devices: List[Device] = [
        device_to_api(devices[name], inv) for name in sorted(devices)
    ]
    counters = CounterSet(
        name=HOST_COUNTER_SET,
        counters={chip_counter_name(c.index): Counter(1) for c in inv.chips},
    )
    return ResourceSlice(
        meta=new_meta(f"{node_name}-{driver}"),
        driver=driver,
        node_name=node_name,
        pool=ResourcePool(name=node_name, generation=pool_generation),
        devices=api_devices,
        shared_counters=[counters],
    )
