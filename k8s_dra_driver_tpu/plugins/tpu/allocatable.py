"""Allocatable devices: the chip/subslice/VFIO sum type + canonical naming.

Reference analog: the AllocatableDevice sum type keyed by canonical name
(/root/reference/cmd/gpu-kubelet-plugin/allocatable.go:37-120) and MIG
canonical naming gpu-<minor>-mig-<profile>-<placement> (mig.go:111-223).
TPU naming:

    tpu-<index>                      one chip
    tpu-subslice-<profile>-at-<x>x<y>  an ICI subslice placement
    tpu-<index>-vfio                 a chip's VFIO passthrough sibling
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple, Union

from k8s_dra_driver_tpu.tpulib.types import (
    ChipInfo,
    HostInventory,
    SubslicePlacement,
)

DEVICE_TYPE_TPU = "tpu"
DEVICE_TYPE_SUBSLICE = "subslice"
DEVICE_TYPE_VFIO = "vfio"


def tpu_device_name(index: int) -> str:
    return f"tpu-{index}"


def vfio_device_name(index: int) -> str:
    return f"tpu-{index}-vfio"


def subslice_device_name(placement: SubslicePlacement) -> str:
    return f"tpu-subslice-{placement.name_suffix}"


_SUBSLICE_RE = re.compile(r"^tpu-subslice-(\d+x\d+(?:x\d+)?)-at-(\d+(?:x\d+)*)$")
_TPU_RE = re.compile(r"^tpu-(\d+)$")
_VFIO_RE = re.compile(r"^tpu-(\d+)-vfio$")


def parse_device_name(name: str) -> Tuple[str, dict]:
    """Return (device_type, details). Raises ValueError on unknown names."""
    m = _TPU_RE.match(name)
    if m:
        return DEVICE_TYPE_TPU, {"index": int(m.group(1))}
    m = _VFIO_RE.match(name)
    if m:
        return DEVICE_TYPE_VFIO, {"index": int(m.group(1))}
    m = _SUBSLICE_RE.match(name)
    if m:
        return DEVICE_TYPE_SUBSLICE, {
            "profile": m.group(1),
            "start": tuple(int(v) for v in m.group(2).split("x")),
        }
    raise ValueError(f"unparseable device name {name!r}")


@dataclass(frozen=True)
class TpuDevice:
    chip: ChipInfo

    @property
    def name(self) -> str:
        return tpu_device_name(self.chip.index)

    @property
    def device_type(self) -> str:
        return DEVICE_TYPE_TPU

    @property
    def chip_indices(self) -> Tuple[int, ...]:
        return (self.chip.index,)


@dataclass(frozen=True)
class SubsliceDevice:
    placement: SubslicePlacement
    chips: Tuple[ChipInfo, ...]

    @property
    def name(self) -> str:
        return subslice_device_name(self.placement)

    @property
    def device_type(self) -> str:
        return DEVICE_TYPE_SUBSLICE

    @property
    def chip_indices(self) -> Tuple[int, ...]:
        return self.placement.chip_indices


@dataclass(frozen=True)
class VfioDevice:
    chip: ChipInfo
    vfio_group_path: str  # /dev/vfio/<group>, empty until bound
    # /dev/vfio/devices/vfioN, set when bound under the iommufd backend.
    vfio_cdev_path: str = ""

    @property
    def name(self) -> str:
        return vfio_device_name(self.chip.index)

    @property
    def device_type(self) -> str:
        return DEVICE_TYPE_VFIO

    @property
    def chip_indices(self) -> Tuple[int, ...]:
        return (self.chip.index,)


AllocatableDevice = Union[TpuDevice, SubsliceDevice, VfioDevice]


def enumerate_allocatable(
    inventory: HostInventory,
    *,
    with_subslices: bool = True,
    with_vfio: bool = False,
) -> Dict[str, AllocatableDevice]:
    """All devices this host can advertise, keyed by canonical name.

    Chips and their VFIO siblings are alternative views of the same silicon
    (the vfio<->gpu sibling flip, allocatable.go:224-318); subslices overlap
    chips by construction — the scheduler's counter bookkeeping enforces
    exclusivity, not this map.
    """
    out: Dict[str, AllocatableDevice] = {}
    for chip in inventory.chips:
        dev = TpuDevice(chip=chip)
        out[dev.name] = dev
        if with_vfio:
            vdev = VfioDevice(
                chip=chip, vfio_group_path=inventory.vfio_devices.get(chip.index, "")
            )
            out[vdev.name] = vdev
    if with_subslices:
        by_index = {c.index: c for c in inventory.chips}
        for prof in inventory.subslice_profiles:
            for pl in prof.placements:
                dev = SubsliceDevice(
                    placement=pl,
                    chips=tuple(by_index[i] for i in pl.chip_indices),
                )
                out[dev.name] = dev
    return out
