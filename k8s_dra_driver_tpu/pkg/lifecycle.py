"""Claim critical-path profiler: where a claim's wall-clock actually goes.

The flight recorder (pkg/history) answers *why* a controller acted;
nothing answers *what the claim's latency was spent on* — queue wait vs
allocation vs prepare vs waiting for the workload to come up. The
:class:`ClaimLifecycleAnalyzer` reconstructs that breakdown per claim
from the watch stream (plus the DecisionRecords and events already in
the history store for provenance), with the same hot-path discipline as
the telemetry aggregator:

- **zero store ``list()`` calls in steady state** — one bootstrap
  listing per kind at construction, then only watch events mutate the
  tracked state (the ``bench_observability`` gate pins the invariant);
- **bounded memory** — per-claim state and finished profiles are
  LRU-capped at :data:`MAX_TRACKED`;
- **quantized, change-gated writes** — the
  :class:`~k8s_dra_driver_tpu.k8s.core.ObservedFootprint` written onto
  ResourceClaim status rounds onto a grid first, so a re-profile of the
  same workload shape writes nothing.

Phase model — milestones observed off the watch stream, forced monotone
by running max so the per-phase durations ALWAYS sum exactly to the
claim-to-running total regardless of store write ordering:

| phase | from -> to |
|---|---|
| ``pending``   | claim created -> consumer pod bound to a node |
| ``admitted``  | pod bound -> claim allocation written |
| ``allocated`` | allocation -> claim condition Prepared |
| ``prepared``  | Prepared -> consumer pod phase Running |

Multi-host domains add two fleet-level phases observed per
ComputeDomain: ``domain-assembly`` (domain created -> status Ready) and
``meshgen-ready`` (Ready -> first compiled mesh bundle).

Each completed profile publishes four ways: the
``tpu_dra_lifecycle_phase_seconds{phase}`` histogram, a
``lifecycle-phase/<phase>`` history series (so ``top --history`` and
sparklines read fleet drift), one ``lifecycle/claim-profiled``
DecisionRecord whose inputs carry the breakdown (so ``tpu-kubectl
explain`` shows it on the claim's own timeline), and the quantized
``observedFootprint`` status write the ROADMAP's recommender reads.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from k8s_dra_driver_tpu.k8s.core import (
    CLAIM_COND_PREPARED,
    COMPUTE_DOMAIN,
    POD,
    RESOURCE_CLAIM,
    ObservedFootprint,
)
from k8s_dra_driver_tpu.k8s.objects import ConflictError, NotFoundError
from k8s_dra_driver_tpu.pkg.history import RULE_LIFECYCLE_PROFILE

# Closed phase vocabulary (metric label + footprint keys + docs table).
PHASE_PENDING = "pending"
PHASE_ADMITTED = "admitted"
PHASE_ALLOCATED = "allocated"
PHASE_PREPARED = "prepared"
CLAIM_PHASES = (PHASE_PENDING, PHASE_ADMITTED, PHASE_ALLOCATED,
                PHASE_PREPARED)
PHASE_DOMAIN_ASSEMBLY = "domain-assembly"
PHASE_MESHGEN_READY = "meshgen-ready"
ALL_PHASES = CLAIM_PHASES + (PHASE_DOMAIN_ASSEMBLY, PHASE_MESHGEN_READY)

# Per-object state / finished-profile caps (LRU beyond, like the
# telemetry aggregator and event correlator).
MAX_TRACKED = 4096

# Footprint quantization: phase durations in quarter-(virtual-)seconds
# so identical workload shapes CAS the same doc and the change gate
# holds re-profile writes at zero. Duty/HBM reuse the telemetry grid.
PHASE_QUANTUM_S = 0.25

# Virtual-seconds histogram envelope: 0.25 * 2^k, k=0..10 (0.25 s ..
# 256 s) — a sim tick is 1 s, a full multi-host assembly tens of ticks.
LIFECYCLE_PHASE_BUCKETS: Tuple[float, ...] = tuple(
    0.25 * (2**k) for k in range(11))


def _quantize_phase(v: float) -> float:
    return round(round(v / PHASE_QUANTUM_S) * PHASE_QUANTUM_S, 6)


@dataclass
class _ClaimTrack:
    """Milestones observed for one live claim (virtual clock)."""

    namespace: str
    name: str
    uid: str
    created_t: float
    bound_t: Optional[float] = None
    allocated_t: Optional[float] = None
    prepared_t: Optional[float] = None
    running_t: Optional[float] = None
    consumers: Tuple[str, ...] = ()   # reserving pod uids
    profiled: bool = False


@dataclass
class _PodTrack:
    bound_t: Optional[float] = None
    running_t: Optional[float] = None


@dataclass
class _DomainTrack:
    created_t: float
    ready_t: Optional[float] = None
    mesh_t: Optional[float] = None


@dataclass
class ClaimProfile:
    """One finished critical-path breakdown — what ``explain --latency``
    renders and the footprint write serializes."""

    namespace: str
    name: str
    uid: str
    phase_seconds: Dict[str, float] = field(default_factory=dict)
    total_seconds: float = 0.0
    completed_at: float = 0.0


class ClaimLifecycleAnalyzer:
    """Watch-fed per-claim lifecycle reconstruction. ``step(now)`` drains
    the watches and finalizes any claim whose consumer reached Running;
    call it from the sim step (or a controller loop) — it never blocks
    and never lists."""

    def __init__(self, api, history=None, metrics_registry=None,
                 write_footprint: bool = True):
        self.api = api
        self.history = history
        self.write_footprint = write_footprint
        self._mu = threading.Lock()
        self._claims: Dict[str, _ClaimTrack] = {}        # tpulint: guarded-by=_mu
        self._pods: Dict[str, _PodTrack] = {}            # tpulint: guarded-by=_mu
        self._pod_claims: Dict[str, Tuple[str, ...]] = {}  # tpulint: guarded-by=_mu
        self._domains: Dict[str, _DomainTrack] = {}      # tpulint: guarded-by=_mu
        self._profiles: Dict[Tuple[str, str], ClaimProfile] = {}  # tpulint: guarded-by=_mu
        self.profiled_total = 0
        self.phase_seconds = None
        if metrics_registry is not None:
            from k8s_dra_driver_tpu.pkg.metrics import Histogram

            self.phase_seconds = metrics_registry.register(Histogram(
                "tpu_dra_lifecycle_phase_seconds",
                "Per-claim critical-path phase durations (virtual "
                "seconds) reconstructed by the lifecycle analyzer.",
                ("phase",),
                buckets=LIFECYCLE_PHASE_BUCKETS))
        # Watch-first, then bootstrap: any event raced in between is
        # absorbed idempotently (milestones only ever latch earlier
        # observations; re-observing an ADDED is a no-op).
        self._claim_watch = api.watch(RESOURCE_CLAIM, maxsize=65536)
        self._pod_watch = api.watch(POD, maxsize=65536)
        self._domain_watch = api.watch(COMPUTE_DOMAIN, maxsize=65536)
        now0 = 0.0
        with self._mu:
            for rc in api.list(RESOURCE_CLAIM):
                self._ingest_claim_locked("ADDED", rc, now0)
            for pod in api.list(POD):
                self._ingest_pod_locked("ADDED", pod, now0)
            for cd in api.list(COMPUTE_DOMAIN):
                self._ingest_domain_locked("ADDED", cd, now0)

    def close(self) -> None:
        self.api.stop_watch(RESOURCE_CLAIM, self._claim_watch)
        self.api.stop_watch(POD, self._pod_watch)
        self.api.stop_watch(COMPUTE_DOMAIN, self._domain_watch)

    # -- ingestion (watch stream) ---------------------------------------------

    def step(self, now: float) -> int:
        """Drain the watch queues, stamping transitions observed this
        pass at ``now`` (the virtual clock), then finalize and publish
        any claim that completed. Returns profiles published."""
        import queue as _q

        done = []
        with self._mu:  # tpulint: holds=_mu
            for watch, ingest in (
                    (self._claim_watch, self._ingest_claim_locked),
                    (self._pod_watch, self._ingest_pod_locked),
                    (self._domain_watch, self._ingest_domain_locked)):
                while True:
                    try:
                        ev = watch.get_nowait()
                    except _q.Empty:
                        break
                    ingest(ev.type, ev.obj, now)
            for tr in self._claims.values():
                if not tr.profiled and tr.running_t is not None:
                    tr.profiled = True
                    done.append(self._finalize_locked(tr, now))
            self._trim_locked()
        for profile, footprint in done:
            self._publish(profile, footprint, now)
        return len(done)

    def _ingest_claim_locked(self, ev_type: str, rc,
                             now: float) -> None:  # tpulint: holds=_mu
        uid = rc.meta.uid
        if ev_type == "DELETED":
            self._claims.pop(uid, None)
            return
        tr = self._claims.get(uid)
        if tr is None:
            tr = self._claims[uid] = _ClaimTrack(
                namespace=rc.meta.namespace, name=rc.meta.name, uid=uid,
                created_t=now)
        if tr.allocated_t is None and rc.allocation is not None:
            tr.allocated_t = now
        if tr.prepared_t is None and any(
                c.type == CLAIM_COND_PREPARED and c.status == "True"
                for c in rc.conditions):
            tr.prepared_t = now
        if rc.reserved_for:
            tr.consumers = tuple(r.uid for r in rc.reserved_for)
            for pod_uid in tr.consumers:
                known = self._pod_claims.get(pod_uid, ())
                if uid not in known:
                    self._pod_claims[pod_uid] = known + (uid,)
                pt = self._pods.get(pod_uid)
                if pt is not None:
                    self._adopt_pod_times_locked(tr, pt)

    def _ingest_pod_locked(self, ev_type: str, pod,
                           now: float) -> None:  # tpulint: holds=_mu
        uid = pod.meta.uid
        if ev_type == "DELETED":
            self._pods.pop(uid, None)
            self._pod_claims.pop(uid, None)
            return
        pt = self._pods.get(uid)
        if pt is None:
            pt = self._pods[uid] = _PodTrack()
        if pt.bound_t is None and pod.node_name:
            pt.bound_t = now
        if pt.running_t is None and pod.phase == "Running":
            pt.running_t = now
        for claim_uid in self._pod_claims.get(uid, ()):
            tr = self._claims.get(claim_uid)
            if tr is not None:
                self._adopt_pod_times_locked(tr, pt)

    @staticmethod
    def _adopt_pod_times_locked(tr: _ClaimTrack, pt: _PodTrack) -> None:
        if tr.bound_t is None and pt.bound_t is not None:
            tr.bound_t = pt.bound_t
        if tr.running_t is None and pt.running_t is not None:
            tr.running_t = pt.running_t

    def _ingest_domain_locked(self, ev_type: str, cd,
                              now: float) -> None:  # tpulint: holds=_mu
        uid = cd.meta.uid
        if ev_type == "DELETED":
            self._domains.pop(uid, None)
            return
        dt = self._domains.get(uid)
        if dt is None:
            dt = self._domains[uid] = _DomainTrack(created_t=now)
        status = getattr(cd, "status", None)
        if dt.ready_t is None and getattr(status, "status", "") == "Ready":
            dt.ready_t = now
            self._observe_domain_phase(PHASE_DOMAIN_ASSEMBLY,
                                       now - dt.created_t, now)
        if dt.mesh_t is None and getattr(status, "mesh_bundle", None) is not None:
            dt.mesh_t = now
            self._observe_domain_phase(PHASE_MESHGEN_READY,
                                       now - (dt.ready_t or dt.created_t), now)

    def _observe_domain_phase(self, phase: str, dur: float,
                              now: float) -> None:
        dur = max(0.0, dur)
        if self.phase_seconds is not None:
            self.phase_seconds.observe(phase, value=dur)
        if self.history is not None:
            self.history.push(f"lifecycle-phase/{phase}", now, dur)

    # -- finalize + publish ---------------------------------------------------

    def _finalize_locked(self, tr: _ClaimTrack,
                         now: float):  # tpulint: holds=_mu
        # Running-max over the milestone chain: a store write order that
        # lands allocation before bind (the sim does) clamps a phase to
        # zero instead of double-counting, so the sum is EXACTLY
        # running_t - created_t.
        edges = [tr.created_t, tr.bound_t, tr.allocated_t, tr.prepared_t,
                 tr.running_t]
        mono = []
        hi = tr.created_t
        for t in edges:
            hi = max(hi, t if t is not None else hi)
            mono.append(hi)
        phases = {
            phase: mono[i + 1] - mono[i]
            for i, phase in enumerate(CLAIM_PHASES)
        }
        total = mono[-1] - mono[0]
        profile = ClaimProfile(
            namespace=tr.namespace, name=tr.name, uid=tr.uid,
            phase_seconds=phases, total_seconds=total, completed_at=now)
        key = (tr.namespace, tr.name)
        self._profiles.pop(key, None)  # LRU touch
        self._profiles[key] = profile
        footprint = None
        if self.write_footprint:
            footprint = self._footprint_locked(profile)
        return profile, footprint

    def _footprint_locked(self, profile: ClaimProfile) -> ObservedFootprint:
        from k8s_dra_driver_tpu.pkg.telemetry import (
            DUTY_QUANTUM,
            HBM_QUANTUM_BYTES,
        )
        from k8s_dra_driver_tpu.tpulib.loadtrace import percentile

        peak_hbm = 0
        duty_p95 = 0.0
        if self.history is not None:
            ns, name = profile.namespace, profile.name
            hbm_pts = self.history.query(f"claim-hbm/{ns}/{name}")
            if hbm_pts:
                peak_hbm = int(max(p["value"] for p in hbm_pts))
            duty_pts = self.history.query(f"claim-duty/{ns}/{name}")
            if duty_pts:
                duty_p95 = percentile(
                    [p["value"] for p in duty_pts], 0.95)
        return ObservedFootprint(
            phase_seconds={k: _quantize_phase(v)
                           for k, v in profile.phase_seconds.items()},
            peak_hbm_bytes=int(round(peak_hbm / HBM_QUANTUM_BYTES))
            * HBM_QUANTUM_BYTES,
            duty_p95=round(round(duty_p95 / DUTY_QUANTUM) * DUTY_QUANTUM, 6),
            updated_at=profile.completed_at,
        )

    def _publish(self, profile: ClaimProfile,
                 footprint: Optional[ObservedFootprint],
                 now: float) -> None:
        self.profiled_total += 1
        if self.phase_seconds is not None:
            for phase, dur in profile.phase_seconds.items():
                self.phase_seconds.observe(phase, value=dur)
        if self.history is not None:
            for phase, dur in profile.phase_seconds.items():
                self.history.push(f"lifecycle-phase/{phase}", now, dur)
            inputs = {phase: round(dur, 3)
                      for phase, dur in profile.phase_seconds.items()}
            inputs["total"] = round(profile.total_seconds, 3)
            self.history.decide(
                controller="lifecycle", rule=RULE_LIFECYCLE_PROFILE,
                outcome="profiled", kind=RESOURCE_CLAIM,
                namespace=profile.namespace, name=profile.name,
                message=f"claim-to-running {profile.total_seconds:.1f}s",
                inputs=inputs, now=now)
        if footprint is None:
            return

        def mutate(obj, f=footprint):
            # Change gate rides dataclass equality (updated_at excluded):
            # identical quantized values leave the object untouched.
            if obj.observed_footprint != f:
                obj.observed_footprint = f

        try:
            self.api.update_with_retry(
                RESOURCE_CLAIM, profile.name, profile.namespace, mutate)
        except (NotFoundError, ConflictError):
            pass

    # -- reads ---------------------------------------------------------------

    def breakdown(self, namespace: str, name: str) -> Optional[ClaimProfile]:
        """The finished profile for a claim, or None if its consumer has
        not reached Running (or it aged out of the LRU)."""
        with self._mu:
            return self._profiles.get((namespace, name))

    def tracked_counts(self) -> Dict[str, int]:
        with self._mu:
            return {"claims": len(self._claims), "pods": len(self._pods),
                    "domains": len(self._domains),
                    "profiles": len(self._profiles)}

    def _trim_locked(self) -> None:
        for d in (self._claims, self._pods, self._pod_claims,
                  self._domains, self._profiles):
            while len(d) > MAX_TRACKED:
                d.pop(next(iter(d)))
