"""ICI mesh partitioner — the pkg/fabricmanager analog.

The reference programs NVSwitch partitions for passthrough device groups
(/root/reference/pkg/fabricmanager/manager.go:27-272) through a cgo client
with a stub for tests (client.go:87-103). TPU counterpart: legal ICI
subslice partitions of a host topology are computed (not queried), and
activation programs the partition through a client interface — real
implementations talk to the platform (via the C++ shim / libtpu), the stub
records calls for tests. Activate/Deactivate are idempotent, as the
reference's are.
"""

from __future__ import annotations

import ctypes
import json
import logging
import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Protocol, Tuple

from k8s_dra_driver_tpu.tpulib.profiles import compute_subslice_profiles
from k8s_dra_driver_tpu.tpulib.types import SubslicePlacement

log = logging.getLogger(__name__)


class PartitionError(Exception):
    pass


@dataclass(frozen=True)
class Partition:
    """A legal, activatable ICI partition: one subslice placement."""

    id: str                      # e.g. "1x2-at-0x0"
    profile: str
    chip_indices: Tuple[int, ...]


class PartitionClient(Protocol):
    def activate(self, partition: Partition) -> None: ...
    def deactivate(self, partition: Partition) -> None: ...


class StubPartitionClient:
    """Records calls; the test double (reference stubClient pattern)."""

    def __init__(self) -> None:
        self.active: Dict[str, Partition] = {}
        self.calls: List[Tuple[str, str]] = []

    def activate(self, partition: Partition) -> None:
        self.calls.append(("activate", partition.id))
        self.active[partition.id] = partition

    def deactivate(self, partition: Partition) -> None:
        self.calls.append(("deactivate", partition.id))
        self.active.pop(partition.id, None)

    def active_ids(self) -> List[str]:
        """Ledger read-back, like NativePartitionClient: a stub shared
        across driver instances models partition state surviving a plugin
        crash (crash-recovery tests restart against the same stub)."""
        return list(self.active)


_TPUPART_CANDIDATES = (
    os.environ.get("TPUPART_LIBRARY_PATH", ""),
    os.path.join(os.path.dirname(__file__), "..", "..", "native", "build",
                 "libtpupart.so"),
    "/usr/local/lib/libtpupart.so",
    "libtpupart.so",
)


def load_tpupart(path: Optional[str] = None) -> Optional["ctypes.CDLL"]:
    """dlopen the native partitioner at an explicit path, the way the
    reference binds libnvfm (client_nvfm.go:32-44). None when unavailable."""
    for cand in ((path,) if path else _TPUPART_CANDIDATES):
        if not cand:
            continue
        try:
            lib = ctypes.CDLL(
                os.path.abspath(cand) if os.path.sep in cand else cand
            )
        except OSError:
            continue
        lib.tpupart_version.restype = ctypes.c_char_p
        for fn in (lib.tpupart_supported, lib.tpupart_active):
            fn.restype = ctypes.c_int
            fn.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
        lib.tpupart_activate.restype = ctypes.c_int
        lib.tpupart_activate.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_int,
        ]
        lib.tpupart_deactivate.restype = ctypes.c_int
        lib.tpupart_deactivate.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
        ]
        return lib
    return None


class NativePartitionClient:
    """PartitionClient backed by the C++ tpupart library: partition legality
    is recomputed natively and the activation ledger lives on disk (flock'd,
    atomic-rename), so it survives plugin restarts and is shared across
    processes — the role the Fabric Manager service plays for the reference
    (pkg/fabricmanager/client_nvfm.go:46-135)."""

    def __init__(self, host_topology: str, state_path: str,
                 lib_path: Optional[str] = None):
        lib = load_tpupart(lib_path)
        if lib is None:
            raise PartitionError("libtpupart.so not found; build native/ first")
        self._lib = lib
        self._topology = host_topology.encode()
        self._state = state_path.encode()
        os.makedirs(os.path.dirname(state_path) or ".", exist_ok=True)

    def _call_json(self, fn, *args) -> dict:
        cap = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            rc = fn(*args, buf, cap)
            if rc >= 0:
                return json.loads(buf.value.decode())
            if rc == -1:
                try:
                    raise PartitionError(json.loads(buf.value.decode())["error"])
                except (ValueError, KeyError):
                    raise PartitionError("native partitioner error") from None
            cap = -rc  # buffer too small; need is -(rc)-1 + NUL

    def supported(self) -> List[Partition]:
        doc = self._call_json(self._lib.tpupart_supported, self._topology)
        return [
            Partition(id=p["id"], profile=p["profile"],
                      chip_indices=tuple(p["chips"]))
            for p in doc["partitions"]
        ]

    def activate(self, partition: Partition) -> None:
        err = ctypes.create_string_buffer(512)
        rc = self._lib.tpupart_activate(
            self._state, self._topology, partition.id.encode(), err, len(err)
        )
        if rc != 0:
            try:
                msg = json.loads(err.value.decode())["error"]
            except (ValueError, KeyError):
                msg = "activate failed"
            raise PartitionError(f"{partition.id}: {msg}")

    def deactivate(self, partition: Partition) -> None:
        err = ctypes.create_string_buffer(512)
        rc = self._lib.tpupart_deactivate(
            self._state, partition.id.encode(), err, len(err)
        )
        if rc != 0:
            raise PartitionError(f"{partition.id}: deactivate failed")

    def active_ids(self) -> List[str]:
        doc = self._call_json(self._lib.tpupart_active, self._state)
        return list(doc["active"])


class PartitionManager:
    """Caches supported partitions for a host topology; activates and
    deactivates idempotently; refuses overlapping activations (two active
    partitions may not share a chip)."""

    def __init__(self, host_topology: str, client: Optional[PartitionClient] = None):
        self.host_topology = host_topology
        self.client = client if client is not None else StubPartitionClient()
        self._mu = threading.Lock()
        self._active: Dict[str, Partition] = {}
        self._supported: Dict[str, Partition] = {}
        for prof in compute_subslice_profiles(host_topology):
            for pl in prof.placements:
                p = self._from_placement(pl)
                self._supported[p.id] = p
        # A client with a persistent ledger (NativePartitionClient) seeds the
        # active set across restarts, like the reference reading partition
        # state back from the FM service (manager.go:96-130).
        if hasattr(self.client, "active_ids"):
            for pid in self.client.active_ids():
                if pid in self._supported:
                    self._active[pid] = self._supported[pid]

    @staticmethod
    def _from_placement(pl: SubslicePlacement) -> Partition:
        return Partition(id=pl.name_suffix, profile=pl.profile,
                         chip_indices=tuple(pl.chip_indices))

    def supported_partitions(self) -> List[Partition]:
        return sorted(self._supported.values(), key=lambda p: p.id)

    def partition_for_chips(self, chips: Tuple[int, ...]) -> Optional[Partition]:
        want = tuple(sorted(chips))
        for p in self._supported.values():
            if tuple(sorted(p.chip_indices)) == want:
                return p
        return None

    def activate(self, partition_id: str) -> Partition:
        with self._mu:
            p = self._supported.get(partition_id)
            if p is None:
                raise PartitionError(
                    f"unsupported partition {partition_id!r} on {self.host_topology}"
                )
            if partition_id in self._active:
                return p  # idempotent
            overlapping = [
                a.id for a in self._active.values()
                if set(a.chip_indices) & set(p.chip_indices)
            ]
            if overlapping:
                raise PartitionError(
                    f"partition {partition_id} overlaps active {overlapping}"
                )
            self.client.activate(p)
            self._active[partition_id] = p
            return p

    def deactivate(self, partition_id: str) -> None:
        with self._mu:
            p = self._active.pop(partition_id, None)
            if p is None:
                return  # idempotent
            self.client.deactivate(p)

    def active_partitions(self) -> List[Partition]:
        with self._mu:
            return sorted(self._active.values(), key=lambda p: p.id)
