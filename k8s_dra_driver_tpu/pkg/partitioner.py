"""ICI mesh partitioner — the pkg/fabricmanager analog.

The reference programs NVSwitch partitions for passthrough device groups
(/root/reference/pkg/fabricmanager/manager.go:27-272) through a cgo client
with a stub for tests (client.go:87-103). TPU counterpart: legal ICI
subslice partitions of a host topology are computed (not queried), and
activation programs the partition through a client interface — real
implementations talk to the platform (via the C++ shim / libtpu), the stub
records calls for tests. Activate/Deactivate are idempotent, as the
reference's are.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Protocol, Tuple

from k8s_dra_driver_tpu.tpulib.profiles import compute_subslice_profiles
from k8s_dra_driver_tpu.tpulib.types import SubslicePlacement

log = logging.getLogger(__name__)


class PartitionError(Exception):
    pass


@dataclass(frozen=True)
class Partition:
    """A legal, activatable ICI partition: one subslice placement."""

    id: str                      # e.g. "1x2-at-0x0"
    profile: str
    chip_indices: Tuple[int, ...]


class PartitionClient(Protocol):
    def activate(self, partition: Partition) -> None: ...
    def deactivate(self, partition: Partition) -> None: ...


class StubPartitionClient:
    """Records calls; the test double (reference stubClient pattern)."""

    def __init__(self) -> None:
        self.active: Dict[str, Partition] = {}
        self.calls: List[Tuple[str, str]] = []

    def activate(self, partition: Partition) -> None:
        self.calls.append(("activate", partition.id))
        self.active[partition.id] = partition

    def deactivate(self, partition: Partition) -> None:
        self.calls.append(("deactivate", partition.id))
        self.active.pop(partition.id, None)


class PartitionManager:
    """Caches supported partitions for a host topology; activates and
    deactivates idempotently; refuses overlapping activations (two active
    partitions may not share a chip)."""

    def __init__(self, host_topology: str, client: Optional[PartitionClient] = None):
        self.host_topology = host_topology
        self.client = client if client is not None else StubPartitionClient()
        self._mu = threading.Lock()
        self._active: Dict[str, Partition] = {}
        self._supported: Dict[str, Partition] = {}
        for prof in compute_subslice_profiles(host_topology):
            for pl in prof.placements:
                p = self._from_placement(pl)
                self._supported[p.id] = p

    @staticmethod
    def _from_placement(pl: SubslicePlacement) -> Partition:
        return Partition(id=pl.name_suffix, profile=pl.profile,
                         chip_indices=tuple(pl.chip_indices))

    def supported_partitions(self) -> List[Partition]:
        return sorted(self._supported.values(), key=lambda p: p.id)

    def partition_for_chips(self, chips: Tuple[int, ...]) -> Optional[Partition]:
        want = tuple(sorted(chips))
        for p in self._supported.values():
            if tuple(sorted(p.chip_indices)) == want:
                return p
        return None

    def activate(self, partition_id: str) -> Partition:
        with self._mu:
            p = self._supported.get(partition_id)
            if p is None:
                raise PartitionError(
                    f"unsupported partition {partition_id!r} on {self.host_topology}"
                )
            if partition_id in self._active:
                return p  # idempotent
            overlapping = [
                a.id for a in self._active.values()
                if set(a.chip_indices) & set(p.chip_indices)
            ]
            if overlapping:
                raise PartitionError(
                    f"partition {partition_id} overlaps active {overlapping}"
                )
            self.client.activate(p)
            self._active[partition_id] = p
            return p

    def deactivate(self, partition_id: str) -> None:
        with self._mu:
            p = self._active.pop(partition_id, None)
            if p is None:
                return  # idempotent
            self.client.deactivate(p)

    def active_partitions(self) -> List[Partition]:
        with self._mu:
            return sorted(self._active.values(), key=lambda p: p.id)
