"""Fleet telemetry: bounded time series, window stats, and the rollup
aggregator joining per-chip samples to claims and ComputeDomains.

Three layers share this module:

- :class:`RingSeries` / :class:`WindowStats` — the node agent's bounded
  per-chip ring buffers (fixed-size arrays, last N samples, min/max/mean/
  p95 over the window; no unbounded growth anywhere).
- :class:`TelemetryAggregator` — the control-plane rollup: joins node
  samples against each node's prepared-claim → chip-set mapping into
  per-claim and per-ComputeDomain gauges (``tpu_dra_claim_*``,
  ``tpu_dra_domain_ici_utilization``) and writes quantized, change-gated
  :class:`~k8s_dra_driver_tpu.k8s.core.UtilizationSummary` docs onto
  ResourceClaim and ComputeDomain status. Gauge label sets are bounded:
  series key on claim *name*+namespace (never uids) and are LRU-bounded
  + forgotten when the claim stops being prepared, the same discipline
  the event correlator applies to its per-object state.
- ``parse_metrics_text`` — the mini exposition parser ``tpu-kubectl top
  nodes`` uses to read per-chip gauges off a /metrics scrape (the same
  grammar the scrape-parser test fixture pins).

The aggregator issues ZERO store ``list()`` calls per rollup pass: claim
targets come from the node views (checkpoint mirrors), domain membership
rides a watch-fed cache bootstrapped once at construction — the
``bench_telemetry`` 1024-node gate pins that invariant.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field, replace
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from k8s_dra_driver_tpu.k8s.core import (
    COMPUTE_DOMAIN,
    RESOURCE_CLAIM,
    UtilizationSummary,
)
from k8s_dra_driver_tpu.k8s.objects import ConflictError, NotFoundError
from k8s_dra_driver_tpu.tpulib.loadtrace import percentile

# Defaults. 120 samples at the sim's 1 s virtual tick = a 2-minute window;
# a real node at 10 s intervals sees 20 minutes.
DEFAULT_WINDOW_SAMPLES = 120
# Quantization steps: steady load must round to the SAME summary pass
# after pass, so status writes (and watch fan-out) happen only on real
# movement. Duty/ICI in 1% steps, HBM in 64 MiB steps.
DUTY_QUANTUM = 0.01
HBM_QUANTUM_BYTES = 64 << 20
# Aggregator keeps per-claim/domain gauge + change-gate state for at most
# this many objects (LRU evict beyond it, like the event correlator).
MAX_TRACKED_OBJECTS = 4096
# Flight-recorder feed gate: a history sample lands only when the value
# moved at least this much (ratio series: duty/ICI; HBM gates on the
# same relative step) or the keepalive elapsed — a steady series costs
# one dict probe per rollup pass, the same quantized-change discipline
# that keeps steady status writes at zero.
HISTORY_QUANTUM = 0.005
HISTORY_KEEPALIVE_S = 300.0


@dataclass(frozen=True)
class WindowStats:
    """Summary statistics over one ring window."""

    count: int = 0
    last: float = 0.0
    min: float = 0.0
    max: float = 0.0
    mean: float = 0.0
    p95: float = 0.0
    span_seconds: float = 0.0   # newest sample time - oldest

    def as_dict(self) -> Dict[str, float]:
        return {"count": self.count, "last": self.last, "min": self.min,
                "max": self.max, "mean": self.mean, "p95": self.p95,
                "span_seconds": self.span_seconds}

    @staticmethod
    def from_dict(doc: Dict[str, float]) -> "WindowStats":
        return WindowStats(
            count=int(doc.get("count", 0)), last=float(doc.get("last", 0.0)),
            min=float(doc.get("min", 0.0)), max=float(doc.get("max", 0.0)),
            mean=float(doc.get("mean", 0.0)), p95=float(doc.get("p95", 0.0)),
            span_seconds=float(doc.get("span_seconds", 0.0)))


class RingSeries:
    """Fixed-capacity (time, value) ring. Push is O(1); ``stats()`` walks
    only the ring (bounded) with the running sum kept streaming so the
    mean never rescans. NOT thread-safe — owners serialize access under
    their own telemetry lock (the sampler's contract: that lock is never
    one the prepare paths hold)."""

    __slots__ = ("cap", "_times", "_values", "_n", "_idx", "_sum")

    def __init__(self, cap: int = DEFAULT_WINDOW_SAMPLES):
        if cap <= 0:
            raise ValueError("ring capacity must be positive")
        self.cap = cap
        self._times = [0.0] * cap
        self._values = [0.0] * cap
        self._n = 0
        self._idx = 0
        self._sum = 0.0

    def push(self, t: float, v: float) -> None:
        if self._n == self.cap:
            self._sum -= self._values[self._idx]
        else:
            self._n += 1
        self._times[self._idx] = t
        self._values[self._idx] = float(v)
        self._sum += float(v)
        self._idx = (self._idx + 1) % self.cap

    def __len__(self) -> int:
        return self._n

    def values(self) -> List[float]:
        """Window contents, oldest first."""
        if self._n < self.cap:
            return self._values[:self._n]
        return self._values[self._idx:] + self._values[:self._idx]

    def times(self) -> List[float]:
        if self._n < self.cap:
            return self._times[:self._n]
        return self._times[self._idx:] + self._times[:self._idx]

    def stats(self) -> WindowStats:
        if self._n == 0:
            return WindowStats()
        vals = self.values()
        ts = self.times()
        return WindowStats(
            count=self._n, last=vals[-1], min=min(vals), max=max(vals),
            mean=self._sum / self._n, p95=percentile(vals, 0.95),
            span_seconds=max(0.0, ts[-1] - ts[0]))


# -- quantization -------------------------------------------------------------


def quantize_summary(s: UtilizationSummary,
                     duty_quantum: float = DUTY_QUANTUM,
                     hbm_quantum: int = HBM_QUANTUM_BYTES) -> UtilizationSummary:
    """Round a summary onto the write grid: two summaries of the same
    steady load are equal after quantization, so the change gate holds
    status writes at zero."""
    def qf(v: float, q: float) -> float:
        return round(round(v / q) * q, 6)

    return replace(
        s,
        # window_seconds/samples are display metadata, excluded from the
        # dataclass equality the change gate compares (they grow every
        # tick while the ring fills); rounded here only so the doc the
        # gate DOES write carries stable-looking values.
        window_seconds=float(int(round(s.window_seconds))),
        duty_cycle_p95=qf(s.duty_cycle_p95, duty_quantum),
        ici_utilization_p95=qf(s.ici_utilization_p95, duty_quantum),
        hbm_used_p95_bytes=int(round(s.hbm_used_p95_bytes / hbm_quantum))
        * hbm_quantum,
    )


# -- node views ---------------------------------------------------------------


@dataclass
class ClaimChips:
    """One prepared claim on one node: the join key the rollup uses."""

    uid: str
    name: str
    namespace: str
    chips: Tuple[int, ...]


@dataclass
class NodeView:
    """Everything the aggregator needs from one node for one pass: the
    monitor's window stats (per chip, per signal) and the prepared-claim
    → chip-set mapping. Built from in-memory snapshots — never from store
    scans or the checkpoint flock."""

    node: str
    duty: Dict[int, WindowStats] = field(default_factory=dict)
    hbm_used: Dict[int, WindowStats] = field(default_factory=dict)
    hbm_total: Dict[int, int] = field(default_factory=dict)
    link_util: WindowStats = field(default_factory=WindowStats)
    claims: List[ClaimChips] = field(default_factory=list)


def _mean(vals: Iterable[float]) -> float:
    vals = list(vals)
    return sum(vals) / len(vals) if vals else 0.0


@dataclass
class RollupResult:
    claims_seen: int = 0
    domains_seen: int = 0
    status_writes: int = 0
    duration_s: float = 0.0


class TelemetryAggregator:
    """Per-claim / per-ComputeDomain rollup over node telemetry views.

    ``rollup(now, views)`` is one aggregation pass; call it from the sim's
    telemetry pass or a controller loop. Claim and domain gauges key on
    (namespace, name) — bounded vocabularies — and are forgotten as soon
    as the object leaves the prepared set (plus an LRU cap as backstop)."""

    def __init__(self, api, metrics_registry,
                 max_tracked: int = MAX_TRACKED_OBJECTS,
                 duty_quantum: float = DUTY_QUANTUM,
                 hbm_quantum: int = HBM_QUANTUM_BYTES):
        from k8s_dra_driver_tpu.pkg.metrics import Gauge

        self.api = api
        self.duty_quantum = duty_quantum
        self.hbm_quantum = hbm_quantum
        self.max_tracked = max_tracked
        r = metrics_registry
        self.claim_hbm = r.register(Gauge(
            "tpu_dra_claim_hbm_used_bytes",
            "HBM bytes in use across a prepared claim's chips.",
            ("namespace", "name")))
        self.claim_duty = r.register(Gauge(
            "tpu_dra_claim_duty_cycle",
            "Mean compute duty cycle across a prepared claim's chips (0-1).",
            ("namespace", "name")))
        self.domain_ici = r.register(Gauge(
            "tpu_dra_domain_ici_utilization",
            "Mean ICI link utilization across a ComputeDomain's member "
            "hosts (0-1).",
            ("namespace", "name")))
        self.rollup_seconds = r.register(Gauge(
            "tpu_dra_telemetry_rollup_seconds",
            "Wall time of the last telemetry aggregation pass."))
        self.rollup_status_writes = r.register(Gauge(
            "tpu_dra_telemetry_status_writes",
            "Status CAS writes issued by the last rollup pass (change-"
            "gated: 0 at steady load)."))
        # Change gates: (ns, name) -> last quantized summary written (or
        # observed on the object), LRU-ordered dicts bounded at max_tracked.
        self._written_claims: Dict[Tuple[str, str], UtilizationSummary] = {}
        self._written_domains: Dict[Tuple[str, str], UtilizationSummary] = {}
        # Watch-fed domain membership cache: (ns, name) -> member node
        # names. One bootstrap listing at construction; after that, only
        # watch events mutate it — rollup passes never list().
        self._domains: Dict[Tuple[str, str], Tuple[str, ...]] = {}
        self._domain_watch = api.watch(COMPUTE_DOMAIN, maxsize=65536)
        for cd in api.list(COMPUTE_DOMAIN):
            self._ingest_domain("ADDED", cd)
        self.total_status_writes = 0  # lifetime counter, bench/test hook
        # Optional flight-recorder sink (pkg/history.py HistoryStore):
        # when set, every rollup pass also pushes node/claim/domain
        # series into the multi-resolution history tiers — the series
        # `tpu-kubectl explain` sparklines and `top --history` read.
        self.history = None
        # Recorder change gates (HISTORY_QUANTUM / HISTORY_KEEPALIVE_S):
        # node -> (duty, t); claim uid -> (duty, hbm, t); domain key ->
        # (ici, t). Probed inline on the rollup hot path — no helper
        # call, no series-string build on the skip path.
        self._hist_node: Dict[str, Tuple[float, float]] = {}
        self._hist_claim: Dict[str, Tuple[float, float, float]] = {}
        self._hist_domain: Dict[Tuple[str, str], Tuple[float, float]] = {}

    def close(self) -> None:
        self.api.stop_watch(COMPUTE_DOMAIN, self._domain_watch)

    def claim_summaries(self) -> Dict[Tuple[str, str], UtilizationSummary]:
        """(namespace, name) -> last quantized summary per tracked claim —
        what the SLO recording rules consume each pass."""
        return dict(self._written_claims)

    def domain_summaries(self) -> Dict[Tuple[str, str], UtilizationSummary]:
        return dict(self._written_domains)

    # -- domain cache --------------------------------------------------------

    def _ingest_domain(self, ev_type: str, cd) -> None:
        key = (cd.meta.namespace, cd.meta.name)
        if ev_type == "DELETED":
            self._domains.pop(key, None)
            self._written_domains.pop(key, None)
            self.domain_ici.forget_matching(namespace=key[0], name=key[1])
            return
        placement = getattr(cd.status, "placement", None)
        if placement is not None and placement.nodes:
            members = tuple(placement.nodes)
        else:
            members = tuple(n.name for n in cd.status.nodes)
        self._domains[key] = members

    def _drain_domain_watch(self) -> None:
        import queue as _q

        while True:
            try:
                ev = self._domain_watch.get_nowait()
            except _q.Empty:
                return
            self._ingest_domain(ev.type, ev.obj)

    # -- rollup --------------------------------------------------------------

    def rollup(self, now: float, views: List[NodeView]) -> RollupResult:
        t0 = time.perf_counter()
        self._drain_domain_watch()
        res = RollupResult()
        by_node = {v.node: v for v in views}

        # Per-claim rollup: a claim's chips live on exactly one node.
        # Recorder locals hoisted out of the loop: the change-gated skip
        # path (steady load) must cost one dict probe per series, not an
        # attribute walk + method call per view.
        hist = self.history
        hq, hka = HISTORY_QUANTUM, HISTORY_KEEPALIVE_S
        hist_node, hist_claim = self._hist_node, self._hist_claim
        hist_node_get, hist_claim_get = hist_node.get, hist_claim.get
        seen_claims = set()
        for view in views:
            if hist is not None and view.duty:
                dvals = view.duty
                d = 0.0
                for s in dvals.values():
                    d += s.last
                d /= len(dvals)
                g = hist_node_get(view.node)
                if (g is None or d - g[0] >= hq or g[0] - d >= hq
                        or now - g[1] >= hka):
                    hist_node[view.node] = (d, now)
                    hist.push(f"node-duty/{view.node}", now, d)
            for cc in view.claims:
                key = (cc.namespace, cc.name)
                duty = [view.duty[i] for i in cc.chips if i in view.duty]
                hbm = [view.hbm_used[i] for i in cc.chips if i in view.hbm_used]
                if not duty or not hbm:
                    continue  # no telemetry yet for these chips
                seen_claims.add(key)
                res.claims_seen += 1
                duty_mean = _mean(s.last for s in duty)
                hbm_last = sum(s.last for s in hbm)
                self.claim_duty.set(cc.namespace, cc.name, value=duty_mean)
                self.claim_hbm.set(cc.namespace, cc.name, value=hbm_last)
                if hist is not None:
                    # Gate tuple: (duty, hbm, hbm tolerance, t) — the
                    # relative-step tolerance is precomputed at push so
                    # the skip path is compares only.
                    g = hist_claim_get(cc.uid)
                    if (g is None
                            or duty_mean - g[0] >= hq
                            or g[0] - duty_mean >= hq
                            or hbm_last - g[1] >= g[2]
                            or g[1] - hbm_last >= g[2]
                            or now - g[3] >= hka):
                        hist_claim[cc.uid] = (
                            duty_mean, hbm_last, hq * (hbm_last or 1.0), now)
                        hist.push(f"claim-duty/{cc.namespace}/{cc.name}",
                                  now, duty_mean)
                        hist.push(f"claim-hbm/{cc.namespace}/{cc.name}",
                                  now, hbm_last)
                summary = UtilizationSummary(
                    window_seconds=_mean(s.span_seconds for s in duty),
                    samples=min(s.count for s in duty),
                    duty_cycle_p95=_mean(s.p95 for s in duty),
                    hbm_used_p95_bytes=int(sum(s.p95 for s in hbm)),
                    hbm_total_bytes=sum(
                        view.hbm_total.get(i, 0) for i in cc.chips),
                    updated_at=now,
                )
                res.status_writes += self._write_claim(key, summary)

        # Per-domain rollup over member hosts present in this pass's views.
        seen_domains = set()
        for key, members in self._domains.items():
            mviews = [by_node[m] for m in members if m in by_node]
            if not mviews:
                continue
            all_duty = [s for v in mviews for s in v.duty.values()]
            if not all_duty or all(s.count == 0 for s in all_duty):
                continue
            seen_domains.add(key)
            res.domains_seen += 1
            ici_last = _mean(v.link_util.last for v in mviews)
            self.domain_ici.set(key[0], key[1], value=ici_last)
            if hist is not None:
                g = self._hist_domain.get(key)
                if (g is None or ici_last - g[0] >= hq
                        or g[0] - ici_last >= hq or now - g[1] >= hka):
                    self._hist_domain[key] = (ici_last, now)
                    hist.push(f"domain-ici/{key[0]}/{key[1]}", now, ici_last)
            summary = UtilizationSummary(
                window_seconds=_mean(s.span_seconds for s in all_duty),
                samples=min(s.count for s in all_duty),
                duty_cycle_p95=_mean(s.p95 for s in all_duty),
                hbm_used_p95_bytes=int(sum(
                    s.p95 for v in mviews for s in v.hbm_used.values())),
                hbm_total_bytes=sum(
                    t for v in mviews for t in v.hbm_total.values()),
                ici_utilization_p95=_mean(v.link_util.p95 for v in mviews),
                updated_at=now,
            )
            res.status_writes += self._write_domain(key, summary)

        self._forget_stale(self._written_claims, seen_claims,
                           (self.claim_duty, self.claim_hbm))
        self._lru_trim(self._written_claims)
        self._lru_trim(self._written_domains)
        # Recorder gate dicts shadow live objects only; nuke-and-repush
        # (one extra sample per series) beats per-entry LRU bookkeeping
        # on the hot path.
        for gate in (self._hist_node, self._hist_claim, self._hist_domain):
            if len(gate) > 4 * self.max_tracked:
                gate.clear()
        res.duration_s = time.perf_counter() - t0
        self.rollup_seconds.set(value=res.duration_s)
        self.rollup_status_writes.set(value=float(res.status_writes))
        self.total_status_writes += res.status_writes
        return res

    # -- write paths ---------------------------------------------------------

    def _write_claim(self, key: Tuple[str, str],
                     summary: UtilizationSummary) -> int:
        q = quantize_summary(summary, self.duty_quantum, self.hbm_quantum)
        prev = self._written_claims.get(key)
        if prev is not None:
            # LRU touch.
            self._written_claims.pop(key, None)
        self._written_claims[key] = q
        if prev == q:
            return 0

        def mutate(obj, s=q):
            obj.utilization = s

        try:
            self.api.update_with_retry(RESOURCE_CLAIM, key[1], key[0], mutate)
        except (NotFoundError, ConflictError):
            self._written_claims.pop(key, None)
            return 0
        return 1

    def _write_domain(self, key: Tuple[str, str],
                      summary: UtilizationSummary) -> int:
        q = quantize_summary(summary, self.duty_quantum, self.hbm_quantum)
        prev = self._written_domains.get(key)
        if prev is not None:
            self._written_domains.pop(key, None)
        self._written_domains[key] = q
        if prev == q:
            return 0

        def mutate(obj, s=q):
            obj.status.utilization = s

        try:
            self.api.update_with_retry(COMPUTE_DOMAIN, key[1], key[0], mutate)
        except (NotFoundError, ConflictError):
            self._written_domains.pop(key, None)
            return 0
        return 1

    def _forget_stale(self, written: Dict, seen: set, gauges) -> None:
        for key in [k for k in written if k not in seen]:
            written.pop(key, None)
            for g in gauges:
                g.forget_matching(namespace=key[0], name=key[1])

    def _lru_trim(self, written: Dict) -> None:
        while len(written) > self.max_tracked:
            written.pop(next(iter(written)))


# -- exposition parsing (tpu-kubectl top nodes) -------------------------------


def parse_metrics_text(text: str) -> Dict[str, Dict[Tuple[Tuple[str, str], ...], float]]:
    """Parse Prometheus text exposition into
    ``{metric: {((label, value), ...): sample}}`` — the subset the mini
    scrape-parser fixture pins (HELP/TYPE skipped, escaped label values
    unescaped)."""
    out: Dict[str, Dict[Tuple[Tuple[str, str], ...], float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if "{" in line:
            name, _, rest = line.partition("{")
            labels_raw, _, value_raw = rest.rpartition("}")
            labels = tuple(sorted(_parse_labels(labels_raw)))
        else:
            name, _, value_raw = line.partition(" ")
            labels = ()
        try:
            value = float(value_raw.strip().split()[0])
        except (ValueError, IndexError):
            continue
        out.setdefault(name.strip(), {})[labels] = value
    return out


def _parse_labels(raw: str) -> List[Tuple[str, str]]:
    labels: List[Tuple[str, str]] = []
    i, n = 0, len(raw)
    while i < n:
        eq = raw.find("=", i)
        if eq < 0:
            break
        key = raw[i:eq].strip().lstrip(",").strip()
        j = eq + 1
        if j >= n or raw[j] != '"':
            break
        j += 1
        buf = []
        while j < n:
            c = raw[j]
            if c == "\\" and j + 1 < n:
                nxt = raw[j + 1]
                buf.append("\n" if nxt == "n" else nxt)
                j += 2
                continue
            if c == '"':
                break
            buf.append(c)
            j += 1
        labels.append((key, "".join(buf)))
        i = j + 1
    return labels
