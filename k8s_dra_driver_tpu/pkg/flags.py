"""Composable CLI flag bundles with environment-variable mirrors.

The reference builds every binary's CLI from shared urfave/cli bundles where
each flag also reads an env var (/root/reference/pkg/flags/,
cmd/gpu-kubelet-plugin/main.go:94-214). Python analog over argparse: each
bundle contributes flags whose defaults resolve from the environment, so
container deployments configure via env and humans via flags.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

from k8s_dra_driver_tpu.pkg import featuregates as fg


def _env_default(env: str, default: Any, cast=str):
    raw = os.environ.get(env)
    if raw is None:
        return default
    try:
        if cast is bool:
            return raw.lower() in ("1", "true", "yes")
        return cast(raw)
    except ValueError:
        return default


class FlagBundle:
    def add_to(self, parser: argparse.ArgumentParser) -> None:
        raise NotImplementedError


@dataclass
class KubeClientFlags(FlagBundle):
    """--kubeconfig / --kube-context / --kube-api-qps / --kube-api-burst."""

    def add_to(self, parser: argparse.ArgumentParser) -> None:
        g = parser.add_argument_group("kubernetes client")
        g.add_argument("--kubeconfig", default=_env_default("KUBECONFIG", ""),
                       help="path to kubeconfig (in-cluster when empty) [KUBECONFIG]")
        g.add_argument("--kube-context", default=_env_default("KUBE_CONTEXT", ""),
                       help="kubeconfig context override [KUBE_CONTEXT]")
        g.add_argument("--kube-api-qps", type=float,
                       default=_env_default("KUBE_API_QPS", 5.0, float),
                       help="client QPS [KUBE_API_QPS]")
        g.add_argument("--kube-api-burst", type=int,
                       default=_env_default("KUBE_API_BURST", 10, int),
                       help="client burst [KUBE_API_BURST]")


class _JSONFormatter(logging.Formatter):
    """One JSON object per line (the component-base logsapi JSON option).
    Records logged under an active tracing span carry its trace_id /
    span_id (stamped by TraceContextFilter), so structured logs and
    /debug/traces spans correlate on one id."""

    def format(self, record: logging.LogRecord) -> str:
        doc = {
            "ts": self.formatTime(record),
            "lvl": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        trace_id = getattr(record, "trace_id", "")
        if trace_id:
            doc["trace_id"] = trace_id
            doc["span_id"] = getattr(record, "span_id", "")
        return json.dumps(doc)


@dataclass
class LoggingFlags(FlagBundle):
    """-v verbosity + --log-json (LOG_VERBOSITY, LOG_JSON)."""

    def add_to(self, parser: argparse.ArgumentParser) -> None:
        g = parser.add_argument_group("logging")
        g.add_argument("-v", "--verbosity", type=int,
                       default=_env_default("LOG_VERBOSITY", 0, int),
                       help="log verbosity (0=info, >=6 debug timings) [LOG_VERBOSITY]")
        g.add_argument("--log-json", action=argparse.BooleanOptionalAction,
                       default=_env_default("LOG_JSON", False, bool),
                       help="JSON log lines [LOG_JSON]")

    @staticmethod
    def configure(args: argparse.Namespace) -> None:
        from k8s_dra_driver_tpu.pkg.tracing import TraceContextFilter

        level = logging.DEBUG if args.verbosity >= 6 else logging.INFO
        handler = logging.StreamHandler()
        handler.addFilter(TraceContextFilter())
        if args.log_json:
            handler.setFormatter(_JSONFormatter())
        else:
            handler.setFormatter(logging.Formatter(
                "%(asctime)s %(levelname)s %(name)s: %(message)s"))
        logging.basicConfig(level=level, handlers=[handler])


@dataclass
class FeatureGateFlags(FlagBundle):
    """--feature-gates (FEATURE_GATES), validated with dependencies."""

    def add_to(self, parser: argparse.ArgumentParser) -> None:
        parser.add_argument(
            "--feature-gates",
            default=_env_default(fg.ENV_VAR, ""),
            help=f"Gate=bool,... known: {', '.join(fg.known_features())} [{fg.ENV_VAR}]",
        )

    @staticmethod
    def resolve(args: argparse.Namespace, exit_on_error: bool = False) -> fg.FeatureGates:
        try:
            gates = fg.parse(args.feature_gates)
            gates.validate()
        except fg.FeatureGateError as e:
            if exit_on_error:
                raise SystemExit(f"error: --feature-gates: {e}") from None
            raise
        return gates


@dataclass
class SliceConfigFlags(FlagBundle):
    """--slice-agent-mode / --slice-agent-isolation (pkg/sliceconfig — the
    reference's pkg/imex Mode/Isolation flag surface)."""

    def add_to(self, parser: argparse.ArgumentParser) -> None:
        g = parser.add_argument_group("slice agent deployment")
        g.add_argument("--slice-agent-mode",
                       choices=("driverManaged", "hostManaged"),
                       default=_env_default("SLICE_AGENT_MODE", "driverManaged"),
                       help="who runs slice agents: this driver's DaemonSet "
                            "or the node image [SLICE_AGENT_MODE]")
        g.add_argument("--slice-agent-isolation", choices=("domain", "channel"),
                       default=_env_default("SLICE_AGENT_ISOLATION", "domain"),
                       help="workload isolation granularity "
                            "[SLICE_AGENT_ISOLATION]")

    @staticmethod
    def resolve(args: argparse.Namespace, gates: "fg.FeatureGates",
                exit_on_error: bool = False):
        from k8s_dra_driver_tpu.pkg.sliceconfig import (
            SliceAgentConfig,
            SliceConfigError,
        )

        try:
            cfg = SliceAgentConfig.parse(
                args.slice_agent_mode, args.slice_agent_isolation
            )
            cfg.validate(gates)
        except SliceConfigError as e:
            if exit_on_error:
                raise SystemExit(f"error: slice-agent config: {e}") from None
            raise
        return cfg


@dataclass
class LeaderElectionFlags(FlagBundle):
    def add_to(self, parser: argparse.ArgumentParser) -> None:
        g = parser.add_argument_group("leader election")
        g.add_argument("--leader-elect", action=argparse.BooleanOptionalAction,
                       default=_env_default("LEADER_ELECT", False, bool),
                       help="enable leader election [LEADER_ELECT]")
        g.add_argument("--leader-elect-lease-duration", type=float,
                       default=_env_default("LEADER_ELECT_LEASE_DURATION", 15.0, float))


@dataclass
class PluginFlags(FlagBundle):
    """Node-plugin common flags: node name, plugin dir, CDI root, metrics."""

    def add_to(self, parser: argparse.ArgumentParser) -> None:
        g = parser.add_argument_group("plugin")
        g.add_argument("--node-name", default=_env_default("NODE_NAME", ""),
                       help="this node's name [NODE_NAME]")
        g.add_argument("--plugin-dir",
                       default=_env_default("PLUGIN_DIR",
                                            "/var/lib/kubelet/plugins/tpu.google.com"),
                       help="checkpoint/lock dir [PLUGIN_DIR]")
        g.add_argument("--cdi-root", default=_env_default("CDI_ROOT", "/var/run/cdi"),
                       help="CDI spec dir [CDI_ROOT]")
        g.add_argument("--metrics-port", type=int,
                       default=_env_default("METRICS_PORT", 0, int),
                       help="serve /metrics on this port; 0 disables [METRICS_PORT]")
        g.add_argument("--healthcheck-port", type=int,
                       default=_env_default("HEALTHCHECK_PORT", -1, int),
                       help="serve /healthz on this port; negative disables "
                            "[HEALTHCHECK_PORT] (reference health.go:52-55)")
        g.add_argument("--pprof-path",
                       default=_env_default("PPROF_PATH", "", str),
                       help="serve thread-stack/runtime-stat debug endpoints "
                            "under this path on the metrics port (reference "
                            "--pprof-path); /debug/traces is always served; "
                            "empty disables stacks/vars [PPROF_PATH]")


def build_parser(prog: str, description: str, bundles: Sequence[FlagBundle]) -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog=prog, description=description)
    for b in bundles:
        b.add_to(parser)
    return parser


def log_startup_config(args: argparse.Namespace, log: logging.Logger) -> None:
    """Dump the resolved config at startup (LogStartupConfig analog)."""
    for k, v in sorted(vars(args).items()):
        log.info("config %s=%r", k, v)
