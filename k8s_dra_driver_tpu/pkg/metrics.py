"""Prometheus-style metrics: DRA request instrumentation + ComputeDomain status.

A minimal dependency-free registry (Counter/Gauge/Histogram with labels,
text exposition format, threaded HTTP server) carrying the reference's metric
surface (/root/reference/pkg/metrics/dra_requests.go:27-151,
computedomain_cluster.go:26-94, prometheus_httpserver.go:37-75), renamed to
the ``tpu_dra_*`` namespace:

- ``tpu_dra_requests_total{driver,method}``
- ``tpu_dra_request_duration_seconds{driver,method}`` — exponential buckets
  0.05s * 2^k, k=0..8 (the designed-for prepare-latency envelope)
- ``tpu_dra_requests_in_flight{driver}``
- ``tpu_dra_prepared_devices{driver,device_type}``
- ``tpu_dra_request_errors_total{driver,method}``
- ``tpu_dra_compute_domain_status{namespace,name,status}`` — state-exclusive
  labels with explicit Forget on deletion
"""

from __future__ import annotations

import http.server
import threading
import time
from bisect import bisect_left
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

LabelValues = Tuple[str, ...]


def _escape_label_value(v: str) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote, and newline must be escaped or the scrape line is invalid
    (ComputeDomain names and error strings can carry any of them)."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _escape_help(text: str) -> str:
    """HELP text escaping per the exposition format: backslash + newline."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(names: Sequence[str], values: LabelValues, extra: str = "") -> str:
    pairs = [f'{n}="{_escape_label_value(v)}"' for n, v in zip(names, values)]
    if extra:
        pairs.append(extra)
    return ("{" + ",".join(pairs) + "}") if pairs else ""


class _Metric:
    def __init__(self, name: str, help_: str, label_names: Sequence[str]):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._mu = threading.Lock()

    def collect(self) -> List[str]:
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help_: str, label_names: Sequence[str] = ()):
        super().__init__(name, help_, label_names)
        self._values: Dict[LabelValues, float] = {}

    def inc(self, *labels: str, by: float = 1.0) -> None:
        if len(labels) != len(self.label_names):
            raise ValueError(f"{self.name}: want {len(self.label_names)} labels, got {labels}")
        with self._mu:
            self._values[labels] = self._values.get(labels, 0.0) + by

    def value(self, *labels: str) -> float:
        with self._mu:
            return self._values.get(labels, 0.0)

    def collect(self) -> List[str]:
        out = [f"# HELP {self.name} {_escape_help(self.help)}",
               f"# TYPE {self.name} {self.kind}"]
        with self._mu:
            for labels, v in sorted(self._values.items()):
                out.append(f"{self.name}{_fmt_labels(self.label_names, labels)} {v}")
        return out


class Gauge(Counter):
    kind = "gauge"

    def set(self, *labels: str, value: float) -> None:
        with self._mu:
            self._values[labels] = value

    def dec(self, *labels: str, by: float = 1.0) -> None:
        self.inc(*labels, by=-by)

    def forget(self, *labels: str) -> None:
        """Drop a label series entirely (the reference's Forget-on-deletion)."""
        with self._mu:
            self._values.pop(labels, None)

    def forget_matching(self, **fixed: str) -> None:
        """Drop every series whose named labels match ``fixed``."""
        idx = {n: i for i, n in enumerate(self.label_names)}
        with self._mu:
            doomed = [
                lv
                for lv in self._values
                if all(lv[idx[n]] == v for n, v in fixed.items())
            ]
            for lv in doomed:
                del self._values[lv]


# The reference's bucket envelope: 0.05s * 2^k for k=0..8 (0.05s .. 12.8s).
DRA_DURATION_BUCKETS: Tuple[float, ...] = tuple(0.05 * (2**k) for k in range(9))

# Batch-size envelope for the batched prepare path: 2^k claims per
# NodePrepareResources call, k=0..8 (1 .. 256) — same exponential shape as
# the duration buckets so both histograms read on one grid.
PREPARE_BATCH_SIZE_BUCKETS: Tuple[float, ...] = tuple(float(2**k) for k in range(9))

# Sub-second envelope for the replication/federation hot paths (WAL
# record apply latency, cross-cluster placement): 0.5ms * 2^k for
# k=0..10 (0.5ms .. 512ms) — the DRA envelope starts at 50ms and would
# fold the entire replication budget into its first bucket.
REPLICATION_LATENCY_BUCKETS: Tuple[float, ...] = tuple(
    0.0005 * (2**k) for k in range(11))


class Histogram(_Metric):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        help_: str,
        label_names: Sequence[str] = (),
        buckets: Sequence[float] = DRA_DURATION_BUCKETS,
    ):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[LabelValues, List[int]] = {}
        self._sums: Dict[LabelValues, float] = {}
        self._totals: Dict[LabelValues, int] = {}

    def observe(self, *labels: str, value: float) -> None:
        if len(labels) != len(self.label_names):
            raise ValueError(f"{self.name}: want {len(self.label_names)} labels, got {labels}")
        with self._mu:
            counts = self._counts.setdefault(labels, [0] * len(self.buckets))
            i = bisect_left(self.buckets, value)
            if i < len(counts):
                counts[i] += 1
            self._sums[labels] = self._sums.get(labels, 0.0) + value
            self._totals[labels] = self._totals.get(labels, 0) + 1

    @contextmanager
    def time(self, *labels: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(*labels, value=time.perf_counter() - t0)

    def count(self, *labels: str) -> int:
        with self._mu:
            return self._totals.get(labels, 0)

    def collect(self) -> List[str]:
        out = [f"# HELP {self.name} {_escape_help(self.help)}",
               f"# TYPE {self.name} {self.kind}"]
        with self._mu:
            for labels in sorted(self._counts):
                cum = 0
                for ub, c in zip(self.buckets, self._counts[labels]):
                    cum += c
                    le = 'le="%s"' % ub
                    out.append(
                        f"{self.name}_bucket"
                        f"{_fmt_labels(self.label_names, labels, le)} {cum}"
                    )
                le_inf = 'le="+Inf"'
                out.append(
                    f"{self.name}_bucket"
                    f"{_fmt_labels(self.label_names, labels, le_inf)} {self._totals[labels]}"
                )
                out.append(f"{self.name}_sum{_fmt_labels(self.label_names, labels)} {self._sums[labels]}")
                out.append(f"{self.name}_count{_fmt_labels(self.label_names, labels)} {self._totals[labels]}")
        return out


class Registry:
    def __init__(self) -> None:
        self._metrics: Dict[str, _Metric] = {}  # tpulint: guarded-by=_mu
        self._mu = threading.Lock()

    def register(self, metric: _Metric) -> _Metric:
        """Register, or return the existing metric of the same name/shape.

        Get-or-create so several plugin bundles (tpu + computedomain) can
        share one registry — series stay distinct via the `driver` label.
        """
        with self._mu:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                if (
                    type(existing) is not type(metric)
                    or existing.label_names != metric.label_names
                    or getattr(existing, "buckets", None) != getattr(metric, "buckets", None)
                ):
                    raise ValueError(
                        f"metric {metric.name} already registered with a different shape"
                    )
                return existing
            self._metrics[metric.name] = metric
        return metric

    def expose(self) -> str:
        with self._mu:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.collect())
        return "\n".join(lines) + "\n"


@dataclass
class DRARequestMetrics:
    """The per-plugin DRA request instrumentation bundle."""

    driver: str
    registry: Registry
    requests_total: Counter = field(init=False)
    request_errors_total: Counter = field(init=False)
    request_duration: Histogram = field(init=False)
    in_flight: Gauge = field(init=False)
    prepared_devices: Gauge = field(init=False)
    prepare_batch_size: Histogram = field(init=False)
    prepare_seconds: Histogram = field(init=False)

    def __post_init__(self) -> None:
        r = self.registry
        self.requests_total = r.register(
            Counter("tpu_dra_requests_total", "DRA requests served.", ("driver", "method"))
        )
        self.request_errors_total = r.register(
            Counter("tpu_dra_request_errors_total", "DRA requests that failed.", ("driver", "method"))
        )
        self.request_duration = r.register(
            Histogram(
                "tpu_dra_request_duration_seconds",
                "DRA request latency.",
                ("driver", "method"),
            )
        )
        self.in_flight = r.register(
            Gauge("tpu_dra_requests_in_flight", "DRA requests currently in flight.", ("driver",))
        )
        self.prepared_devices = r.register(
            Gauge(
                "tpu_dra_prepared_devices",
                "Devices currently prepared, by type.",
                ("driver", "device_type"),
            )
        )
        self.prepare_batch_size = r.register(
            Histogram(
                "tpu_dra_prepare_batch_size",
                "Claims per batched prepare/unprepare call.",
                ("driver", "method"),
                buckets=PREPARE_BATCH_SIZE_BUCKETS,
            )
        )
        self.prepare_seconds = r.register(
            Histogram(
                "tpu_dra_prepare_seconds",
                "Wall time of one batched prepare/unprepare call.",
                ("driver", "method"),
            )
        )

    @contextmanager
    def track(self, method: str) -> Iterator[None]:
        self.requests_total.inc(self.driver, method)
        self.in_flight.inc(self.driver)
        t0 = time.perf_counter()
        try:
            yield
        except BaseException:
            self.request_errors_total.inc(self.driver, method)
            raise
        finally:
            self.in_flight.dec(self.driver)
            self.request_duration.observe(self.driver, method, value=time.perf_counter() - t0)

    @contextmanager
    def track_batch(self, method: str, batch_size: int) -> Iterator[None]:
        """Instrument one batched DRA call serving ``batch_size`` claims:
        requests_total counts claims (so per-claim accounting survives the
        batched pipeline), in_flight carries the whole batch while it runs,
        and the batch itself lands in prepare_batch_size / prepare_seconds.
        request_duration gets one observation per call — the per-RPC
        semantics of the reference's dra_requests.go histogram."""
        self.requests_total.inc(self.driver, method, by=batch_size)
        self.in_flight.inc(self.driver, by=batch_size)
        self.prepare_batch_size.observe(self.driver, method, value=float(batch_size))
        t0 = time.perf_counter()
        try:
            yield
        except BaseException:
            self.request_errors_total.inc(self.driver, method, by=batch_size)
            raise
        finally:
            self.in_flight.dec(self.driver, by=batch_size)
            dt = time.perf_counter() - t0
            self.request_duration.observe(self.driver, method, value=dt)
            self.prepare_seconds.observe(self.driver, method, value=dt)

    def record_claim_errors(self, method: str, count: int = 1) -> None:
        """Per-claim failures surfaced inline in a batch result (the batch
        call itself succeeded, so track_batch saw no exception)."""
        if count > 0:
            self.request_errors_total.inc(self.driver, method, by=count)


COMPUTE_DOMAIN_STATES = ("NotReady", "Ready", "Rejected", "Deleting")


class ComputeDomainStatusMetric:
    """Cluster-level ComputeDomain status gauge with state-exclusive labels:
    exactly one of the per-state series is 1 for a live domain."""

    def __init__(self, registry: Registry):
        self.gauge = registry.register(
            Gauge(
                "tpu_dra_compute_domain_status",
                "ComputeDomain status (state-exclusive).",
                ("namespace", "name", "status"),
            )
        )

    def set(self, namespace: str, name: str, status: str) -> None:
        if status not in COMPUTE_DOMAIN_STATES:
            raise ValueError(f"unknown ComputeDomain status {status!r}")
        for s in COMPUTE_DOMAIN_STATES:
            self.gauge.set(namespace, name, s, value=1.0 if s == status else 0.0)

    def forget(self, namespace: str, name: str) -> None:
        self.gauge.forget_matching(namespace=namespace, name=name)


# Why the mesh compiler (re-)ran — a closed vocabulary for the counter
# label: the first compile for a placement vs a link-health transition
# forcing a re-route.
MESHGEN_TRIGGERS = ("placement", "link-health")


class MeshgenMetrics:
    """Per-domain Placement→JAX mesh compiler telemetry: how often bundles
    (re)compile and the hop-count quality of the emitted device order vs
    the naive enumeration baseline — the same two numbers ``bench_meshgen``
    gates on, live per domain."""

    def __init__(self, registry: Registry):
        self.builds_total = registry.register(Counter(
            "tpu_dra_meshgen_builds_total",
            "Mesh bundles compiled, by trigger (placement/link-health).",
            ("trigger",),
        ))
        self.revision = registry.register(Gauge(
            "tpu_dra_meshgen_revision",
            "Current mesh-bundle revision of a ComputeDomain.",
            ("namespace", "name"),
        ))
        self.hop_score = registry.register(Gauge(
            "tpu_dra_meshgen_hop_score",
            "Mesh-axis-neighbor ICI hop count of the domain's device "
            "order (order=generated|naive).",
            ("namespace", "name", "order"),
        ))

    def built(self, namespace: str, name: str, bundle, trigger: str) -> None:
        if trigger not in MESHGEN_TRIGGERS:
            raise ValueError(f"unknown meshgen trigger {trigger!r}")
        self.builds_total.inc(trigger)
        self.record(namespace, name, bundle)

    def record(self, namespace: str, name: str, bundle) -> None:
        self.revision.set(namespace, name, value=float(bundle.revision))
        self.hop_score.set(namespace, name, "generated",
                           value=float(bundle.hop_score))
        self.hop_score.set(namespace, name, "naive",
                           value=float(bundle.naive_hop_score))

    def forget(self, namespace: str, name: str) -> None:
        self.revision.forget_matching(namespace=namespace, name=name)
        self.hop_score.forget_matching(namespace=namespace, name=name)


def _debug_stacks_text() -> bytes:
    """All live thread stacks, the goroutine-dump half of net/http/pprof."""
    from k8s_dra_driver_tpu.utils.debug import format_stacks

    return format_stacks().encode()


def _debug_vars_json() -> bytes:
    """Process-level runtime stats (expvar/pprof-index analog)."""
    import gc
    import json
    import os
    import resource

    ru = resource.getrusage(resource.RUSAGE_SELF)
    try:
        n_fds = len(os.listdir("/proc/self/fd"))
    except OSError:
        n_fds = -1
    return json.dumps({
        "pid": os.getpid(),
        "threads": threading.active_count(),
        "open_fds": n_fds,
        "max_rss_kib": ru.ru_maxrss,
        "user_cpu_s": ru.ru_utime,
        "system_cpu_s": ru.ru_stime,
        "gc_counts": gc.get_count(),
    }, indent=1).encode()


class MetricsServer:
    """Threaded /metrics HTTP server over a Registry.

    With ``debug_path`` set (the reference controller's --pprof-path,
    /root/reference/cmd/compute-domain-controller/main.go:423-431), also
    serves ``<debug_path>/stacks`` (live thread stacks) and
    ``<debug_path>/vars`` (process runtime stats). The tracer's span ring
    buffer is always exported as Chrome trace-event JSON at
    ``<debug_path or /debug>/traces`` — loadable in Perfetto /
    chrome://tracing, and what the sim ``trace`` command consumes.

    HTTP semantics: GET and HEAD are served; any other method gets 405
    with an Allow header (scanners and misconfigured scrapers must not
    hang or 500). ``/metrics`` and the debug endpoints are point-in-time
    reads, so every response carries ``Cache-Control: no-store``."""

    def __init__(self, registry: Registry, host: str = "127.0.0.1", port: int = 0,
                 debug_path: str = "", tracer=None):
        registry_ref = registry
        if tracer is None:
            from k8s_dra_driver_tpu.pkg.tracing import get_tracer
            tracer = get_tracer()
        tracer_ref = tracer
        # Normalize: "debug" and "/debug/" both mean "/debug"; "/" serves
        # the endpoints at the root. Empty disables stacks/vars (the
        # traces endpoint stays on, under /debug).
        debug_enabled = bool(debug_path.strip())
        debug = "/" + debug_path.strip().strip("/") if debug_enabled else ""
        if debug == "/":
            debug = ""
        traces_path = f"{debug}/traces" if debug_enabled else "/debug/traces"

        class Handler(http.server.BaseHTTPRequestHandler):
            def _resolve(self):
                """(body, content-type) for this path, or None for 404."""
                path = self.path.split("?", 1)[0].rstrip("/")
                if debug_enabled and path == f"{debug}/stacks":
                    return _debug_stacks_text(), "text/plain"
                if debug_enabled and path == f"{debug}/vars":
                    return _debug_vars_json(), "application/json"
                # /debug/traces stays valid even under a custom --pprof-path
                # prefix: the docs and the sim `trace --url` client promise
                # that URL unconditionally. ?trace_id= / ?name= narrow the
                # dump to one trace / one span name (what an `explain` row
                # deep-links); spansDropped rides every payload either way.
                if path in (traces_path, "/debug/traces"):
                    from urllib.parse import parse_qs, urlparse

                    q = parse_qs(urlparse(self.path).query)
                    trace_id = q.get("trace_id", [None])[0]
                    name = q.get("name", [None])[0]
                    if trace_id is not None or name is not None:
                        spans = tracer_ref.spans(trace_id=trace_id, name=name)
                        return (tracer_ref.export_chrome_json(spans),
                                "application/json")
                    return tracer_ref.export_chrome_json(), "application/json"
                if path in ("", "/metrics"):
                    return (registry_ref.expose().encode(),
                            "text/plain; version=0.0.4")
                return None

            def _serve(self, include_body: bool) -> None:
                resolved = self._resolve()
                if resolved is None:
                    self.send_error(404)
                    return
                body, ctype = resolved
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.send_header("Cache-Control", "no-store")
                self.end_headers()
                if include_body:
                    self.wfile.write(body)

            def do_GET(self) -> None:  # noqa: N802 — http.server API
                self._serve(include_body=True)

            def do_HEAD(self) -> None:  # noqa: N802 — http.server API
                self._serve(include_body=False)

            def _method_not_allowed(self) -> None:
                # Drain any request body so the connection stays sane,
                # then answer 405 instead of http.server's default 501.
                length = int(self.headers.get("Content-Length", "0") or 0)
                if length:
                    self.rfile.read(length)
                body = b"405 Method Not Allowed\n"
                self.send_response(405)
                self.send_header("Allow", "GET, HEAD")
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            do_POST = _method_not_allowed  # noqa: N815 — http.server API
            do_PUT = _method_not_allowed  # noqa: N815
            do_DELETE = _method_not_allowed  # noqa: N815
            do_PATCH = _method_not_allowed  # noqa: N815
            do_OPTIONS = _method_not_allowed  # noqa: N815

            def log_message(self, *args: object) -> None:
                pass

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
