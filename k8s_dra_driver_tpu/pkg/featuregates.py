"""Versioned feature gates with cross-gate dependency validation.

The TPU-native gate set mirrors the reference's eleven gates
(/root/reference/pkg/featuregates/featuregates.go:47-262), with GPU-specific
concepts mapped to their TPU analogs (MIG -> ICI subslice, NVLink fabric ->
ICI fabric, IMEX daemon -> slice agent, MPS -> premapped-buffer sharing).
Gates are set via the ``FEATURE_GATES`` env var or a ``Gate=true,Other=false``
flag string; dependency validation rejects configurations that enable a gate
whose prerequisites are disabled.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, Iterable, List, Mapping, Optional, Tuple


class Stage(Enum):
    ALPHA = "Alpha"
    BETA = "Beta"
    GA = "GA"


@dataclass(frozen=True)
class FeatureSpec:
    name: str
    default: bool
    stage: Stage
    description: str = ""
    lock_to_default: bool = False
    # Gates that must be enabled for this gate to be enabled.
    requires: Tuple[str, ...] = ()


# The TPU-native gate registry. One-to-one with the reference's set where an
# analog exists; names keep the reference's casing convention.
FEATURES: Tuple[FeatureSpec, ...] = (
    FeatureSpec(
        "TimeSlicingSettings", False, Stage.ALPHA,
        "Per-claim time-slicing interval config on shared TPU chips.",
    ),
    FeatureSpec(
        "PremappedBufferSharing", False, Stage.ALPHA,
        "Multi-process sharing of one chip via premapped HBM buffer limits "
        "(the MPS analog; TPUs have no MPS control daemon).",
        requires=("TimeSlicingSettings",),
    ),
    FeatureSpec(
        "SliceAgentsWithDNSNames", True, Stage.BETA,
        "Slice agents peer via stable per-index DNS names instead of raw pod "
        "IPs, so agent restarts keep their identity.",
    ),
    FeatureSpec(
        "PassthroughSupport", False, Stage.ALPHA,
        "Advertise whole hosts as VFIO passthrough devices for untrusted "
        "workloads (binds accel devices to vfio-pci).",
    ),
    FeatureSpec(
        "TPUDeviceHealthCheck", False, Stage.ALPHA,
        "Subscribe to libtpu/device health events and taint unhealthy "
        "devices in published ResourceSlices.",
    ),
    FeatureSpec(
        "DynamicSubslice", False, Stage.ALPHA,
        "Carve ICI subslice partitions through the partitioner ledger at "
        "Prepare time (the DynamicMIG analog); unprepare/rollback releases "
        "them.",
        requires=("ICIPartitioning",),
    ),
    FeatureSpec(
        "ComputeDomainCliques", True, Stage.BETA,
        "Track per-ICI-domain membership via ComputeDomainClique objects.",
    ),
    FeatureSpec(
        "CrashOnICIFabricErrors", True, Stage.BETA,
        "Refuse to start (rather than degrade) when ICI fabric state cannot "
        "be read or reports an error.",
    ),
    FeatureSpec(
        "DeviceMetadata", False, Stage.ALPHA,
        "Attach vendor metadata (serial, firmware, wrap-link map) to "
        "published devices.",
    ),
    FeatureSpec(
        "ICIPartitioning", False, Stage.ALPHA,
        "Program ICI mesh partitions (the NVSwitch/FabricManager "
        "partitioning analog) — consumed by passthrough device groups and "
        "by DynamicSubslice carving. No PassthroughSupport dependency: "
        "subslice deployments must not be forced to advertise VFIO devices.",
    ),
    FeatureSpec(
        "HostManagedSliceAgent", False, Stage.ALPHA,
        "Assume slice agents are managed by the host OS image rather than a "
        "driver-managed DaemonSet.",
        requires=("ComputeDomainCliques",),
    ),
    FeatureSpec(
        "StorePersistence", False, Stage.ALPHA,
        "Back the sim API store with an append-only WAL plus periodic "
        "snapshots so a large-cluster sim survives restart by replay "
        "instead of re-running its claim storm.",
    ),
    FeatureSpec(
        "FederatedFleet", False, Stage.ALPHA,
        "Attach a WAL-streaming ReplicationSource to the persistent store "
        "so read replicas in other clusters can follow it (federation/), "
        "and serve the /replication HTTP routes.",
        requires=("StorePersistence",),
    ),
    FeatureSpec(
        "FleetTelemetry", False, Stage.ALPHA,
        "Sample per-chip HBM/duty-cycle/power/ICI counters into bounded "
        "ring-buffer time series, roll them up to per-claim and per-"
        "ComputeDomain utilization summaries, and evaluate SLO burn-rate "
        "rules over them.",
    ),
    FeatureSpec(
        "ServingAutoscaler", False, Stage.ALPHA,
        "Run the serving-fleet loop: the sim traffic engine drives per-"
        "ServingGroup QPS traces through a queueing model into the "
        "telemetry plane, and the autoscaler controller closes horizontal "
        "(spec.replicas) and vertical (subslice tier) scaling on SLO "
        "burn-rate alerts and utilization rollups.",
        requires=("FleetTelemetry",),
    ),
    FeatureSpec(
        "ElasticComputeDomains", False, Stage.ALPHA,
        "Make ComputeDomain membership mutable: controller-orchestrated "
        "resize epochs driven by spec.numNodes edits and slice-agent "
        "lease expiry (host failure) — quiesce via MigrationCheckpoint, "
        "re-place against the bitmask tables, recompile the mesh bundle, "
        "restart workers, with full rollback on mid-epoch failure.",
        requires=("ComputeDomainCliques",),
    ),
    FeatureSpec(
        "ContentionPolicy", False, Stage.ALPHA,
        "Run the multi-tenant contention plane: weighted-fair-queuing "
        "admission over TenantQuota weights with per-tenant chip quotas "
        "and starvation aging, plus checkpoint-aware preemption — a "
        "higher-tier claim that parks unschedulable evicts strictly-"
        "lower-tier victims through the owner-tagged cordon CAS and the "
        "MigrationCheckpoint-guarded unprepare path.",
    ),
    FeatureSpec(
        "LiveRepack", False, Stage.ALPHA,
        "Run the online defragmentation rebalancer: migrate small-subslice "
        "claims (cordon -> checkpoint-aware unprepare -> re-place -> "
        "re-prepare) to restore large-profile placeability, or consolidate "
        "onto fewer hosts in energy mode.",
    ),
)

_SPECS: Dict[str, FeatureSpec] = {f.name: f for f in FEATURES}

ENV_VAR = "FEATURE_GATES"


class FeatureGateError(ValueError):
    pass


@dataclass
class FeatureGates:
    """An immutable-ish view of resolved gate values."""

    _values: Dict[str, bool] = field(default_factory=dict)

    def enabled(self, name: str) -> bool:
        if name not in _SPECS:
            raise FeatureGateError(f"unknown feature gate {name!r}")
        return self._values.get(name, _SPECS[name].default)

    def as_dict(self) -> Dict[str, bool]:
        return {f.name: self.enabled(f.name) for f in FEATURES}

    def validate(self) -> None:
        """Reject configurations whose dependency graph is unsatisfied."""
        for f in FEATURES:
            if self.enabled(f.name):
                for dep in f.requires:
                    if not self.enabled(dep):
                        raise FeatureGateError(
                            f"feature gate {f.name} requires {dep} to be enabled"
                        )

    def __str__(self) -> str:
        return ",".join(f"{k}={str(v).lower()}" for k, v in sorted(self.as_dict().items()))


def parse(spec: str) -> FeatureGates:
    """Parse ``Gate=true,Other=false`` (k8s component-base syntax)."""
    values: Dict[str, bool] = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        if "=" not in part:
            raise FeatureGateError(f"malformed feature gate entry {part!r} (want Name=bool)")
        name, _, raw = part.partition("=")
        name = name.strip()
        if name not in _SPECS:
            raise FeatureGateError(f"unknown feature gate {name!r}")
        raw = raw.strip().lower()
        if raw not in ("true", "false"):
            raise FeatureGateError(f"invalid value {raw!r} for feature gate {name}")
        value = raw == "true"
        spec_ = _SPECS[name]
        if spec_.lock_to_default and value != spec_.default:
            raise FeatureGateError(f"feature gate {name} is locked to {spec_.default}")
        values[name] = value
    return FeatureGates(values)


def from_environment(env: Optional[Mapping[str, str]] = None) -> FeatureGates:
    env = env if env is not None else os.environ
    return parse(env.get(ENV_VAR, ""))


def validate_feature_gates(gates: FeatureGates) -> FeatureGates:
    gates.validate()
    return gates


def known_features() -> List[str]:
    return [f"{f.name}={f.default} ({f.stage.value})" for f in FEATURES]
