"""Slice-channel char-device discovery (the nvcaps analog).

The reference discovers IMEX channel char devices by parsing `/proc/devices`
for the `nvidia-caps-imex-channels` character major and building
`/dev/nvidia-caps-imex-channels/chan<N>` nodes from it
(/root/reference/internal/common/nvcaps.go:78-218). The TPU build keeps the
same shape for its slice channels: the per-slice bootstrap capability handed
to a workload is a char device `/dev/tpu-slice-channels/chan<N>` whose major
comes from `/proc/devices` and whose minor is the channel id. CDI carries
path+type+major+minor so the runtime mknods the node inside the container.

Mock seam (reference precedent `ALT_PROC_DEVICES_PATH`,
nvcaps.go:33-75): the `TPU_DRA_ALT_PROC_DEVICES` env var redirects the
`/proc/devices` read so CPU-only CI can fake a channel major without the
kernel module; `using_alt_proc_devices()` lets callers skip kernel-only
operations in that mode, exactly like the reference's
`common.UsingAltProcDevices()` guards.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import List, Optional

# The char-device class name our (hypothetical) kernel facility registers,
# standing in for `nvidia-caps-imex-channels`.
CHANNEL_CLASS_NAME = "tpu-slice-channels"
CHANNEL_DEV_DIR = "/dev/tpu-slice-channels"

ALT_PROC_DEVICES_ENV = "TPU_DRA_ALT_PROC_DEVICES"

_proc_devices_override: Optional[str] = None


def configure_proc_devices_path(path: Optional[str]) -> None:
    """Test hook (reference ConfigureProcDevicesPath, nvcaps.go:60-75)."""
    global _proc_devices_override
    _proc_devices_override = path


def proc_devices_path() -> str:
    if _proc_devices_override:
        return _proc_devices_override
    return os.environ.get(ALT_PROC_DEVICES_ENV) or "/proc/devices"


def using_alt_proc_devices() -> bool:
    """True when the mock seam is active — kernel-only operations must be
    skipped (reference UsingAltProcDevices)."""
    return bool(_proc_devices_override or os.environ.get(ALT_PROC_DEVICES_ENV))


def get_char_device_major(class_name: str = CHANNEL_CLASS_NAME) -> Optional[int]:
    """Parse the `Character devices:` section of /proc/devices for
    ``class_name``'s major number (nvcaps.go:78-120). Returns None when the
    class is absent (kernel facility not loaded) or the file is unreadable.
    """
    try:
        with open(proc_devices_path(), "r", encoding="utf-8") as f:
            lines = f.read().splitlines()
    except OSError:
        return None
    in_char = False
    for line in lines:
        stripped = line.strip()
        if stripped == "Character devices:":
            in_char = True
            continue
        if stripped == "Block devices:":
            in_char = False
            continue
        if not in_char or not stripped:
            continue
        parts = stripped.split()
        if len(parts) >= 2 and parts[1] == class_name:
            try:
                return int(parts[0])
            except ValueError:
                return None
    return None


@dataclass(frozen=True)
class ChannelDevice:
    """One slice-channel char device (NVcapDeviceInfo analog)."""

    channel_id: int
    major: int

    @property
    def minor(self) -> int:
        return self.channel_id

    @property
    def path(self) -> str:
        return f"{CHANNEL_DEV_DIR}/chan{self.channel_id}"

    def to_cdi_node(self) -> dict:
        return {
            "path": self.path,
            "type": "c",
            "major": self.major,
            "minor": self.minor,
            "permissions": "rw",
        }


def enumerate_channels(
    count: int, class_name: str = CHANNEL_CLASS_NAME
) -> List[ChannelDevice]:
    """Channel devices chan0..chan<count-1>, or [] when the char class is not
    registered — callers degrade to env-only injection (mockless CI)."""
    major = get_char_device_major(class_name)
    if major is None:
        return []
    return [ChannelDevice(channel_id=i, major=major) for i in range(count)]


def channel_device(channel_id: int) -> Optional[ChannelDevice]:
    major = get_char_device_major()
    if major is None:
        return None
    return ChannelDevice(channel_id=channel_id, major=major)
