"""EventRecorder: client-go-style Event emission with aggregation.

The reference narrates allocation and ComputeDomain transitions through
corev1 Events recorded via client-go's EventRecorder, whose correlator
(k8s.io/client-go/tools/record) deduplicates repeats, caps per-object spam
with a token bucket, and keeps count/firstTimestamp/lastTimestamp on the
aggregated Event. This module is that correlator for the in-process API:

- **Dedup**: the series key is (involved object, type, reason, message).
  A repeat increments ``count`` and advances ``lastTimestamp`` on the ONE
  stored Event — a 100x FailedScheduling storm is one row with count=100.
  The Event name is a deterministic hash of the series key, so recorders
  in different processes sharing one API server aggregate into the same
  object instead of racing duplicates.
- **Burst limiter**: creating a NEW series consumes a token from a
  per-involved-object bucket (capacity ``burst``, refilled at
  ``refill_per_s``) — the EventCorrelator spam filter. Aggregation updates
  are free (they are what the limiter is funnelling spam into).
  Suppressions are themselves counted (``tpu_dra_events_suppressed_total``).
- **Bounded backlog**: at most ``max_events_per_object`` distinct series
  per involved object; the stalest series is evicted to admit a new one,
  so one flapping object cannot grow the store without bound.

Reason strings are CamelCase constants catalogued below; the
``hack/check_event_reasons.py`` gate fails `make verify` when an emitted
reason is not CamelCase or missing from ``docs/reference/events.md``.
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple, Union

from k8s_dra_driver_tpu.k8s.core import (
    EVENT,
    EVENT_TYPE_NORMAL,
    EVENT_TYPE_WARNING,
    Event,
    ObjectReference,
)
from k8s_dra_driver_tpu.k8s.objects import (
    AlreadyExistsError,
    ConflictError,
    K8sObject,
    NotFoundError,
    new_meta,
)

log = logging.getLogger(__name__)

# -- reason catalog (docs/reference/events.md is the operator-facing copy) --

# Scheduler / allocator
REASON_SCHEDULED = "Scheduled"
REASON_FAILED_SCHEDULING = "FailedScheduling"
REASON_ALLOCATION_FAILED = "AllocationFailed"
REASON_DOMAIN_PLACED = "DomainPlaced"
# Kubelet plugins
REASON_PREPARED_DEVICES = "PreparedDevices"
REASON_PREPARE_FAILED = "PrepareFailed"
REASON_UNPREPARE_FAILED = "UnprepareFailed"
REASON_CHECKPOINT_RECOVERED = "CheckpointRecovered"
# Device health
REASON_DEVICE_DEGRADED = "DeviceDegraded"
REASON_DEVICE_RECOVERED = "DeviceRecovered"
# Live-repack rebalancer
REASON_REBALANCE_PLANNED = "RebalancePlanned"
REASON_CLAIM_MIGRATED = "ClaimMigrated"
REASON_MIGRATION_FAILED = "MigrationFailed"
# SLO layer (pkg/slo.py burn-rate evaluator)
REASON_SLO_BURN_RATE = "SLOBurnRate"
# Serving autoscaler (autoscaler/controller.py). Messages carry no live
# replica counts, so one sustained trough dedups into ONE ScaleDown
# series with a rising count instead of a row per decision.
REASON_SCALE_UP = "ScaleUp"
REASON_SCALE_DOWN = "ScaleDown"
REASON_SCALE_DEFERRED = "ScaleDeferred"
# Contention plane (scheduling/: WFQ admission + checkpoint-aware
# preemption). Messages carry no live numbers so a sustained condition
# dedups into ONE series with a rising count.
REASON_PREEMPTED = "Preempted"
REASON_PREEMPTION_FAILED = "PreemptionFailed"
REASON_QUOTA_EXCEEDED = "QuotaExceeded"
# Elastic ComputeDomains (controller/elastic.py resize epochs)
REASON_DOMAIN_RESIZING = "DomainResizing"
REASON_DOMAIN_HEALED = "DomainHealed"
REASON_RESIZE_FAILED = "ResizeFailed"
# ComputeDomain controller / daemon
REASON_MESH_BUNDLE_UPDATED = "MeshBundleUpdated"
REASON_NODE_JOINED = "NodeJoined"
REASON_CLIQUE_ASSEMBLED = "CliqueAssembled"
REASON_DOMAIN_READY = "DomainReady"
REASON_DOMAIN_DEGRADED = "DomainDegraded"
REASON_DOMAIN_RECOVERED = "DomainRecovered"
REASON_DOMAIN_REJECTED = "DomainRejected"
# Federation (federation/replication.py): replica lag fires against the
# fleet recorder (the follower store is read-only); the failover pair
# lands in the promoted replica's OWN store — the leader may be gone.
REASON_REPLICA_LAGGING = "ReplicaLagging"
REASON_FAILOVER_STARTED = "FailoverStarted"
REASON_FAILOVER_COMPLETED = "FailoverCompleted"

# Correlator defaults, scaled from client-go's EventCorrelator (burst 25,
# refill 1 token / 5 min per object-and-source).
DEFAULT_BURST = 25
DEFAULT_REFILL_PER_S = 1.0 / 300.0
DEFAULT_MAX_EVENTS_PER_OBJECT = 16
# Cap on per-object correlator state (token buckets + series gates) held in
# memory — client-go bounds the same state with an LRU cache. Past the cap
# the least-recently-touched half is evicted; an evicted object that comes
# back simply starts with a full bucket again.
MAX_TRACKED_OBJECTS = 4096

_SeriesKey = Tuple[str, str, str, str, str, str, str]
_ObjKey = Tuple[str, str, str, str]


def object_reference(obj: Union[K8sObject, ObjectReference]) -> ObjectReference:
    if isinstance(obj, ObjectReference):
        return obj
    return ObjectReference(
        kind=obj.kind, name=obj.meta.name, namespace=obj.meta.namespace,
        uid=obj.meta.uid,
    )


def event_name(ref: ObjectReference, type_: str, reason: str, message: str) -> str:
    """Deterministic per-series Event name: dedup works across recorder
    instances and processes because they all address the same object."""
    key = "\x00".join((ref.kind, ref.namespace, ref.name, ref.uid,
                       type_, reason, message))
    h = hashlib.sha1(key.encode(), usedforsecurity=False).hexdigest()[:12]
    return f"{ref.name}.{h}"


def event_namespace(ref: ObjectReference) -> str:
    """Where an Event about this object is stored: its namespace, or —
    for cluster-scoped objects like Nodes — "default", matching real
    Kubernetes so `get events` (which lists the default namespace) shows
    DeviceDegraded rows without -A."""
    return ref.namespace or "default"


def events_for(api, obj: Union[K8sObject, ObjectReference]) -> List[Event]:
    """Every Event involving one object (by uid when set, else by
    kind/namespace/name), oldest-last-activity first — the rows a
    ``describe`` renders."""
    ref = object_reference(obj)
    out: List[Event] = []
    for ev in api.list(EVENT, namespace=event_namespace(ref)):
        io = ev.involved_object
        if ref.uid and io.uid:
            if io.uid != ref.uid:
                continue
        elif (io.kind, io.namespace, io.name) != (ref.kind, ref.namespace, ref.name):
            continue
        out.append(ev)
    out.sort(key=lambda e: (e.last_timestamp, e.meta.name))
    return out


class EventRecorder:
    """Records Events against an APIServer with correlator semantics.

    ``component`` is the recorder's source identity (scheduler, allocator,
    tpu-kubelet-plugin, ...). ``clock`` is injectable for deterministic
    timestamp tests. Thread-safe; the token buckets and backlog accounting
    are process-local while dedup itself is store-backed (cross-process)."""

    def __init__(
        self,
        api,
        component: str,
        metrics_registry=None,
        clock: Callable[[], float] = time.time,
        burst: int = DEFAULT_BURST,
        refill_per_s: float = DEFAULT_REFILL_PER_S,
        max_events_per_object: int = DEFAULT_MAX_EVENTS_PER_OBJECT,
    ) -> None:
        from k8s_dra_driver_tpu.pkg.metrics import Counter, Registry

        self.api = api
        self.component = component
        self.clock = clock
        self.burst = burst
        self.refill_per_s = refill_per_s
        self.max_events_per_object = max_events_per_object
        registry = metrics_registry or Registry()
        self.emitted_total = registry.register(Counter(
            "tpu_dra_events_emitted_total",
            "Events recorded (created or aggregated), by component and reason.",
            ("component", "reason"),
        ))
        self.suppressed_total = registry.register(Counter(
            "tpu_dra_events_suppressed_total",
            "Events dropped by the per-object burst limiter, by component "
            "and reason.",
            ("component", "reason"),
        ))
        self._mu = threading.Lock()
        # obj key -> [tokens, last refill timestamp]
        self._buckets: Dict[_ObjKey, List[float]] = {}  # tpulint: guarded-by=_mu
        # obj key -> Event names this recorder created — gates the backlog
        # enforcement scan (an O(namespace-events) list) to objects that
        # have plausibly reached the cap, instead of paying it per series.
        self._series_seen: Dict[_ObjKey, set] = {}  # tpulint: guarded-by=_mu

    # -- public emit helpers -------------------------------------------------

    def normal(self, involved, reason: str, message: str) -> Optional[Event]:
        return self.event(involved, EVENT_TYPE_NORMAL, reason, message)

    def warning(self, involved, reason: str, message: str) -> Optional[Event]:
        return self.event(involved, EVENT_TYPE_WARNING, reason, message)

    def event(
        self, involved: Union[K8sObject, ObjectReference], type_: str,
        reason: str, message: str,
    ) -> Optional[Event]:
        """Record one event occurrence. Returns the stored (created or
        aggregated) Event, or None when the burst limiter suppressed it.
        Never raises: a recorder failure must not fail the actor's
        reconcile (client-go's recorder is fire-and-forget too)."""
        try:
            return self._record(object_reference(involved), type_, reason, message)
        except Exception:  # noqa: BLE001 — telemetry must not break control flow
            log.exception("event %s/%s dropped", reason, message)
            return None

    # -- internals -----------------------------------------------------------

    def _record(self, ref: ObjectReference, type_: str, reason: str,
                message: str) -> Optional[Event]:
        now = self.clock()
        name = event_name(ref, type_, reason, message)
        ns = event_namespace(ref)
        from k8s_dra_driver_tpu.pkg import tracing

        ctx = tracing.current()
        trace_id = ctx.trace_id if ctx is not None else ""
        # Aggregation first: a dedup hit is an update, costs no token.
        if self._bump_existing(name, ns, now, trace_id):
            self.emitted_total.inc(self.component, reason)
            return self.api.try_get(EVENT, name, ns)
        if not self._take_token(ref, now):
            self.suppressed_total.inc(self.component, reason)
            return None
        obj_key: _ObjKey = (ref.kind, ref.namespace, ref.name, ref.uid)
        with self._mu:
            seen = self._series_seen.setdefault(obj_key, set())
            seen.add(name)
            near_cap = len(seen) >= self.max_events_per_object
        if near_cap:
            self._enforce_backlog(ref, obj_key, name)
        ev = Event(
            meta=new_meta(name, ns),
            involved_object=ref,
            type=type_,
            reason=reason,
            message=message,
            source=self.component,
            count=1,
            first_timestamp=now,
            last_timestamp=now,
            trace_id=trace_id,
        )
        try:
            created = self.api.create(ev)
        except AlreadyExistsError:
            # Cross-process race on the deterministic name: fold into it.
            self._bump_existing(name, ns, now, trace_id)
            created = self.api.try_get(EVENT, name, ns)
        self.emitted_total.inc(self.component, reason)
        return created

    def _bump_existing(self, name: str, ns: str, now: float,
                       trace_id: str = "") -> bool:
        def bump(obj):
            obj.count += 1
            obj.last_timestamp = max(obj.last_timestamp, now)
            if trace_id:
                # Latest occurrence wins: an aggregated series links the
                # most recent causal trace, matching lastTimestamp.
                obj.trace_id = trace_id
        try:
            self.api.update_with_retry(EVENT, name, ns, bump)
            return True
        except (NotFoundError, ConflictError):
            return False

    def _evict_stale_objects_locked(self) -> None:
        # tpulint: holds=_mu
        """Drop correlator state for the least-recently-touched half of
        tracked objects once the cap is hit — short-lived pods/claims must
        not grow a long-lived recorder's memory forever (caller holds
        self._mu)."""
        if len(self._buckets) < MAX_TRACKED_OBJECTS:
            return
        by_age = sorted(self._buckets, key=lambda k: self._buckets[k][1])
        for key in by_age[: len(by_age) // 2]:
            del self._buckets[key]
            self._series_seen.pop(key, None)

    def _take_token(self, ref: ObjectReference, now: float) -> bool:
        key: _ObjKey = (ref.kind, ref.namespace, ref.name, ref.uid)
        with self._mu:
            bucket = self._buckets.get(key)
            if bucket is None:
                self._evict_stale_objects_locked()
                bucket = self._buckets[key] = [float(self.burst), now]
            tokens, last = bucket
            tokens = min(float(self.burst),
                         tokens + max(0.0, now - last) * self.refill_per_s)
            if tokens < 1.0:
                bucket[0], bucket[1] = tokens, now
                return False
            bucket[0], bucket[1] = tokens - 1.0, now
            return True

    def _enforce_backlog(self, ref: ObjectReference, obj_key: _ObjKey,
                         new_name: str) -> None:
        """Keep at most max_events_per_object series per involved object by
        evicting the series with the stalest lastTimestamp — recent
        narration survives, ancient flaps age out. Only called once the
        process-local series count plausibly reached the cap; the store
        listing here is the ground truth (evictions and other processes'
        series included)."""
        existing = events_for(self.api, ref)
        while len(existing) >= self.max_events_per_object:
            victim = existing.pop(0)
            try:
                self.api.delete(EVENT, victim.meta.name, victim.namespace)
            except NotFoundError:
                pass
        with self._mu:
            # Resync the gate to the store's verdict: the surviving series
            # plus the one being created now (not yet stored).
            self._series_seen[obj_key] = (
                {e.meta.name for e in existing} | {new_name})


def find_compute_domain_by_uid(api, namespace: str, uid: str):
    """Resolve a ComputeDomain object from the uid actors carry around
    (COMPUTE_DOMAIN_UUID) so events land on the domain, not just its uid."""
    from k8s_dra_driver_tpu.k8s.core import COMPUTE_DOMAIN

    for cd in api.list(COMPUTE_DOMAIN, namespace=namespace):
        if cd.uid == uid:
            return cd
    return None
