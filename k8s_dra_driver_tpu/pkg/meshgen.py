"""Placement→JAX mesh compiler.

The control plane computes exact ICI geometry — ``ComputeDomainStatus.
placement`` records the host-grid block and each host contributes a known
chip grid — yet a claiming JAX pod that calls ``jax.devices()`` and
reshapes in enumeration order throws that topology away at the last hop:
enumeration is host-major, so a ``(data, model)`` reshape scatters
model-axis neighbors across host boundaries and every tensor-parallel
collective pays cross-host hops it never needed to.

This module closes the gap as a **pure compiler** (stdlib + tpulib types
only, no k8s imports): from a domain's placement block, the member hosts'
topology strings, and the current ICI link-health taints, it emits a
**mesh bundle** —

- a topology-aligned flat device order: mesh axes map onto the physical
  chip grid of the block, the innermost (``model``) axis walks
  ring-adjacent chips along the fastest physical axis and the outer
  (``data``) axis advances host-major along the slower one, so every
  mesh-axis neighbor pair is one ICI hop apart when the fabric is whole;
- named ``jax.sharding.Mesh`` axes sized to the REAL slice shape of the
  block (not a guessed power-of-two factorization);
- regex partition rules in the ``match_partition_rules`` style (SNIPPETS
  exemplar) covering the transformer parameter families the workload tier
  trains;
- a deterministic hop-count score of the generated vs naive enumeration
  order — the quantity ``bench_meshgen`` gates on.

When a ``tpu.google.com/ici-link-unhealthy`` taint lands mid-domain the
compiler re-routes the innermost ring order around the dead link (each
data row's collective is its own ring, so rows re-order independently)
and the controller bumps the bundle revision.

The serialized JSON travels as ``ComputeDomainStatus.meshBundle`` on the
wire and reaches claiming containers as the ``TPU_DRA_MESH_BUNDLE`` CDI
env; ``parallel/mesh.py::mesh_from_bundle`` is the client half.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from k8s_dra_driver_tpu.tpulib.profiles import host_chip_coords
from k8s_dra_driver_tpu.tpulib.types import (
    format_topology,
    parse_topology,
    topology_chips,
)

# Env key the CDI channel device injects the serialized bundle under.
MESH_BUNDLE_ENV = "TPU_DRA_MESH_BUNDLE"
# Host-grid bounds of the block ("x,y,z" libtpu style), injected alongside.
PROCESS_BOUNDS_ENV = "TPU_PROCESS_BOUNDS"

# Canonical mesh axis names, outermost first. 2D blocks use the pair;
# a third effective axis (v4/v5p tori) rides an extra leading name.
DEFAULT_AXIS_NAMES = ("data", "model")

# Hop cost charged for a unit-distance pair whose direct ICI link is dead:
# the shortest detour through a neighboring row/column of a ≥2-wide mesh
# is 3 hops (out, across, back).
BROKEN_LINK_DETOUR_HOPS = 3

# Ring re-order search is exhaustive up to this group size (6! = 720
# orders); longer rings fall back to a greedy nearest-neighbor walk. Only
# groups touching a dead link pay the search at all.
EXHAUSTIVE_RING_LIMIT = 6


@dataclass(frozen=True)
class MeshDevice:
    """One chip slot in the bundle's flat device order."""

    node: str                  # member host (placement.nodes entry)
    worker: int                # index of node in the block (row-major)
    chip: int                  # host-local chip index
    coord: Tuple[int, ...]     # chip coordinate within the BLOCK's chip grid


@dataclass
class MeshBundle:
    """The compiled mesh: everything a claiming pod needs to build a
    topology-optimal ``jax.sharding.Mesh`` without re-deriving geometry."""

    revision: int = 0
    slice_topology: str = ""        # chip grid of the BLOCK, e.g. "4x4"
    host_topology: str = ""         # chips per host, e.g. "2x2"
    process_bounds: str = ""        # host grid of the block, "2,2,1"
    axis_names: List[str] = field(default_factory=list)
    axis_sizes: List[int] = field(default_factory=list)
    device_order: List[MeshDevice] = field(default_factory=list)
    # [regex, spec] pairs; spec entries are axis names or None, in the
    # match_partition_rules convention (None = replicate that dim).
    partition_rules: List[List[object]] = field(default_factory=list)
    hop_score: int = 0              # generated order (lower = better)
    naive_hop_score: int = 0        # enumeration order on the same grid
    # Dead ICI links the order routes around: ["node", chip_a, chip_b].
    broken_links: List[List[object]] = field(default_factory=list)

    @property
    def num_devices(self) -> int:
        return len(self.device_order)

    @property
    def chips_per_host(self) -> int:
        return topology_chips(self.host_topology) if self.host_topology else 0

    def flat_indices(self) -> List[int]:
        """Enumeration indices (worker*chips_per_host + chip) in bundle
        order — the permutation ``mesh_from_bundle`` applies to the
        host-major ``jax.devices()`` list."""
        cph = self.chips_per_host
        return [d.worker * cph + d.chip for d in self.device_order]

    def remap_workers(self, node_to_worker: Dict[str, int]) -> "MeshBundle":
        """A copy whose ``worker`` slots follow ``node_to_worker`` — the
        CDI handler's injection-time rewrite from block position to the
        clique's CAS-allocated index. ``jax.devices()`` enumerates by
        process index (= clique index via TPU_WORKER_ID), and clique
        indices are first-come, so block order only coincides with
        enumeration order when daemons happened to register in block
        order; the env copy must carry the RUNTIME indices or
        ``flat_indices`` permutes the wrong devices. An incomplete
        mapping or one that is not a permutation of the block's worker
        slots returns self unchanged (fallback contract: a half-assembled
        clique degrades to the unremapped bundle, never corrupts it)."""
        old = {d.worker for d in self.device_order}
        new = {node_to_worker.get(d.node, -1) for d in self.device_order}
        if new != old:
            return self
        return dataclasses.replace(self, device_order=[
            MeshDevice(node=d.node, worker=node_to_worker[d.node],
                       chip=d.chip, coord=d.coord)
            for d in self.device_order
        ])

    def matches_inputs(
        self,
        block_shape: str,
        host_topology: str,
        nodes: Sequence[str],
        broken_links: Iterable[Sequence] = (),
    ) -> bool:
        """True when this bundle was compiled from exactly these inputs —
        the controller's hot-path no-recompile test. compile_bundle is
        deterministic, so matching inputs imply identical geometry; a
        taint-storm reconcile that changes nothing skips device_layout +
        two hop_score passes per domain. ``broken_links`` must be in the
        compiler's normalized form (member-filtered, (node, lo, hi),
        sorted) — what Controller._mesh_inputs produces."""
        if self.host_topology != host_topology:
            return False
        if [list(b) for b in broken_links] != self.broken_links:
            return False
        try:
            grid = block_chip_grid(block_shape, host_topology)
        except (ValueError, TypeError):
            return False
        if format_topology(grid) != self.slice_topology:
            return False
        by_worker = {d.worker: d.node for d in self.device_order}
        if len(by_worker) != len(nodes):
            return False
        return [by_worker.get(i) for i in range(len(nodes))] == list(nodes)

    def same_geometry(self, other: "MeshBundle") -> bool:
        """Content equality ignoring revision and scores — the
        controller's should-I-re-emit test (a no-op reconcile must not
        bump the revision)."""
        return (
            self.slice_topology == other.slice_topology
            and self.host_topology == other.host_topology
            and self.process_bounds == other.process_bounds
            and self.axis_names == other.axis_names
            and self.axis_sizes == other.axis_sizes
            and self.device_order == other.device_order
            and self.partition_rules == other.partition_rules
            and self.broken_links == other.broken_links
        )

    # -- JSON (the env shape; k8swire reuses the same field names) ----------

    def to_json_obj(self) -> dict:
        return {
            "revision": self.revision,
            "sliceTopology": self.slice_topology,
            "hostTopology": self.host_topology,
            "processBounds": self.process_bounds,
            "axisNames": list(self.axis_names),
            "axisSizes": list(self.axis_sizes),
            "deviceOrder": [
                {"node": d.node, "worker": d.worker, "chip": d.chip,
                 "coord": list(d.coord)}
                for d in self.device_order
            ],
            "partitionRules": [list(r) for r in self.partition_rules],
            "hopScore": self.hop_score,
            "naiveHopScore": self.naive_hop_score,
            "brokenLinks": [list(b) for b in self.broken_links],
        }

    def to_json(self) -> str:
        return json.dumps(self.to_json_obj(), separators=(",", ":"),
                          sort_keys=True)

    @classmethod
    def from_json_obj(cls, obj: dict) -> "MeshBundle":
        if not isinstance(obj, dict):
            raise ValueError(f"mesh bundle must be a JSON object, "
                             f"got {type(obj).__name__}")
        return cls(
            revision=int(obj.get("revision", 0)),
            slice_topology=obj.get("sliceTopology", ""),
            host_topology=obj.get("hostTopology", ""),
            process_bounds=obj.get("processBounds", ""),
            axis_names=[str(a) for a in obj.get("axisNames") or []],
            axis_sizes=[int(s) for s in obj.get("axisSizes") or []],
            device_order=[
                MeshDevice(node=d.get("node", ""),
                           worker=int(d.get("worker", 0)),
                           chip=int(d.get("chip", 0)),
                           coord=tuple(int(c) for c in d.get("coord") or ()))
                for d in obj.get("deviceOrder") or []
            ],
            partition_rules=[list(r) for r in obj.get("partitionRules") or []],
            hop_score=int(obj.get("hopScore", 0)),
            naive_hop_score=int(obj.get("naiveHopScore", 0)),
            broken_links=[list(b) for b in obj.get("brokenLinks") or []],
        )

    @classmethod
    def from_json(cls, text: str) -> "MeshBundle":
        return cls.from_json_obj(json.loads(text))


# -- partition rules ----------------------------------------------------------


def default_partition_rules(model_axis: str = "model") -> List[List[object]]:
    """(regex, spec) pairs over '/'-joined parameter paths for the
    transformer families the workload tier (models/*) trains: tp shards
    heads and the FFN hidden dim over the model axis, norms/scalars
    replicate, and the final catch-all replicates anything novel instead
    of erroring — the bundle is advisory, so a workload with exotic
    params still boots."""
    return [
        ["wqkv$", [None, None, model_axis, None]],
        ["wo$", [model_axis, None, None]],
        ["w1$", [None, model_axis]],
        ["w2$", [model_axis, None]],
        ["(embed|unembed)$", [None, None]],
        ["(ln1|ln2|scale|bias)$", []],
        [".*", []],
    ]


# -- geometry -----------------------------------------------------------------


def block_chip_grid(block_shape: str, host_topology: str) -> Tuple[int, ...]:
    """Chip-grid dims of a host block: block shape (host units) times the
    per-host chip shape, both padded with 1s to the larger rank."""
    b = parse_topology(block_shape)
    h = parse_topology(host_topology)
    rank = max(len(b), len(h))
    b = b + (1,) * (rank - len(b))
    h = h + (1,) * (rank - len(h))
    return tuple(bd * hd for bd, hd in zip(b, h))


def device_layout(
    block_shape: str,
    host_topology: str,
    nodes: Sequence[str],
) -> Dict[Tuple[int, ...], MeshDevice]:
    """Block-grid chip coordinate -> MeshDevice for every chip the block's
    hosts contribute. ``nodes`` is placement.nodes — row-major over the
    block, the same order ``iter_host_blocks`` yields, so worker slot i is
    the i-th block cell."""
    host_dims = parse_topology(host_topology)
    block_dims = parse_topology(block_shape)
    rank = max(len(host_dims), len(block_dims))
    hd = host_dims + (1,) * (rank - len(host_dims))
    bd = block_dims + (1,) * (rank - len(block_dims))
    hosts = list(itertools.product(*(range(d) for d in bd)))
    if len(nodes) != len(hosts):
        raise ValueError(
            f"placement lists {len(nodes)} nodes but block {block_shape} "
            f"holds {len(hosts)} hosts")
    out: Dict[Tuple[int, ...], MeshDevice] = {}
    for worker, hcoord in enumerate(hosts):
        for chip, ccoord in enumerate(host_chip_coords(host_dims)):
            cc = tuple(ccoord) + (0,) * (rank - len(ccoord))
            coord = tuple(h * d + c for h, d, c in zip(hcoord, hd, cc))
            out[coord] = MeshDevice(node=nodes[worker], worker=worker,
                                    chip=chip, coord=coord)
    return out


def _manhattan(a: Tuple[int, ...], b: Tuple[int, ...]) -> int:
    return sum(abs(x - y) for x, y in zip(a, b))


def _hop(a: MeshDevice, b: MeshDevice, broken: frozenset) -> int:
    """ICI hops between two chips of the block, charging the detour cost
    when the pair's direct link is dead. ``broken`` holds coordinate
    pairs (frozenset of the two endpoint coords)."""
    d = _manhattan(a.coord, b.coord)
    if d == 1 and frozenset((a.coord, b.coord)) in broken:
        return BROKEN_LINK_DETOUR_HOPS
    return d


def hop_score(order: Sequence[MeshDevice], axis_sizes: Sequence[int],
              broken: Iterable[frozenset] = ()) -> int:
    """Deterministic adjacency score of a flat device order laid out as a
    mesh of ``axis_sizes``: the sum of ICI hops over every pair of
    mesh-axis neighbors (each undirected edge once). This is the
    collective cost model at this layer — a psum over one axis chains
    exactly these neighbor links — and the quantity the bench gate
    compares."""
    broken_set = frozenset(broken)
    sizes = tuple(axis_sizes)
    n = 1
    for s in sizes:
        n *= s
    if n != len(order):
        raise ValueError(f"axis sizes {sizes} need {n} devices, "
                         f"got {len(order)}")
    strides = []
    acc = 1
    for s in reversed(sizes):
        strides.append(acc)
        acc *= s
    strides.reverse()

    def at(idx: Tuple[int, ...]) -> MeshDevice:
        return order[sum(i * st for i, st in zip(idx, strides))]

    total = 0
    for idx in itertools.product(*(range(s) for s in sizes)):
        for ax in range(len(sizes)):
            if idx[ax] + 1 < sizes[ax]:
                nxt = list(idx)
                nxt[ax] += 1
                total += _hop(at(idx), at(tuple(nxt)), broken_set)
    return total


def naive_order(layout: Dict[Tuple[int, ...], MeshDevice]) -> List[MeshDevice]:
    """The enumeration order a bundle-less pod gets from jax.devices():
    host-major (process index), then host-local chip index."""
    return sorted(layout.values(), key=lambda d: (d.worker, d.chip))


def _ring_path(devs: List[MeshDevice], broken: frozenset) -> List[MeshDevice]:
    """Order one innermost-axis group as the cheapest chain of its chips.

    The group's physical coords are collinear along the fastest axis, so
    the identity order is an optimal unit-hop chain on a healthy fabric;
    with a dead link inside the group the row re-orders independently
    (each data row's collective is its own ring). Cost is (dead links
    traversed, total hops): a chain that routes AROUND the dead link
    always beats one that limps across it, even at equal hop count.
    Exhaustive for small groups, greedy nearest-neighbor beyond
    EXHAUSTIVE_RING_LIMIT."""
    def chain_cost(path: Sequence[MeshDevice]) -> Tuple[int, int]:
        dead = hops = 0
        for i in range(len(path) - 1):
            h = _hop(path[i], path[i + 1], broken)
            hops += h
            if h == BROKEN_LINK_DETOUR_HOPS and _manhattan(
                    path[i].coord, path[i + 1].coord) == 1:
                dead += 1
        return (dead, hops)

    if chain_cost(devs) == (0, len(devs) - 1):
        return devs  # already a clean unit-hop chain
    if len(devs) <= EXHAUSTIVE_RING_LIMIT:
        return list(min(itertools.permutations(devs), key=chain_cost))
    remaining = list(devs)
    path = [remaining.pop(0)]
    while remaining:
        nxt = min(remaining,
                  key=lambda d: chain_cost((path[-1], d)))
        remaining.remove(nxt)
        path.append(nxt)
    return path


def generated_order(
    layout: Dict[Tuple[int, ...], MeshDevice],
    grid: Tuple[int, ...],
    inner_axis: int,
    broken: Iterable[frozenset] = (),
) -> List[MeshDevice]:
    """Topology-aligned flat order: outer axes walk the slower physical
    dims in ascending row-major order, the innermost axis chains
    ring-adjacent chips along the fastest dim — re-routed per group
    around dead links."""
    broken_set = frozenset(broken)
    outer_axes = [i for i in range(len(grid)) if i != inner_axis]
    out: List[MeshDevice] = []
    for outer in itertools.product(*(range(grid[i]) for i in outer_axes)):
        group = []
        for j in range(grid[inner_axis]):
            coord = [0] * len(grid)
            for ax, v in zip(outer_axes, outer):
                coord[ax] = v
            coord[inner_axis] = j
            group.append(layout[tuple(coord)])
        out.extend(_ring_path(group, broken_set))
    return out


def _axis_names_for(n_axes: int) -> List[str]:
    """('data','model') for 2 effective axes, ('model',) for 1; extra
    leading axes (3D tori) are named replica/replicaN so the trailing
    pair stays the familiar one."""
    if n_axes <= len(DEFAULT_AXIS_NAMES):
        return list(DEFAULT_AXIS_NAMES[-n_axes:])
    extra = n_axes - len(DEFAULT_AXIS_NAMES)
    return [("replica" if extra == 1 else f"replica{i}")
            for i in range(extra)] + list(DEFAULT_AXIS_NAMES)


def broken_links_to_coords(
    layout: Dict[Tuple[int, ...], MeshDevice],
    broken_links: Iterable[Tuple[str, int, int]],
) -> List[frozenset]:
    """Translate (node, chip_a, chip_b) host-local dead links into block
    chip-coordinate pairs. Links on nodes outside the block are ignored."""
    by_node_chip = {(d.node, d.chip): d.coord for d in layout.values()}
    out: List[frozenset] = []
    for node, a, b in broken_links:
        ca = by_node_chip.get((node, int(a)))
        cb = by_node_chip.get((node, int(b)))
        if ca is not None and cb is not None:
            out.append(frozenset((ca, cb)))
    return out


def compile_bundle(
    block_shape: str,
    host_topology: str,
    nodes: Sequence[str],
    broken_links: Iterable[Tuple[str, int, int]] = (),
    revision: int = 1,
) -> MeshBundle:
    """The compiler entry point: placement block + member nodes +
    link-health taints -> a MeshBundle. Deterministic for identical
    inputs (the controller's same_geometry dedup depends on it)."""
    grid = block_chip_grid(block_shape, host_topology)
    layout = device_layout(block_shape, host_topology, nodes)
    # Effective mesh axes: unit dims carry no devices and no adjacency, so
    # they collapse out of the axis list (a 2x2x1-host v4 block is a 2D
    # mesh); the innermost effective axis is the ring axis.
    nonunit = [i for i, d in enumerate(grid) if d > 1] or [len(grid) - 1]
    inner_axis = nonunit[-1]
    eff_sizes = [grid[i] for i in nonunit]
    node_set = set(nodes)
    broken_list = sorted(
        (str(n), min(int(a), int(b)), max(int(a), int(b)))
        for n, a, b in broken_links
        if n in node_set
    )
    broken_coords = broken_links_to_coords(layout, broken_list)
    order = generated_order(layout, grid, inner_axis, broken_coords)
    naive = naive_order(layout)
    axis_names = _axis_names_for(len(eff_sizes))
    bounds = list(parse_topology(block_shape))
    bounds += [1] * (3 - len(bounds))
    return MeshBundle(
        revision=revision,
        slice_topology=format_topology(grid),
        host_topology=host_topology,
        process_bounds=",".join(str(b) for b in bounds),
        axis_names=axis_names,
        axis_sizes=eff_sizes,
        device_order=order,
        partition_rules=default_partition_rules(axis_names[-1]),
        hop_score=hop_score(order, eff_sizes, broken_coords),
        naive_hop_score=hop_score(naive, eff_sizes, broken_coords),
        broken_links=[list(b) for b in broken_list],
    )


def compile_for_placement(placement, host_topology: str,
                          broken_links: Iterable[Tuple[str, int, int]] = (),
                          revision: int = 1) -> Optional[MeshBundle]:
    """``compile_bundle`` over a ComputeDomainPlacement-shaped object (any
    object with block_shape/nodes attributes — keeps this module free of
    api imports). Returns None when the placement is not compilable
    (malformed shape, node-count mismatch): the caller degrades to no
    bundle rather than failing its reconcile."""
    try:
        return compile_bundle(
            placement.block_shape, host_topology, list(placement.nodes),
            broken_links=broken_links, revision=revision)
    except (ValueError, KeyError, TypeError):
        return None
