"""Rate-limited reconcile work queues.

A de-duplicating delayed queue with pluggable per-item rate limiters, used by
every reconcile loop in the framework (controller, compute-domain managers,
cleanup managers). Failed items are retried with exponential backoff; jitter
decorrelates retry storms across nodes. The default limiters delegate to the
consolidated ``pkg.backoff.Backoff`` policy (capped exponential,
DETERMINISTIC jitter, per-item reset on success), so every retry delay in
the control plane lands in the shared ``tpu_dra_retry_backoff_seconds``
histogram.

Reference behavior: /root/reference/pkg/workqueue/workqueue.go:49-67
(prep/unprep 5s->10m exponential limiters) and jitterlimiter.go:31-66
(±factor jitter).
"""

from __future__ import annotations

import heapq
import logging
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Hashable, Optional

from k8s_dra_driver_tpu.pkg.backoff import Backoff, BackoffMetrics
from k8s_dra_driver_tpu.pkg.metrics import Counter, Gauge, Histogram, Registry

log = logging.getLogger(__name__)

# Queue-latency / work-duration envelope: 1ms * 4^k, k=0..8 (1ms .. ~65s)
# — reconcile handlers are far faster than DRA prepares, so the
# request-duration envelope (50ms floor) would flatten everything into
# the first bucket.
WORKQUEUE_SECONDS_BUCKETS = tuple(0.001 * (4**k) for k in range(9))


class WorkQueueMetrics:
    """The k8s workqueue metric surface (depth / adds / retries /
    queue-latency / work-duration), on the shared ``tpu_dra_*``
    registry, one series per queue name."""

    def __init__(self, registry: Registry):
        self.depth = registry.register(Gauge(
            "tpu_dra_workqueue_depth",
            "Items waiting in the queue (not yet picked up by a worker).",
            ("queue",),
        ))
        self.adds_total = registry.register(Counter(
            "tpu_dra_workqueue_adds_total",
            "Items accepted by the queue (dedup'd enqueues don't count).",
            ("queue",),
        ))
        self.retries_total = registry.register(Counter(
            "tpu_dra_workqueue_retries_total",
            "Handler failures re-queued with backoff.",
            ("queue",),
        ))
        self.queue_latency = registry.register(Histogram(
            "tpu_dra_workqueue_queue_latency_seconds",
            "Time from enqueue (incl. backoff delay) to worker pickup.",
            ("queue",),
            buckets=WORKQUEUE_SECONDS_BUCKETS,
        ))
        self.work_seconds = registry.register(Histogram(
            "tpu_dra_workqueue_work_seconds",
            "Handler execution time per item.",
            ("queue",),
            buckets=WORKQUEUE_SECONDS_BUCKETS,
        ))


class RateLimiter:
    """Maps an item key to its next retry delay (seconds)."""

    def when(self, key: Hashable) -> float:
        raise NotImplementedError

    def forget(self, key: Hashable) -> None:
        raise NotImplementedError


class ExponentialRateLimiter(RateLimiter):
    """base * 2^failures, capped at max — the k8s ItemExponentialFailureRateLimiter shape."""

    def __init__(self, base: float = 0.005, cap: float = 1000.0):
        self.base = base
        self.cap = cap
        self._failures: Dict[Hashable, int] = {}  # tpulint: guarded-by=_mu
        self._mu = threading.Lock()

    def when(self, key: Hashable) -> float:
        with self._mu:
            n = self._failures.get(key, 0)
            self._failures[key] = n + 1
        return min(self.base * (2**n), self.cap)

    def failures(self, key: Hashable) -> int:
        with self._mu:
            return self._failures.get(key, 0)

    def forget(self, key: Hashable) -> None:
        with self._mu:
            self._failures.pop(key, None)


class JitterRateLimiter(RateLimiter):
    """Wraps another limiter, scaling each delay by a random factor in
    [1-factor, 1+factor] to decorrelate thundering herds of retries."""

    def __init__(self, inner: RateLimiter, factor: float = 0.2, rng: Optional[random.Random] = None):
        if not 0.0 <= factor < 1.0:
            raise ValueError(f"jitter factor must be in [0,1), got {factor}")
        self.inner = inner
        self.factor = factor
        self._rng = rng or random.Random()

    def when(self, key: Hashable) -> float:
        delay = self.inner.when(key)
        return delay * (1.0 + self.factor * (2.0 * self._rng.random() - 1.0))

    def forget(self, key: Hashable) -> None:
        self.inner.forget(key)


class BackoffRateLimiter(RateLimiter):
    """RateLimiter over the consolidated ``pkg.backoff.Backoff`` policy:
    capped exponential with deterministic (key, attempt)-derived jitter —
    the k8s exponential+jitter pair without the RNG, so seeded runs and
    retry-timing tests reproduce exactly. ``source`` labels the shared
    ``tpu_dra_retry_backoff_seconds`` histogram series."""

    def __init__(self, base: float, cap: float, jitter: float = 0.2,
                 metrics_registry: Optional[Registry] = None,
                 source: str = "workqueue"):
        self.backoff = Backoff(
            base=base, cap=cap, jitter=jitter,
            metrics=BackoffMetrics(metrics_registry or Registry()),
            source=source,
            # k8s ItemExponentialFailureRateLimiter shape: the first
            # failure already waits `base` (the queue retries eagerly
            # enough at the 5ms controller default; the 5s prepare
            # limiter MUST hold even the first retry back).
            first_free=False,
        )

    def when(self, key: Hashable) -> float:
        return self.backoff.failure(key)

    def forget(self, key: Hashable) -> None:
        self.backoff.reset(key)


def default_controller_rate_limiter(
        metrics_registry: Optional[Registry] = None) -> RateLimiter:
    return BackoffRateLimiter(base=0.005, cap=1000.0,
                              metrics_registry=metrics_registry,
                              source="workqueue")


def prepare_unprepare_rate_limiter(
        metrics_registry: Optional[Registry] = None) -> RateLimiter:
    """The reference's dedicated prepare/unprepare limiter: 5s -> 10min."""
    return BackoffRateLimiter(base=5.0, cap=600.0,
                              metrics_registry=metrics_registry,
                              source="workqueue-prepare")


@dataclass(order=True)
class _Scheduled:
    ready_at: float
    seq: int
    key: Hashable = field(compare=False)
    enq_at: float = field(compare=False, default=0.0)


class WorkQueue:
    """De-duplicating delayed reconcile queue.

    ``enqueue(key, obj)`` schedules ``handler(key, obj)`` on a worker thread.
    While a key is queued or being processed, further enqueues coalesce into a
    single re-run with the latest object. A handler exception requeues the key
    after ``rate_limiter.when(key)``; success calls ``forget``.
    """

    def __init__(
        self,
        handler: Callable[[Hashable, Any], None],
        rate_limiter: Optional[RateLimiter] = None,
        name: str = "workqueue",
        max_retries: Optional[int] = None,
        metrics_registry: Optional[Registry] = None,
    ):
        self._handler = handler
        self._rl = rate_limiter or default_controller_rate_limiter()
        self.name = name
        self._max_retries = max_retries
        # A private registry when none is shared: instrumentation code
        # stays unconditional, series just aren't scraped anywhere.
        self.metrics = WorkQueueMetrics(metrics_registry or Registry())
        self._mu = threading.Condition()
        self._heap: list[_Scheduled] = []  # tpulint: guarded-by=_mu
        self._seq = 0
        self._latest: Dict[Hashable, Any] = {}  # tpulint: guarded-by=_mu
        self._queued: set[Hashable] = set()  # tpulint: guarded-by=_mu
        self._processing: set[Hashable] = set()  # tpulint: guarded-by=_mu
        self._dirty: set[Hashable] = set()  # re-enqueued while processing  # tpulint: guarded-by=_mu
        self._retry_count: Dict[Hashable, int] = {}  # tpulint: guarded-by=_mu
        self._stopped = False
        self._threads: list[threading.Thread] = []

    def enqueue(self, key: Hashable, obj: Any = None, delay: float = 0.0) -> None:
        with self._mu:
            if self._stopped:
                return
            self._latest[key] = obj
            if key in self._processing:
                self._dirty.add(key)
                return
            if key in self._queued:
                return
            self._queued.add(key)
            self._push_locked(key, delay)

    # tpulint: holds=_mu (only enqueue/_finish call it, lock held)
    def _push_locked(self, key: Hashable, delay: float) -> None:
        self._seq += 1
        now = time.monotonic()
        heapq.heappush(self._heap, _Scheduled(now + delay, self._seq, key,
                                              enq_at=now))
        self.metrics.adds_total.inc(self.name)
        self.metrics.depth.set(self.name, value=float(len(self._queued)))
        self._mu.notify_all()

    def start(self, workers: int = 1) -> None:
        with self._mu:
            # A queue may be stopped and started again (leadership lost then
            # regained); clear the stop flag or workers exit immediately and
            # enqueues are silently dropped.
            self._stopped = False
        for i in range(workers):
            t = threading.Thread(target=self._worker, name=f"{self.name}-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        with self._mu:
            self._stopped = True
            self._mu.notify_all()
        for t in self._threads:
            t.join(timeout=5)
        self._threads.clear()

    def drain(self, timeout: float = 10.0) -> bool:
        """Wait until the queue is empty and nothing is processing. For tests."""
        deadline = time.monotonic() + timeout
        with self._mu:
            while self._heap or self._processing or self._dirty:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._mu.wait(timeout=min(remaining, 0.1))
        return True

    def _pop(self) -> Optional[Hashable]:
        with self._mu:
            while not self._stopped:
                if self._heap:
                    item = self._heap[0]
                    now = time.monotonic()
                    if item.ready_at <= now:
                        heapq.heappop(self._heap)
                        self._queued.discard(item.key)
                        self._processing.add(item.key)
                        self.metrics.depth.set(
                            self.name, value=float(len(self._queued)))
                        self.metrics.queue_latency.observe(
                            self.name, value=now - item.enq_at)
                        return item.key
                    self._mu.wait(timeout=min(item.ready_at - now, 0.5))
                else:
                    self._mu.wait(timeout=0.5)
            return None

    def _worker(self) -> None:
        while True:
            key = self._pop()
            if key is None:
                return
            obj = self._latest.get(key)
            try:
                with self.metrics.work_seconds.time(self.name):
                    self._handler(key, obj)
            except Exception:  # noqa: BLE001 — reconcile errors retry by design
                log.exception("%s: handler failed for %r", self.name, key)
                self._finish(key, failed=True)
            else:
                self._finish(key, failed=False)

    def _finish(self, key: Hashable, failed: bool) -> None:
        with self._mu:
            self._processing.discard(key)
            if failed:
                n = self._retry_count.get(key, 0) + 1
                self._retry_count[key] = n
                if self._max_retries is not None and n > self._max_retries:
                    log.error("%s: dropping %r after %d retries", self.name, key, n - 1)
                    self._rl.forget(key)
                    self._retry_count.pop(key, None)
                    if key in self._dirty:
                        # A newer object arrived mid-failure: that's fresh
                        # work, not part of the exhausted retry series.
                        self._dirty.discard(key)
                        self._queued.add(key)
                        self._push_locked(key, 0.0)
                else:
                    self._dirty.discard(key)
                    self._queued.add(key)
                    self.metrics.retries_total.inc(self.name)
                    self._push_locked(key, self._rl.when(key))
            else:
                self._rl.forget(key)
                self._retry_count.pop(key, None)
                if key in self._dirty:
                    self._dirty.discard(key)
                    self._queued.add(key)
                    self._push_locked(key, 0.0)
            if key not in self._queued and key not in self._processing:
                # Nothing further scheduled for this key: drop its payload so
                # churning keys don't pin dead objects forever.
                self._latest.pop(key, None)
            self._mu.notify_all()
