"""Lease-based leader election over the API server.

The controller runs active-passive replicas; only the lease holder
reconciles, and the lease is released on clean shutdown so failover is
immediate (ReleaseOnCancel, /root/reference/cmd/compute-domain-controller/
main.go:313-414).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from k8s_dra_driver_tpu.k8s import APIServer, ConflictError, NotFoundError
from k8s_dra_driver_tpu.k8s.objects import K8sObject, new_meta
from k8s_dra_driver_tpu.pkg.metrics import Counter, Gauge, Registry

log = logging.getLogger(__name__)

LEASE = "Lease"


class LeaderElectionMetrics:
    """Transition counters + a held gauge, one series per lease, so an
    operator can see failover churn (clock slip, API partitions) that a
    point-in-time `is_leader` probe would hide."""

    def __init__(self, registry: Registry):
        self.transitions_total = registry.register(Counter(
            "tpu_dra_leader_election_transitions_total",
            "Leadership transitions, by direction (acquired/lost).",
            ("lease", "transition"),
        ))
        self.is_leader = registry.register(Gauge(
            "tpu_dra_leader_is_leader",
            "1 while this replica holds the lease.",
            ("lease",),
        ))


@dataclass
class Lease(K8sObject):
    kind: str = LEASE
    holder: str = ""
    acquired_at: float = 0.0
    renewed_at: float = 0.0
    lease_duration_s: float = 15.0


class LeaderElector:
    def __init__(
        self,
        api: APIServer,
        lease_name: str,
        identity: str,
        namespace: str = "kube-system",
        lease_duration_s: float = 15.0,
        retry_period_s: float = 2.0,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
        metrics_registry: Optional[Registry] = None,
    ):
        self.api = api
        self.lease_name = lease_name
        self.metrics = LeaderElectionMetrics(metrics_registry or Registry())
        self.namespace = namespace
        self.identity = identity
        self.lease_duration_s = lease_duration_s
        self.retry_period_s = retry_period_s
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._leading = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def is_leader(self) -> bool:
        return self._leading.is_set()

    def try_acquire_or_renew(self) -> bool:
        now = time.time()
        lease = self.api.try_get(LEASE, self.lease_name, self.namespace,
                                 copy=True)
        if lease is None:
            try:
                self.api.create(Lease(
                    meta=new_meta(self.lease_name, self.namespace),
                    holder=self.identity, acquired_at=now, renewed_at=now,
                    lease_duration_s=self.lease_duration_s,
                ))
                return True
            except Exception:  # noqa: BLE001 — racing creator
                return False
        expired = now - lease.renewed_at > lease.lease_duration_s
        if lease.holder != self.identity and not expired and lease.holder:
            return False
        lease.holder = self.identity
        lease.renewed_at = now
        if expired:
            lease.acquired_at = now
        try:
            self.api.update(lease)
            return True
        except (ConflictError, NotFoundError):
            return False

    def release(self) -> None:
        lease = self.api.try_get(LEASE, self.lease_name, self.namespace,
                                 copy=True)
        if lease is not None and lease.holder == self.identity:
            lease.holder = ""
            lease.renewed_at = 0.0
            try:
                self.api.update(lease)
            except (ConflictError, NotFoundError):
                pass

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name=f"leaderelect-{self.lease_name}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        if self._leading.is_set():
            self._leading.clear()
            self.release()
            self.metrics.transitions_total.inc(self.lease_name, "lost")
            self.metrics.is_leader.set(self.lease_name, value=0.0)
            if self.on_stopped_leading:
                self.on_stopped_leading()

    def _run(self) -> None:
        renew_period = min(self.retry_period_s, self.lease_duration_s / 3)
        while not self._stop.is_set():
            got = self.try_acquire_or_renew()
            if got and not self._leading.is_set():
                self._leading.set()
                self.metrics.transitions_total.inc(self.lease_name, "acquired")
                self.metrics.is_leader.set(self.lease_name, value=1.0)
                log.info("%s became leader of %s", self.identity, self.lease_name)
                if self.on_started_leading:
                    self.on_started_leading()
            elif not got and self._leading.is_set():
                # Lost the lease (e.g. clock slip / partition): crash-only
                # controllers exit here; we flag and call back.
                self._leading.clear()
                self.metrics.transitions_total.inc(self.lease_name, "lost")
                self.metrics.is_leader.set(self.lease_name, value=0.0)
                log.warning("%s lost leadership of %s", self.identity, self.lease_name)
                if self.on_stopped_leading:
                    self.on_stopped_leading()
            self._stop.wait(renew_period if got else self.retry_period_s)
