"""Shared libraries (L1): featuregates, flock, workqueue, metrics, bootid.

TPU-native re-design of the reference's pkg/{featuregates,flags,metrics,
flock,workqueue,bootid} (see SURVEY.md §2.3).
"""
