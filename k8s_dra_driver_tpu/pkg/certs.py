"""Self-signed serving-cert generation for the admission webhook.

The reference webhook serves HTTPS from --tls-cert-file/--tls-private-key-file
(/root/reference/cmd/webhook/main.go:83-129) and its chart injects the CA into
the ValidatingWebhookConfiguration's caBundle. Real clusters use cert-manager;
for self-contained installs and tests this module mints a CA + server cert
(SAN-based, as required since TLS 1.3 / Go 1.15-era verification) the same way
helm's genCA/genSignedCert sprig functions do.

Also usable as a one-shot CLI (the chart's cert-generation hook job):

    python -m k8s_dra_driver_tpu.pkg.certs --out-dir /certs \
        --san webhook-svc.kube-system.svc --san 127.0.0.1
"""

from __future__ import annotations

import argparse
import datetime
import ipaddress
import os
from dataclasses import dataclass
from typing import List, Tuple

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import rsa
from cryptography.x509.oid import NameOID

DEFAULT_DAYS = 365


@dataclass
class CertPaths:
    cert_file: str
    key_file: str
    ca_file: str

    def read_ca_pem(self) -> bytes:
        with open(self.ca_file, "rb") as f:
            return f.read()


def _key() -> rsa.RSAPrivateKey:
    return rsa.generate_private_key(public_exponent=65537, key_size=2048)


def _name(cn: str) -> x509.Name:
    return x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])


def _san_entries(sans: List[str]) -> x509.SubjectAlternativeName:
    entries: List[x509.GeneralName] = []
    for san in sans:
        try:
            entries.append(x509.IPAddress(ipaddress.ip_address(san)))
        except ValueError:
            entries.append(x509.DNSName(san))
    return x509.SubjectAlternativeName(entries)


def generate_ca(
    common_name: str = "tpu-dra-webhook-ca", days: int = DEFAULT_DAYS
) -> Tuple[x509.Certificate, rsa.RSAPrivateKey]:
    key = _key()
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(_name(common_name))
        .issuer_name(_name(common_name))
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=days))
        .add_extension(x509.BasicConstraints(ca=True, path_length=0), critical=True)
        .add_extension(
            x509.KeyUsage(
                digital_signature=True, key_cert_sign=True, crl_sign=True,
                content_commitment=False, key_encipherment=False,
                data_encipherment=False, key_agreement=False,
                encipher_only=False, decipher_only=False,
            ),
            critical=True,
        )
        .sign(key, hashes.SHA256())
    )
    return cert, key


def generate_server_cert(
    ca_cert: x509.Certificate,
    ca_key: rsa.RSAPrivateKey,
    sans: List[str],
    common_name: str = "",
    days: int = DEFAULT_DAYS,
) -> Tuple[x509.Certificate, rsa.RSAPrivateKey]:
    if not sans:
        raise ValueError("server cert needs at least one SAN")
    key = _key()
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(_name(common_name or sans[0]))
        .issuer_name(ca_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=days))
        .add_extension(_san_entries(sans), critical=False)
        .add_extension(
            x509.ExtendedKeyUsage([x509.oid.ExtendedKeyUsageOID.SERVER_AUTH]),
            critical=False,
        )
        .sign(ca_key, hashes.SHA256())
    )
    return cert, key


def _pem_cert(cert: x509.Certificate) -> bytes:
    return cert.public_bytes(serialization.Encoding.PEM)


def _pem_key(key: rsa.RSAPrivateKey) -> bytes:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )


def write_webhook_certs(
    out_dir: str, sans: List[str], days: int = DEFAULT_DAYS
) -> CertPaths:
    """Mint CA + server cert; write tls.crt / tls.key / ca.crt (the k8s TLS
    secret layout). Returns the paths."""
    os.makedirs(out_dir, exist_ok=True)
    ca_cert, ca_key = generate_ca(days=days)
    cert, key = generate_server_cert(ca_cert, ca_key, sans, days=days)
    paths = CertPaths(
        cert_file=os.path.join(out_dir, "tls.crt"),
        key_file=os.path.join(out_dir, "tls.key"),
        ca_file=os.path.join(out_dir, "ca.crt"),
    )
    for path, data in (
        (paths.cert_file, _pem_cert(cert)),
        (paths.key_file, _pem_key(key)),
        (paths.ca_file, _pem_cert(ca_cert)),
    ):
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "wb") as f:
            f.write(data)
    return paths


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        "tpu-dra-certs", description="mint webhook serving certs (CA + server)"
    )
    parser.add_argument("--out-dir", required=True)
    parser.add_argument("--san", action="append", default=[],
                        help="DNS name or IP; repeatable")
    parser.add_argument("--days", type=int, default=DEFAULT_DAYS)
    args = parser.parse_args(argv)
    paths = write_webhook_certs(args.out_dir, args.san or ["localhost", "127.0.0.1"],
                                days=args.days)
    print(f"wrote {paths.cert_file} {paths.key_file} {paths.ca_file}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
