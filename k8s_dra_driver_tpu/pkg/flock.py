"""File-based advisory locks with timeout/poll semantics.

Used for the node-global prepare/unprepare lock (``pu.lock``) that serializes
claim preparation across plugin *processes*, and the checkpoint lock
(``cp.lock``) guarding read-modify-write of the checkpoint file.

Reference behavior: /root/reference/pkg/flock/flock.go:27-136 (syscall flock
with timeout/poll options); lock usage at
/root/reference/cmd/gpu-kubelet-plugin/driver.go:43-46,388-395.
"""

from __future__ import annotations

import errno
import fcntl
import os
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Iterator, Optional


class FlockTimeoutError(TimeoutError):
    """Raised when the lock could not be acquired within the timeout."""


@dataclass
class Flock:
    """An advisory exclusive lock on a filesystem path.

    The lock file is created if missing and never deleted (deleting a lock
    file while another process holds its fd open would split the lock).
    """

    path: str
    poll_interval: float = 0.05

    def __post_init__(self) -> None:
        self._fd: Optional[int] = None

    @property
    def held(self) -> bool:
        return self._fd is not None

    def acquire(self, timeout: Optional[float] = None) -> None:
        """Acquire the lock, blocking up to ``timeout`` seconds.

        ``timeout=None`` blocks indefinitely; ``timeout=0`` is a single
        non-blocking attempt.
        """
        if self._fd is not None:
            raise RuntimeError(f"flock {self.path!r} already held by this object")
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        fd = os.open(self.path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            if timeout is None:
                fcntl.flock(fd, fcntl.LOCK_EX)
            else:
                deadline = time.monotonic() + timeout
                while True:
                    try:
                        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                        break
                    except OSError as e:
                        if e.errno not in (errno.EAGAIN, errno.EACCES):
                            raise
                        if time.monotonic() >= deadline:
                            raise FlockTimeoutError(
                                f"timed out after {timeout}s acquiring {self.path!r}"
                            ) from None
                        time.sleep(self.poll_interval)
        except BaseException:
            os.close(fd)
            raise
        self._fd = fd

    def release(self) -> None:
        if self._fd is None:
            raise RuntimeError(f"flock {self.path!r} not held")
        fd, self._fd = self._fd, None
        try:
            fcntl.flock(fd, fcntl.LOCK_UN)
        finally:
            os.close(fd)

    @contextmanager
    def hold(self, timeout: Optional[float] = None,
             trace_name: str = "") -> Iterator["Flock"]:
        """Acquire-for-scope. With ``trace_name`` set, the wait and the
        critical section become separate child spans
        (``<trace_name>.acquire`` / ``<trace_name>.hold``) — the flock
        wait vs hold split the batched prepare pipeline's telemetry
        reads (contention shows up in acquire, lock-amortized work in
        hold)."""
        if not trace_name:
            self.acquire(timeout=timeout)
            try:
                yield self
            finally:
                self.release()
            return
        from k8s_dra_driver_tpu.pkg.tracing import span

        with span(f"{trace_name}.acquire", path=self.path):
            self.acquire(timeout=timeout)
        try:
            with span(f"{trace_name}.hold", path=self.path):
                yield self
        finally:
            self.release()
