"""Dependency-free claim-lifecycle tracing.

Spans carry a trace id / span id pair and nest through a thread-local
context stack, so one NodePrepareResources batch (or one controller
reconcile, one scheduler pass, one clique sync) becomes a tree the
operator can read as a timeline. Finished spans land in a bounded
in-memory ring buffer and are exported as Chrome trace-event JSON —
loadable in Perfetto / chrome://tracing — via ``MetricsServer``'s
``/debug/traces`` endpoint and the ``python -m k8s_dra_driver_tpu.sim
trace <claim-uid>`` timeline command.

Design constraints, in the spirit of the rest of ``pkg/``:

- stdlib only (the kubelet plugin images carry no OTel SDK);
- always on: recording a span is two monotonic reads, a dict, and one
  deque append under a lock — cheap enough for the prepare hot path the
  PR 1 batching work created (flock hold, checkpoint fsync, CDI fan-out);
- bounded: the ring buffer drops the oldest trace data instead of
  growing, like the reference's pprof ring buffers;
- explicit cross-thread propagation: thread-local context does not leak
  into worker pools; callers capture ``current()`` and pass it as
  ``parent=`` (the batched CDI materialization fan-out does exactly
  this).

Log correlation: ``TraceContextFilter`` stamps ``trace_id``/``span_id``
onto every LogRecord emitted under an active span, and the JSON log
formatter (pkg/flags) includes them, so a log line and its span join on
one id.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

import logging

# Default ring capacity: at ~300 bytes/span this is a few MiB ceiling —
# roughly the last several thousand prepare batches worth of spans.
DEFAULT_CAPACITY = 8192

# Attribute keys the claim-lifecycle timeline joins on: a span is "about"
# a claim when claim_uid equals it or claim_uids contains it.
ATTR_CLAIM_UID = "claim_uid"
ATTR_CLAIM_UIDS = "claim_uids"

# Attribute naming the cluster a span ran in. Stamped by the federation
# layer so a merged cross-cluster Chrome export still says where each
# span happened (`/debug/traces` cross-links).
ATTR_CLUSTER = "cluster"

# Cross-boundary propagation: a trace context stamped onto an object's
# annotations survives WAL replication and kind-agnostic copies, so a
# follower-region controller picking the object up can parent its spans
# (and therefore its DecisionRecords/Events) under the fleet-level
# decision that routed the object there. Format: "<trace_id>:<span_id>".
TRACE_CONTEXT_ANNOTATION = "tpu.google.com/trace-context"


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


@dataclass(frozen=True)
class SpanContext:
    """The propagatable half of a span: what a child (possibly on another
    thread) needs to attach itself to the tree."""

    trace_id: str
    span_id: str


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: str = ""
    start_time: float = 0.0       # wall clock, seconds since epoch
    duration: float = 0.0         # seconds
    attrs: Dict[str, Any] = field(default_factory=dict)
    status: str = "ok"            # ok | error
    error: str = ""
    thread: str = ""

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def about_claim(self, claim_uid: str) -> bool:
        if self.attrs.get(ATTR_CLAIM_UID) == claim_uid:
            return True
        uids = self.attrs.get(ATTR_CLAIM_UIDS)
        return bool(uids) and claim_uid in uids

    def to_chrome_event(self) -> Dict[str, Any]:
        """One complete ("ph": "X") Chrome trace event; ts/dur in µs."""
        args = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.parent_id:
            args["parent_id"] = self.parent_id
        if self.status != "ok":
            args["status"] = self.status
            args["error"] = self.error
        args.update(self.attrs)
        return {
            "name": self.name,
            "ph": "X",
            "ts": self.start_time * 1e6,
            "dur": self.duration * 1e6,
            "pid": os.getpid(),
            "tid": self.thread,
            "cat": "tpu-dra",
            "args": args,
        }


class Tracer:
    """Span factory + bounded in-memory exporter."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = capacity
        self._mu = threading.Lock()
        self._spans: List[Span] = []  # tpulint: guarded-by=_mu
        self._dropped = 0  # tpulint: guarded-by=_mu
        self._dropped_reported = 0  # tpulint: guarded-by=_mu
        self._local = threading.local()
        self._dropped_total = None   # Counter once attach_metrics() runs
        self._utilization_gauge = None

    # -- context -------------------------------------------------------------

    def _stack(self) -> List[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def current(self) -> Optional[SpanContext]:
        """This thread's active span context, or None outside any span."""
        stack = self._stack()
        return stack[-1].context if stack else None

    # -- recording -----------------------------------------------------------

    @contextmanager
    def span(self, name: str, parent: Optional[SpanContext] = None,
             **attrs: Any) -> Iterator[Span]:
        """Open a span. Nesting is automatic within a thread; pass
        ``parent=`` (a ``SpanContext`` captured with ``current()``) to
        attach work running on another thread to the same trace."""
        ctx = parent if parent is not None else self.current()
        sp = Span(
            name=name,
            trace_id=ctx.trace_id if ctx else _new_id(8),
            span_id=_new_id(4),
            parent_id=ctx.span_id if ctx else "",
            start_time=time.time(),
            attrs=dict(attrs),
            thread=threading.current_thread().name,
        )
        stack = self._stack()
        stack.append(sp)
        t0 = time.perf_counter()
        try:
            yield sp
        except BaseException as e:
            sp.status = "error"
            sp.error = f"{type(e).__name__}: {e}"
            raise
        finally:
            sp.duration = time.perf_counter() - t0
            stack.pop()
            with self._mu:
                self._spans.append(sp)
                if len(self._spans) > self.capacity:
                    # Amortized trim: drop the oldest tenth in one slice
                    # instead of popping per append. Every span dropped
                    # here is ACCOUNTED — silent loss made post-hoc
                    # debugging lie about what the ring ever held.
                    del self._spans[: max(1, self.capacity // 10)]
                    self._dropped += max(1, self.capacity // 10)
                report = 0
                if self._dropped_total is not None:
                    report = self._dropped - self._dropped_reported
                    self._dropped_reported = self._dropped
                utilization = len(self._spans) / max(1, self.capacity)
            if report:
                self._dropped_total.inc(by=float(report))
            if self._utilization_gauge is not None:
                self._utilization_gauge.set(value=utilization)

    # -- metrics -------------------------------------------------------------

    def attach_metrics(self, registry) -> None:
        """Register the span-loss accounting on ``registry`` (get-or-
        create, so re-attaching the same registry is idempotent)."""
        from k8s_dra_driver_tpu.pkg.metrics import Counter, Gauge

        self._dropped_total = registry.register(Counter(
            "tpu_dra_trace_spans_dropped_total",
            "Finished spans evicted from the bounded trace ring to make "
            "room for newer ones (each was silently lost before this "
            "counter existed)."))
        self._utilization_gauge = registry.register(Gauge(
            "tpu_dra_trace_ring_utilization",
            "Fill fraction of the bounded span ring (0-1); sawtooths "
            "between 0.9 and 1.0 once eviction starts."))
        with self._mu:
            # Backfill only drops not yet reported: re-attaching the
            # same registry must not double-count the backlog.
            backlog = self._dropped - self._dropped_reported
            self._dropped_reported = self._dropped
            utilization = len(self._spans) / max(1, self.capacity)
        if backlog:
            self._dropped_total.inc(by=float(backlog))
        self._utilization_gauge.set(value=utilization)

    def dropped_count(self) -> int:
        """Spans evicted from the ring since construction."""
        with self._mu:
            return self._dropped

    def utilization(self) -> float:
        with self._mu:
            return len(self._spans) / max(1, self.capacity)

    # -- reads ---------------------------------------------------------------

    def spans(self, trace_id: Optional[str] = None,
              name: Optional[str] = None) -> List[Span]:
        with self._mu:
            snap = list(self._spans)
        if trace_id is not None:
            snap = [s for s in snap if s.trace_id == trace_id]
        if name is not None:
            snap = [s for s in snap if s.name == name]
        return snap

    def traces_for_claim(self, claim_uid: str) -> List[Span]:
        """Every span of every trace that touched ``claim_uid`` — the
        whole tree, not just the tagged spans, so the timeline shows the
        flock/fsync/CDI children around the tagged batch span."""
        snap = self.spans()
        trace_ids = {s.trace_id for s in snap if s.about_claim(claim_uid)}
        return [s for s in snap if s.trace_id in trace_ids]

    def clear(self) -> None:
        with self._mu:
            self._spans.clear()

    # -- export --------------------------------------------------------------

    def export_chrome(self, spans: Optional[List[Span]] = None) -> Dict[str, Any]:
        """The Chrome trace-event JSON document (object form, complete
        events) Perfetto and chrome://tracing both load."""
        if spans is None:
            spans = self.spans()
        return {
            "displayTimeUnit": "ms",
            # Ring-eviction accounting rides the payload so a dump that
            # LOOKS complete declares what it no longer holds.
            "spansDropped": self.dropped_count(),
            "traceEvents": [s.to_chrome_event() for s in spans],
        }

    def export_chrome_json(self, spans: Optional[List[Span]] = None) -> bytes:
        return json.dumps(self.export_chrome(spans)).encode()


# -- module-level default tracer ---------------------------------------------

_default_tracer = Tracer()


def get_tracer() -> Tracer:
    return _default_tracer


def current() -> Optional[SpanContext]:
    return _default_tracer.current()


def span(name: str, parent: Optional[SpanContext] = None, **attrs: Any):
    """Open a span on the process-default tracer (the common case: every
    component in one binary shares one ring buffer, like one /metrics
    registry)."""
    return _default_tracer.span(name, parent=parent, **attrs)


# -- cross-boundary propagation (object annotations) ---------------------------


def inject_context(annotations: Dict[str, str],
                   ctx: Optional[SpanContext] = None) -> Dict[str, str]:
    """Stamp ``ctx`` (default: this thread's active span) into an
    annotation map so the trace follows the object — across the store,
    across the replication WAL, across clusters. No-op without a
    context. Returns the map for chaining."""
    if ctx is None:
        ctx = _default_tracer.current()
    if ctx is not None:
        annotations[TRACE_CONTEXT_ANNOTATION] = \
            f"{ctx.trace_id}:{ctx.span_id}"
    return annotations


def extract_context(
        annotations: Optional[Dict[str, str]]) -> Optional[SpanContext]:
    """The inverse of :func:`inject_context`: the propagated parent
    context carried by an object's annotations, or None. Malformed
    values are ignored (an annotation is user-writable state)."""
    raw = (annotations or {}).get(TRACE_CONTEXT_ANNOTATION, "")
    trace_id, sep, span_id = raw.partition(":")
    if not sep or not trace_id or not span_id:
        return None
    return SpanContext(trace_id=trace_id, span_id=span_id)


# -- log correlation ----------------------------------------------------------


class TraceContextFilter(logging.Filter):
    """Stamps trace_id/span_id from the active span onto LogRecords, so
    structured log lines and trace spans correlate on one id. Outside a
    span both fields are empty strings (never missing — formatters can
    reference them unconditionally)."""

    def __init__(self, tracer: Optional[Tracer] = None):
        super().__init__()
        self._tracer = tracer or _default_tracer

    def filter(self, record: logging.LogRecord) -> bool:
        ctx = self._tracer.current()
        record.trace_id = ctx.trace_id if ctx else ""
        record.span_id = ctx.span_id if ctx else ""
        return True


# -- timeline rendering (sim `trace` command, debug dumps) --------------------


def render_timeline(spans: List[Span]) -> str:
    """ASCII timeline of one or more traces: spans sorted by start time,
    indented by parent depth, with offsets relative to each trace's root."""
    if not spans:
        return "(no spans)"
    out: List[str] = []
    by_trace: Dict[str, List[Span]] = {}
    for s in spans:
        by_trace.setdefault(s.trace_id, []).append(s)
    for trace_id in sorted(by_trace, key=lambda t: min(s.start_time for s in by_trace[t])):
        group = sorted(by_trace[trace_id], key=lambda s: (s.start_time, s.span_id))
        t0 = group[0].start_time
        total_ms = max((s.start_time - t0) * 1e3 + s.duration * 1e3 for s in group)
        out.append(f"trace {trace_id} ({len(group)} spans, {total_ms:.3f}ms)")
        parents = {s.span_id: s.parent_id for s in group}

        def depth(s: Span) -> int:
            d, pid, seen = 0, s.parent_id, set()
            while pid and pid in parents and pid not in seen:
                seen.add(pid)
                d += 1
                pid = parents[pid]
            return d

        for s in group:
            off_ms = (s.start_time - t0) * 1e3
            attrs = " ".join(
                f"{k}={v}" for k, v in sorted(s.attrs.items())
                if k != ATTR_CLAIM_UIDS
            )
            uids = s.attrs.get(ATTR_CLAIM_UIDS)
            if uids:
                attrs = (attrs + f" claims={len(uids)}").strip()
            err = f" ERROR({s.error})" if s.status != "ok" else ""
            out.append(
                f"  {off_ms:9.3f}ms {'  ' * depth(s)}- {s.name} "
                f"({s.duration * 1e3:.3f}ms){(' ' + attrs) if attrs else ''}{err}"
            )
    return "\n".join(out)


def spans_from_chrome(doc: Dict[str, Any]) -> List[Span]:
    """Rebuild Span objects from an exported Chrome trace document (the
    sim `trace` command consumes dumps fetched from /debug/traces)."""
    spans: List[Span] = []
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        args = dict(ev.get("args", {}))
        trace_id = args.pop("trace_id", "")
        span_id = args.pop("span_id", "")
        parent_id = args.pop("parent_id", "")
        status = args.pop("status", "ok")
        error = args.pop("error", "")
        spans.append(Span(
            name=ev.get("name", ""),
            trace_id=trace_id,
            span_id=span_id,
            parent_id=parent_id,
            start_time=float(ev.get("ts", 0.0)) / 1e6,
            duration=float(ev.get("dur", 0.0)) / 1e6,
            attrs=args,
            status=status,
            error=error,
            thread=str(ev.get("tid", "")),
        ))
    return spans
