"""Topology-aware placement engine: bitmask subslice tables + host-grid math.

Two layers, both precomputed once per topology and shared by reference:

1. **Chip-level placement tables** (``PlacementTables``): every legal
   subslice placement of a host topology — the same enumeration
   ``compute_subslice_profiles`` feeds the kubelet plugin — becomes an int
   chip-bitmask, with per-placement conflict masks (pairwise chip-set
   intersection, the exact overlap rule ``DeviceState._validate_no_overlap``
   enforces at Prepare time) and per-profile candidate lists. Overlap and
   feasibility questions collapse to a single AND + popcount, and the
   allocator can score a candidate device by how many *surviving
   larger-profile* placements it would destroy — the fragmentation-aware
   best-fit that keeps large ICI-contiguous subslices placeable under mixed
   workloads (the MIG-fragmentation failure mode Flex-MIG/MISO document).

2. **Host-grid block planning** (``choose_host_block``): a multi-host
   ComputeDomain needs hosts that are *grid-adjacent* within one ICI
   domain, not just "N free hosts". Given each candidate host's ici-domain
   and host-grid coordinate (published as ResourceSlice attributes), the
   planner enumerates contiguous axis-aligned blocks of the requested size
   and returns the most compact one that is entirely free.

Dependency-free (stdlib + tpulib types only) so both the sim allocator and
the node plugins can use it.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from functools import lru_cache
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from k8s_dra_driver_tpu.tpulib.profiles import (  # noqa: F401 — re-exported
    compute_subslice_profiles,
    host_grid_coord,
    host_grid_dims,
)
from k8s_dra_driver_tpu.tpulib.types import (
    format_topology,
    parse_topology,
    topology_chips,
)


def popcount(x: int) -> int:
    return x.bit_count()


def chips_to_mask(chips: Iterable[int]) -> int:
    mask = 0
    for c in chips:
        mask |= 1 << c
    return mask


def chip_bits_of_device(dev) -> int:
    """Chip-bitmask of an API ``Device``, derived from the counters it
    consumes: every ``chip-<i>`` counter is one bit. This is the same
    derivation rule the per-host CounterSet encodes (deviceinfo.py) and the
    chip-index overlap rule DeviceState enforces, so two devices overlap
    iff their masks AND to non-zero. Devices consuming no chip counters
    (channels, daemons) map to 0."""
    bits = 0
    for cc in dev.consumes_counters:
        for cname in cc.counters:
            if cname.startswith("chip-"):
                idx = cname[5:]
                if idx.isdigit():
                    bits |= 1 << int(idx)
    return bits


@dataclass(frozen=True)
class Placement:
    """One legal placement: a profile shape at a fixed origin, as chips and
    as a bitmask. ``index`` is the bit position in placement-set bitmaps."""

    index: int
    profile: str                 # "1x2", or the host topology for whole-host
    chips: Tuple[int, ...]
    mask: int

    @property
    def num_chips(self) -> int:
        return len(self.chips)


class PlacementTables:
    """Precomputed bitmask tables for one host topology.

    ``placements`` covers every subslice placement from
    ``compute_subslice_profiles`` plus one synthetic *whole-host* placement
    (all chips) so fragmentation scoring accounts for destroying whole-host
    capacity — the shape multi-host ComputeDomain workers claim.

    Two bitmap spaces:
    - chip masks: bit i = host-local chip i (``mask`` fields);
    - placement-set bitmaps: bit k = placement with ``index`` k
      (``conflicts``/``larger_conflicts``/``surviving()`` results).
    """

    def __init__(self, host_topology: str):
        self.host_topology = host_topology
        self.dims = parse_topology(host_topology)
        self.num_chips = topology_chips(host_topology)
        self.full_mask = (1 << self.num_chips) - 1
        placements: List[Placement] = []
        for prof in compute_subslice_profiles(host_topology):
            for pl in prof.placements:
                placements.append(Placement(
                    index=len(placements), profile=prof.name,
                    chips=tuple(pl.chip_indices),
                    mask=chips_to_mask(pl.chip_indices),
                ))
        # Whole-host rides along as the largest "profile": not a subslice
        # device, but the capacity unit large claims consume and the one
        # fragmentation destroys first.
        self.whole_host_index = len(placements)
        placements.append(Placement(
            index=self.whole_host_index, profile=host_topology,
            chips=tuple(range(self.num_chips)), mask=self.full_mask,
        ))
        self.placements: Tuple[Placement, ...] = tuple(placements)
        by_profile: Dict[str, List[int]] = {}
        by_mask: Dict[int, int] = {}
        for p in self.placements:
            by_profile.setdefault(p.profile, []).append(p.index)
            by_mask[p.mask] = p.index
        self.by_profile: Dict[str, Tuple[int, ...]] = {
            k: tuple(v) for k, v in by_profile.items()
        }
        self.by_mask = by_mask
        # conflicts[i]: placement-set bitmap of every OTHER placement whose
        # chip set intersects placement i's (== chip-mask AND != 0).
        # larger_conflicts[i]: same, restricted to strictly-larger profiles
        # — the "surviving larger placements destroyed" term of the
        # best-fit score.
        conflicts = [0] * len(self.placements)
        larger = [0] * len(self.placements)
        for a in self.placements:
            for b in self.placements:
                if a.index != b.index and (a.mask & b.mask):
                    conflicts[a.index] |= 1 << b.index
                    if b.num_chips > a.num_chips:
                        larger[a.index] |= 1 << b.index
        self.conflicts: Tuple[int, ...] = tuple(conflicts)
        self.larger_conflicts: Tuple[int, ...] = tuple(larger)
        self.all_placements_bitmap = (1 << len(self.placements)) - 1

    def surviving(self, used_mask: int,
                  available: Optional[int] = None) -> int:
        """Placement-set bitmap of placements still placeable: available
        (device published and untainted) and with every chip free."""
        if available is None:
            available = self.all_placements_bitmap
        out = 0
        for p in self.placements:
            if (available >> p.index) & 1 and not (p.mask & used_mask):
                out |= 1 << p.index
        return out

    def frag_score(self, chip_mask: int, surviving: int) -> int:
        """How many surviving strictly-larger placements choosing
        ``chip_mask`` would destroy (lower = better packing). A mask that
        is itself a table placement uses its precomputed conflict set (one
        AND + popcount); an arbitrary mask falls back to a scan."""
        idx = self.by_mask.get(chip_mask)
        if idx is not None:
            return popcount(self.larger_conflicts[idx] & surviving)
        n = popcount(chip_mask)
        score = 0
        rest = surviving
        while rest:
            low = rest & -rest
            p = self.placements[low.bit_length() - 1]
            if p.num_chips > n and (p.mask & chip_mask):
                score += 1
            rest ^= low
        return score

    def largest_free_chips(self, used_mask: int,
                           available: Optional[int] = None) -> int:
        """Chips in the largest still-placeable profile (whole-host
        included) — the per-node fragmentation signal
        ``tpu_dra_node_frag_largest_free_profile`` exports."""
        best = 0
        rest = self.surviving(used_mask, available)
        while rest:
            low = rest & -rest
            p = self.placements[low.bit_length() - 1]
            if p.num_chips > best:
                best = p.num_chips
            rest ^= low
        return best


@lru_cache(maxsize=64)
def tables_for(host_topology: str) -> PlacementTables:
    """Memoized per-topology tables: a 64-node cluster of identical hosts
    builds ONE table, not 64."""
    return PlacementTables(host_topology)


# -- host-grid math ----------------------------------------------------------
# host_grid_dims / host_grid_coord are re-exported from tpulib.profiles —
# ONE tiling rule shared by SliceProfile.host_grid, the tpulibs' chip-block
# origins, the published hostCoord attribute, and the block planner below.


def _block_shapes(grid: Tuple[int, ...], n: int) -> List[Tuple[int, ...]]:
    """Axis-aligned block shapes of volume n fitting the grid, most compact
    first (smallest max dimension — fewest ICI hops across the block)."""
    shapes = set()
    for dims in itertools.product(*(range(1, g + 1) for g in grid)):
        vol = 1
        for d in dims:
            vol *= d
        if vol == n:
            shapes.add(dims)
    return sorted(shapes, key=lambda s: (max(s), s))


@dataclass(frozen=True)
class HostBlock:
    """A chosen contiguous host set within one ICI domain's host grid."""

    ici_domain: str
    origin: Tuple[int, ...]
    shape: Tuple[int, ...]
    nodes: Tuple[str, ...]       # row-major over the block's coordinates

    @property
    def origin_str(self) -> str:
        return format_topology(self.origin) if len(self.origin) > 1 else str(
            self.origin[0])

    @property
    def shape_str(self) -> str:
        return format_topology(self.shape) if len(self.shape) > 1 else str(
            self.shape[0])


def iter_host_blocks(
    topologies: Dict[str, dict],
    candidate_nodes: Sequence[str],
    num_nodes: int,
):
    """Yield every contiguous host-grid block of ``num_nodes`` candidate
    hosts, in the deterministic preference order ``choose_host_block``
    documents: ICI domains in the order their first candidate appears in
    ``candidate_nodes``, block shapes most-compact-first, origins
    ascending. The live-repack planner consumes the full enumeration to
    rank blocks by how many claims must migrate to vacate them; the
    scheduler takes the first fully-free one."""
    cands = [n for n in candidate_nodes if n in topologies]
    if num_nodes <= 0 or len(cands) < num_nodes:
        return
    domains: Dict[str, Dict[Tuple[int, ...], str]] = {}
    domain_order: List[str] = []
    for node in cands:
        info = topologies[node]
        dom = info.get("ici_domain", "")
        coord = info.get("host_coord")
        if coord is None:
            continue
        if dom not in domains:
            domains[dom] = {}
            domain_order.append(dom)
        domains[dom][tuple(coord)] = node
    for dom in domain_order:
        coords = domains[dom]
        if len(coords) < num_nodes:
            continue
        any_node = next(iter(coords.values()))
        info = topologies[any_node]
        try:
            grid = host_grid_dims(info["slice_topology"],
                                  info["host_topology"])
        except (KeyError, ValueError, TypeError):
            # Missing/None topology strings must degrade to "no block in
            # this domain", never abort the caller's pass.
            continue
        for shape in _block_shapes(grid, num_nodes):
            for origin in itertools.product(
                    *(range(g - s + 1) for g, s in zip(grid, shape))):
                cells = list(itertools.product(
                    *(range(o, o + s) for o, s in zip(origin, shape))))
                if all(c in coords for c in cells):
                    yield HostBlock(
                        ici_domain=dom, origin=tuple(origin), shape=shape,
                        nodes=tuple(coords[c] for c in cells),
                    )


def choose_host_block(
    topologies: Dict[str, dict],
    free_nodes: Sequence[str],
    num_nodes: int,
) -> Optional[HostBlock]:
    """Pick a contiguous host-grid block of ``num_nodes`` free hosts.

    ``topologies``: node -> {"ici_domain", "slice_topology",
    "host_topology", "host_coord" (tuple)} — the ResourceSlice attribute
    surface. ``free_nodes``: nodes the feasibility filter admitted for the
    domain's whole-host claim, in preference order.

    Deterministic choice: ICI domains in the order their first free node
    appears in ``free_nodes`` preference order (name order on ties), block
    shapes most-compact-first, origins ascending. Returns None when no
    domain holds a fully-free block of the requested size (the scheduler
    then degrades to unaligned placement rather than deadlocking)."""
    return next(iter_host_blocks(topologies, free_nodes, num_nodes), None)
