"""One retry-backoff policy for every retry loop in the control plane.

Before this module each retrying actor rolled its own loop: the workqueue
had an exponential limiter plus a *random* jitter wrapper, the rebalancer
retried failed migrations at full pass rate, and ad-hoc ``for _ in
range(n)`` loops hid everywhere. This consolidates the policy:

- **Capped exponential**: delay ``base * 2^k`` growing per consecutive
  failure of a key, capped at ``cap``. ``first_free=True`` (the
  pass-driven loops) makes the FIRST failure free — a single transient
  error retries on the very next pass, the clamp only kicks in once a
  key is *repeatedly* failing; ``first_free=False`` keeps the k8s
  ItemExponentialFailureRateLimiter shape (first failure already waits
  ``base``) for the workqueue path.
- **Deterministic jitter**: the classic thundering-herd scaling factor in
  ``[1-jitter, 1+jitter]``, derived from a CRC of ``(key, attempt)``
  instead of an RNG. Two actors retrying different keys still
  decorrelate, but a seeded sim run — and a test asserting on retry
  timing — reproduces exactly.
- **Per-key reset on success**: one success forgets the key's failure
  history entirely (the k8s rate-limiter ``Forget`` contract).

Every computed delay is observed into the shared
``tpu_dra_retry_backoff_seconds`` histogram (label: ``source``), so an
operator can see *which* retry loop is spinning from one scrape.
"""

from __future__ import annotations

import threading
import time
import zlib
from typing import Callable, Dict, Hashable, Optional

from k8s_dra_driver_tpu.pkg.metrics import Histogram, Registry

# Envelope sized for retry delays: 10ms .. ~10min.
BACKOFF_SECONDS_BUCKETS = tuple(0.01 * (4 ** k) for k in range(9))


class BackoffMetrics:
    """The shared backoff histogram; get-or-create on the registry so
    every adopting loop (workqueue, rebalancer, resize orchestrator)
    lands series in ONE family, split by ``source``."""

    def __init__(self, registry: Registry):
        self.backoff_seconds = registry.register(Histogram(
            "tpu_dra_retry_backoff_seconds",
            "Computed retry-backoff delays, by retry-loop source "
            "(workqueue name, rebalancer, resize).",
            ("source",),
            buckets=BACKOFF_SECONDS_BUCKETS,
        ))


def deterministic_jitter(key: Hashable, attempt: int, jitter: float) -> float:
    """Scaling factor in [1-jitter, 1+jitter], a pure function of
    (key, attempt) — reproducible across runs, decorrelated across keys."""
    if jitter <= 0.0:
        return 1.0
    h = zlib.crc32(f"{key!r}:{attempt}".encode())
    frac = (h % 10_000) / 10_000.0            # [0, 1)
    return 1.0 + jitter * (2.0 * frac - 1.0)


class Backoff:
    """Per-key capped-exponential backoff with eligibility tracking.

    Two usage styles, sharing one failure ledger:

    - ``failure(key) -> delay``: record a failure and get the next delay
      (what a delayed queue feeds its scheduler) — the workqueue style.
    - ``failure(key)`` then ``ready(key)``: record failures and poll
      eligibility against ``clock`` — the pass-driven style (rebalancer,
      resize orchestrator), where the actor visits the key every pass
      and must *skip* it until the backoff elapsed.

    ``reset(key)`` on success forgets everything about the key.
    Thread-safe.
    """

    def __init__(
        self,
        base: float = 1.0,
        cap: float = 600.0,
        jitter: float = 0.2,
        clock: Callable[[], float] = time.monotonic,
        metrics: Optional[BackoffMetrics] = None,
        source: str = "",
        first_free: bool = True,
    ):
        if base < 0 or cap < 0:
            raise ValueError(f"base/cap must be >= 0, got {base}/{cap}")
        if not 0.0 <= jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {jitter}")
        self.base = base
        self.cap = cap
        self.jitter = jitter
        self.clock = clock
        self.metrics = metrics
        self.source = source
        self.first_free = first_free
        self._mu = threading.Lock()
        self._failures: Dict[Hashable, int] = {}  # tpulint: guarded-by=_mu
        self._eligible_at: Dict[Hashable, float] = {}  # tpulint: guarded-by=_mu

    def delay_for(self, key: Hashable, failures: int) -> float:
        """The pure policy: delay after the ``failures``-th consecutive
        failure of ``key``, jittered and capped."""
        exponent = failures - 2 if self.first_free else failures - 1
        if exponent < 0:
            return 0.0
        raw = min(self.base * (2.0 ** exponent), self.cap)
        return min(raw * deterministic_jitter(key, failures, self.jitter),
                   self.cap)

    def failure(self, key: Hashable) -> float:
        """Record one failure; returns (and observes) the delay before the
        key should be retried."""
        with self._mu:
            n = self._failures.get(key, 0) + 1
            self._failures[key] = n
            delay = self.delay_for(key, n)
            self._eligible_at[key] = self.clock() + delay
        if self.metrics is not None:
            self.metrics.backoff_seconds.observe(self.source, value=delay)
        return delay

    def ready(self, key: Hashable) -> bool:
        """True when the key may be retried now (or was never failed)."""
        with self._mu:
            at = self._eligible_at.get(key)
        return at is None or self.clock() >= at

    def pending(self) -> int:
        """How many keys are currently backoff-blocked — the signal a
        deterministic sim folds into its quiescence token so it keeps
        stepping while a retry is still owed."""
        now = self.clock()
        with self._mu:
            return sum(1 for at in self._eligible_at.values() if at > now)

    def failures(self, key: Hashable) -> int:
        with self._mu:
            return self._failures.get(key, 0)

    def reset(self, key: Hashable) -> None:
        with self._mu:
            self._failures.pop(key, None)
            self._eligible_at.pop(key, None)
