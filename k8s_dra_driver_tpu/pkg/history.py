"""Flight recorder: multi-resolution telemetry history + decision provenance.

Every observability layer before this one is *instantaneous*: the gauges
and window-stat rings forget the past beyond one window, and the acting
controllers (scheduler, WFQ admission, autoscaler, rebalancer,
preemption, elastic resize) leave only deduped Events behind. This
module is the queryable past both the MISO-style right-sizing
recommender and the predictive serving forecaster presuppose:

- :class:`HistoryStore` — a fixed-memory multi-resolution time-series
  store. Every pushed sample lands in a raw ring and is simultaneously
  downsampled into 1-minute and 10-minute bucket tiers with streaming
  min/max/mean/p95 per bucket (p95 over a bounded per-bucket reservoir).
  Series are LRU-bounded; nothing grows without bound.
- :class:`DecisionRecord` — structured provenance for every controller
  action: the triggering object+revision, the observed inputs the rule
  fired on (qps, rho, burn rates, blocking set, ...), the ``RULE_*`` id
  that fired, the outcome, and the active trace id. Stored as a bounded
  per-object history so ``tpu-kubectl explain`` can merge them with the
  object's Events into one causal timeline.
- WAL-style segment persistence under the existing persist_dir: appends
  go to jsonl segments, ``checkpoint()`` folds them into one atomic
  snapshot (StoreWAL's discipline: numeric segment order, torn-tail
  tolerance on replay), so a restarted sim keeps the fleet's past and
  ``fingerprint()`` proves the restore byte-faithful.
- ``query(series, window, resolution)`` — the read contract the
  forecaster/recommender (and ``explain`` / ``top --history``) consume.

Rule ids are the closed ``RULE_*`` vocabulary below; the tpulint
``decision-discipline`` checker pins call sites to the constants and the
catalog to ``docs/reference/history.md``, exactly like event reasons.

Clock discipline: callers stamp samples and decisions with THEIR clock
(the sim's virtual clocks — determinism contract); ``wall`` on a
DecisionRecord is the only wall-clock field and exists solely so explain
can merge decisions with (wall-stamped) Events on one axis.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import (
    Any,
    Callable,
    Deque,
    Dict,
    List,
    Optional,
    Sequence,
    Tuple,
    Union,
)

from k8s_dra_driver_tpu.tpulib.loadtrace import percentile

log = logging.getLogger(__name__)

# -- rule catalog (docs/reference/history.md is the operator-facing copy) ----
# DecisionRecord.rule takes ONLY these constants (tpulint:
# decision-discipline). Format: "<controller>/<rule-that-fired>".

# Scheduler admission (sim scheduler pass)
RULE_SCHED_BIND = "scheduler/bind"
RULE_SCHED_PARK = "scheduler/park-unschedulable"
# WFQ / tenant-quota admission (scheduling/manager.py)
RULE_WFQ_PARK_QUOTA = "wfq/park-quota-exceeded"
# Serving autoscaler (autoscaler/controller.py)
RULE_SCALE_UP = "autoscaler/scale-up"
RULE_SCALE_DOWN = "autoscaler/scale-down"
RULE_SCALE_DEFER = "autoscaler/scale-deferred"
RULE_SCALE_TIER_DOWN = "autoscaler/tier-down"
# Live-repack rebalancer (rebalancer/controller.py)
RULE_MIGRATE = "rebalancer/migrate"
RULE_MIGRATE_FAILED = "rebalancer/migrate-failed"
# Checkpoint-aware preemption (scheduling/preemption.py)
RULE_EVICT = "preemption/evict-lower-tier"
RULE_EVICT_FAILED = "preemption/evict-failed"
# Elastic ComputeDomains (controller/elastic.py resize epochs)
RULE_RESIZE_START = "elastic/resize-epoch-start"
RULE_RESIZE_PHASE = "elastic/resize-phase"
RULE_RESIZE_HEALED = "elastic/resize-healed"
RULE_RESIZE_ROLLBACK = "elastic/resize-rollback"
# Cross-cluster global scheduler (federation/scheduler.py)
RULE_FED_PLACE = "federation/place"
RULE_FED_SPILL = "federation/spill"
# Leader failover (federation/replication.py promote())
RULE_FED_FAILOVER = "federation/failover"
# Critical-path profiler (pkg/lifecycle.py): one record per claim whose
# consumer reached Running, inputs carrying the per-phase breakdown.
RULE_LIFECYCLE_PROFILE = "lifecycle/claim-profiled"

# -- bounds ------------------------------------------------------------------

# Raw tier: the last N pushed samples per series (at the sim's 1 s tick,
# four virtual minutes; a real node at 10 s intervals sees 40 minutes).
RAW_CAPACITY = 240
# Downsampled tiers: (name, bucket width seconds, buckets retained).
# 1m * 180 = 3 h; 10m * 288 = 48 h of retained history per series.
TIERS: Tuple[Tuple[str, float, int], ...] = (
    ("1m", 60.0, 180),
    ("10m", 600.0, 288),
)
RESOLUTIONS = ("raw",) + tuple(name for name, _, _ in TIERS)
# Bounded per-open-bucket reservoir for the exact p95; past it, new
# samples still stream min/max/mean but p95 covers the first N.
BUCKET_RESERVOIR = 128
# Per-store LRU bound on distinct series (same cap discipline as the
# telemetry aggregator and event correlator).
MAX_SERIES = 4096
# Decision history: bounded per involved object, LRU-bounded objects.
MAX_DECISIONS_PER_OBJECT = 256
MAX_DECISION_OBJECTS = 4096
# Segment rotation: past this many appended records a fresh segment
# starts; past MAX_SEGMENTS the store checkpoints (snapshot + truncate).
SEGMENT_MAX_RECORDS = 65536
MAX_SEGMENTS = 4

_SNAPSHOT_NAME = "snapshot.json"
_SEGMENT_RE = re.compile(r"^seg\.(\d+)\.jsonl$")

_ObjKey = Tuple[str, str, str]


def series_name(*parts: str) -> str:
    """Canonical series id: slash-joined path, e.g.
    ``claim-duty/default/my-claim`` — what query()/explain address."""
    return "/".join(p for p in parts if p != "")


# -- decision records ---------------------------------------------------------


@dataclass
class DecisionRecord:
    """One controller decision: what acted, on which object revision,
    from which observed inputs, under which rule, with what outcome."""

    time: float                    # caller's (virtual) clock
    controller: str                # scheduler | autoscaler | preemption | ...
    rule: str                      # a RULE_* constant
    outcome: str                   # bound | parked | evicted | scaled | ...
    kind: str = ""
    namespace: str = ""
    name: str = ""
    revision: int = 0              # object resourceVersion when acted on
    message: str = ""
    inputs: Dict[str, Any] = field(default_factory=dict)
    trace_id: str = ""
    wall: float = 0.0              # wall clock, ONLY for merging with Events

    def to_doc(self) -> Dict[str, Any]:
        return {
            "time": self.time, "controller": self.controller,
            "rule": self.rule, "outcome": self.outcome, "kind": self.kind,
            "namespace": self.namespace, "name": self.name,
            "revision": self.revision, "message": self.message,
            "inputs": self.inputs, "trace_id": self.trace_id,
            "wall": self.wall,
        }

    @staticmethod
    def from_doc(doc: Dict[str, Any]) -> "DecisionRecord":
        return DecisionRecord(
            time=float(doc.get("time", 0.0)),
            controller=str(doc.get("controller", "")),
            rule=str(doc.get("rule", "")),
            outcome=str(doc.get("outcome", "")),
            kind=str(doc.get("kind", "")),
            namespace=str(doc.get("namespace", "")),
            name=str(doc.get("name", "")),
            revision=int(doc.get("revision", 0)),
            message=str(doc.get("message", "")),
            inputs=dict(doc.get("inputs", {})),
            trace_id=str(doc.get("trace_id", "")),
            wall=float(doc.get("wall", 0.0)),
        )


# -- buckets ------------------------------------------------------------------


class _Bucket:
    """One open downsample bucket: streaming min/max/mean plus a bounded
    reservoir for the p95. Sealed into a plain stats dict when the clock
    crosses its right edge."""

    __slots__ = ("start", "count", "vmin", "vmax", "total", "reservoir")

    def __init__(self, start: float):
        self.start = start
        self.count = 0
        self.vmin = float("inf")
        self.vmax = float("-inf")
        self.total = 0.0
        self.reservoir: List[float] = []

    def add(self, v: float) -> None:
        self.count += 1
        self.vmin = min(self.vmin, v)
        self.vmax = max(self.vmax, v)
        self.total += v
        if len(self.reservoir) < BUCKET_RESERVOIR:
            self.reservoir.append(v)

    def stats(self) -> Dict[str, float]:
        return {
            "t": self.start,
            "count": self.count,
            "min": self.vmin,
            "max": self.vmax,
            "mean": self.total / max(1, self.count),
            "p95": percentile(self.reservoir, 0.95) if self.reservoir else 0.0,
        }

    def to_doc(self) -> Dict[str, Any]:
        return {"start": self.start, "count": self.count, "min": self.vmin,
                "max": self.vmax, "total": self.total,
                "reservoir": list(self.reservoir)}

    @staticmethod
    def from_doc(doc: Dict[str, Any]) -> "_Bucket":
        b = _Bucket(float(doc["start"]))
        b.count = int(doc["count"])
        b.vmin = float(doc["min"])
        b.vmax = float(doc["max"])
        b.total = float(doc["total"])
        b.reservoir = [float(v) for v in doc.get("reservoir", [])]
        return b


class _Tier:
    __slots__ = ("width", "cap", "open", "sealed")

    def __init__(self, width: float, cap: int):
        self.width = width
        self.cap = cap
        self.open: Optional[_Bucket] = None
        self.sealed: Deque[Dict[str, float]] = deque(maxlen=cap)

    def add(self, t: float, v: float) -> None:
        start = (t // self.width) * self.width
        if self.open is None:
            self.open = _Bucket(start)
        elif start > self.open.start:
            self.sealed.append(self.open.stats())
            self.open = _Bucket(start)
        # Late samples (start < open.start) fold into the open bucket:
        # pushes ride monotonic virtual clocks, so this only absorbs
        # clock-domain skew instead of re-opening sealed history.
        self.open.add(v)

    def points(self) -> List[Dict[str, float]]:
        out = list(self.sealed)
        if self.open is not None and self.open.count:
            out.append(self.open.stats())
        return out


class _Series:
    __slots__ = ("raw", "tiers")

    def __init__(self, raw_capacity: int):
        self.raw: Deque[Tuple[float, float]] = deque(maxlen=raw_capacity)
        self.tiers: Dict[str, _Tier] = {
            name: _Tier(width, cap) for name, width, cap in TIERS
        }

    def push(self, t: float, v: float) -> None:
        self.raw.append((t, v))
        for tier in self.tiers.values():
            tier.add(t, v)


# -- the store ----------------------------------------------------------------


class HistoryStore:
    """Fixed-memory flight recorder with optional segment persistence.

    ``dirpath=None`` keeps everything in memory (tests, short-lived
    tools). With a directory, appends land in jsonl segments and
    ``checkpoint()``/``close()`` fold them into one atomic snapshot the
    next open restores — ``fingerprint()`` before close equals
    ``fingerprint()`` after reopen (the bench_history gate).

    Thread-safe: one mutex over the series and decision maps; queries
    snapshot under the lock so a concurrent writer can never hand a
    reader a torn bucket (the ``history-rollover-vs-explain`` tpusan
    scenario drives exactly that interleaving)."""

    def __init__(self, dirpath: Optional[str] = None, *,
                 metrics_registry=None,
                 raw_capacity: int = RAW_CAPACITY,
                 max_series: int = MAX_SERIES,
                 max_decisions_per_object: int = MAX_DECISIONS_PER_OBJECT,
                 max_decision_objects: int = MAX_DECISION_OBJECTS,
                 segment_max_records: int = SEGMENT_MAX_RECORDS,
                 max_segments: int = MAX_SEGMENTS,
                 clock: Callable[[], float] = lambda: 0.0):
        self.dirpath = dirpath
        self.raw_capacity = raw_capacity
        self.max_series = max_series
        self.max_decisions_per_object = max_decisions_per_object
        self.max_decision_objects = max_decision_objects
        self.segment_max_records = segment_max_records
        self.max_segments = max_segments
        self.clock = clock
        self._mu = threading.Lock()
        self._series: Dict[str, _Series] = {}  # tpulint: guarded-by=_mu
        self._decisions: Dict[_ObjKey, Deque[DecisionRecord]] = {}  # tpulint: guarded-by=_mu
        self._seg_file = None  # tpulint: guarded-by=_mu
        self._seg_epoch = 0  # tpulint: guarded-by=_mu
        self._seg_records = 0  # tpulint: guarded-by=_mu
        self.restored_samples = 0
        self.restored_decisions = 0
        self._samples_total = self._decisions_total = None
        self._series_gauge = None
        if metrics_registry is not None:
            from k8s_dra_driver_tpu.pkg.metrics import Counter, Gauge

            self._samples_total = metrics_registry.register(Counter(
                "tpu_dra_history_samples_total",
                "Telemetry samples recorded into the history store."))
            self._decisions_total = metrics_registry.register(Counter(
                "tpu_dra_history_decisions_total",
                "Controller DecisionRecords recorded, by controller.",
                ("controller",)))
            self._series_gauge = metrics_registry.register(Gauge(
                "tpu_dra_history_series",
                "Distinct time series currently retained by the history "
                "store (LRU-bounded)."))
        if dirpath is not None:
            os.makedirs(dirpath, exist_ok=True)
            with self._mu:
                self._restore_locked()
                self._open_segment_locked()

    # -- ingest --------------------------------------------------------------

    def push(self, series: str, t: float, v: float) -> None:
        """Record one sample. O(1): raw ring append + one open-bucket
        update per tier, plus a buffered segment line when persisting."""
        v = float(v)
        with self._mu:
            s = self._series.get(series)
            created = s is None
            if created:
                s = self._series[series] = _Series(self.raw_capacity)
                self._trim_series_locked()
            else:
                # LRU touch.
                self._series[series] = self._series.pop(series)
            s.push(t, v)
            self._append_locked({"k": "s", "s": series, "t": t, "v": v})
            nseries = len(self._series)
        if self._samples_total is not None:
            self._samples_total.inc()
            if created:
                # Set only on membership change — a per-push gauge write
                # doubles the recorder's metrics cost for a static value.
                self._series_gauge.set(value=float(nseries))

    def record(self, rec: DecisionRecord) -> DecisionRecord:
        """Store one DecisionRecord under its involved object (bounded
        per object, object set LRU-bounded)."""
        key: _ObjKey = (rec.kind, rec.namespace, rec.name)
        with self._mu:
            dq = self._decisions.get(key)
            if dq is None:
                dq = self._decisions[key] = deque(
                    maxlen=self.max_decisions_per_object)
                self._trim_decisions_locked()
            else:
                self._decisions[key] = self._decisions.pop(key)
            dq.append(rec)
            self._append_locked({"k": "d", **rec.to_doc()})
        if self._decisions_total is not None:
            self._decisions_total.inc(rec.controller)
        return rec

    def decide(self, *, controller: str, rule: str, outcome: str,
               obj=None, kind: str = "", namespace: str = "", name: str = "",
               revision: int = 0, message: str = "",
               inputs: Optional[Dict[str, Any]] = None,
               now: Optional[float] = None) -> Optional[DecisionRecord]:
        """Convenience wrapper every controller calls: resolves the
        involved object's identity+revision from ``obj`` (a K8sObject or
        anything with .meta), the active trace id from the ambient span,
        and never raises — provenance must not break control flow."""
        try:
            from k8s_dra_driver_tpu.pkg import tracing

            if obj is not None:
                meta = getattr(obj, "meta", None)
                kind = kind or getattr(obj, "kind", "") or type(obj).__name__
                if meta is not None:
                    namespace = namespace or getattr(meta, "namespace", "")
                    name = name or getattr(meta, "name", "")
                    revision = revision or getattr(meta, "resource_version", 0)
                else:
                    namespace = namespace or getattr(obj, "namespace", "")
                    name = name or getattr(obj, "name", "")
            ctx = tracing.current()
            rec = DecisionRecord(
                time=self.clock() if now is None else now,
                controller=controller, rule=rule, outcome=outcome,
                kind=kind, namespace=namespace, name=name,
                revision=int(revision), message=message,
                inputs=dict(inputs or {}),
                trace_id=ctx.trace_id if ctx else "",
                wall=time.time(),
            )
            return self.record(rec)
        except Exception:  # noqa: BLE001 — provenance is fire-and-forget, like the event recorder
            log.exception("decision record (%s/%s) dropped", controller, rule)
            return None

    # -- queries -------------------------------------------------------------

    def series_names(self) -> List[str]:
        with self._mu:
            return sorted(self._series)

    def query(self, series: str,
              window: Optional[Union[float, Tuple[float, float]]] = None,
              resolution: str = "raw") -> List[Dict[str, float]]:
        """Points for one series. ``resolution`` is ``raw`` (points
        ``{"t", "value"}``) or a tier name (buckets ``{"t", "count",
        "min", "max", "mean", "p95"}``). ``window`` is either ``(lo,
        hi)`` absolute bounds or a float W meaning the last W seconds
        relative to the newest retained point; None returns everything
        retained at that resolution. The forecaster/recommender
        contract — and what explain/top render from."""
        if resolution not in RESOLUTIONS:
            raise ValueError(
                f"unknown resolution {resolution!r}; want one of {RESOLUTIONS}")
        with self._mu:
            s = self._series.get(series)
            if s is None:
                return []
            if resolution == "raw":
                pts = [{"t": t, "value": v} for t, v in s.raw]
            else:
                pts = s.tiers[resolution].points()
        if window is None or not pts:
            return pts
        if isinstance(window, (int, float)):
            hi = pts[-1]["t"]
            lo = hi - float(window)
        else:
            lo, hi = float(window[0]), float(window[1])
        return [p for p in pts if lo <= p["t"] <= hi]

    def decisions_for(self, kind: str, namespace: str, name: str,
                      window: Optional[Tuple[float, float]] = None,
                      limit: int = 0) -> List[DecisionRecord]:
        """The bounded decision history of one object, oldest first."""
        with self._mu:
            dq = self._decisions.get((kind, namespace, name))
            out = list(dq) if dq else []
        if window is not None:
            lo, hi = window
            out = [r for r in out if lo <= r.time <= hi]
        if limit > 0:
            out = out[-limit:]
        return out

    def decisions_by_trace(self, trace_ids,
                           limit: int = 0) -> List[DecisionRecord]:
        """Every retained decision stamped with one of ``trace_ids``,
        wall-ordered oldest first — the cross-cluster trace-stitching
        read: ``explain --all-clusters`` collects an object's own trace
        ids, then pulls in the fleet-level records (spill, placement,
        failover) that share them but were recorded against OTHER
        objects (Cluster/..., the consumer Pod), so the causal chain
        survives the object-keyed index."""
        want = {t for t in trace_ids if t}
        if not want:
            return []
        with self._mu:
            out = [r for dq in self._decisions.values() for r in dq
                   if r.trace_id in want]
        out.sort(key=lambda r: (r.wall, r.time))
        if limit > 0:
            out = out[-limit:]
        return out

    def decision_count(self) -> int:
        with self._mu:
            return sum(len(dq) for dq in self._decisions.values())

    # -- bounds --------------------------------------------------------------

    def _trim_series_locked(self) -> None:
        # tpulint: holds=_mu (LRU evict; callers hold the lock)
        while len(self._series) > self.max_series:
            self._series.pop(next(iter(self._series)))

    def _trim_decisions_locked(self) -> None:
        # tpulint: holds=_mu
        while len(self._decisions) > self.max_decision_objects:
            self._decisions.pop(next(iter(self._decisions)))

    # -- persistence ---------------------------------------------------------

    def _append_locked(self, doc: Dict[str, Any]) -> None:
        # tpulint: holds=_mu
        if self._seg_file is None:
            return
        self._seg_file.write(json.dumps(doc, separators=(",", ":")) + "\n")
        self._seg_records += 1
        if self._seg_records >= self.segment_max_records:
            self._rotate_locked()

    def _segments_locked(self) -> List[Tuple[int, str]]:
        # tpulint: holds=_mu
        out = []
        try:
            names = os.listdir(self.dirpath)
        except OSError:
            return []
        for n in names:
            m = _SEGMENT_RE.match(n)
            if m:
                out.append((int(m.group(1)), os.path.join(self.dirpath, n)))
        # Numeric epoch order, never lexicographic (seg.10 after seg.9).
        return sorted(out)

    def _open_segment_locked(self) -> None:
        # tpulint: holds=_mu
        segs = self._segments_locked()
        self._seg_epoch = (segs[-1][0] + 1) if segs else 1
        path = os.path.join(self.dirpath, f"seg.{self._seg_epoch}.jsonl")
        self._seg_file = open(path, "a", encoding="utf-8")  # tpulint: disable=sleep-under-lock -- cold path: one open per 65536-record rotation
        self._seg_records = 0

    def _rotate_locked(self) -> None:
        # tpulint: holds=_mu
        self._seg_file.close()
        self._seg_file = None
        if len(self._segments_locked()) >= self.max_segments:
            # Fold everything into one snapshot so replay stays short
            # and old segments never pile up.
            self._checkpoint_locked()
        self._open_segment_locked()

    def _restore_locked(self) -> None:
        # tpulint: holds=_mu
        snap = os.path.join(self.dirpath, _SNAPSHOT_NAME)
        if os.path.exists(snap):
            try:
                with open(snap, "r", encoding="utf-8") as f:  # tpulint: disable=sleep-under-lock -- construction-time restore, no contenders yet
                    self._load_state_locked(json.load(f))
            except (OSError, ValueError, KeyError):
                log.exception("history snapshot unreadable; replaying "
                              "segments only")
        for _, path in self._segments_locked():
            try:
                with open(path, "r", encoding="utf-8") as f:  # tpulint: disable=sleep-under-lock -- construction-time replay, no contenders yet
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            doc = json.loads(line)
                        except ValueError:
                            break  # torn tail: everything before it counts
                        self._replay_locked(doc)
            except OSError:
                continue

    def _replay_locked(self, doc: Dict[str, Any]) -> None:
        # tpulint: holds=_mu
        if doc.get("k") == "s":
            name = doc["s"]
            s = self._series.get(name)
            if s is None:
                s = self._series[name] = _Series(self.raw_capacity)
                self._trim_series_locked()
            else:
                self._series[name] = self._series.pop(name)
            s.push(float(doc["t"]), float(doc["v"]))
            self.restored_samples += 1
        elif doc.get("k") == "d":
            rec = DecisionRecord.from_doc(doc)
            key: _ObjKey = (rec.kind, rec.namespace, rec.name)
            dq = self._decisions.get(key)
            if dq is None:
                dq = self._decisions[key] = deque(
                    maxlen=self.max_decisions_per_object)
                self._trim_decisions_locked()
            else:
                self._decisions[key] = self._decisions.pop(key)
            dq.append(rec)
            self.restored_decisions += 1

    # -- snapshot / fingerprint ----------------------------------------------

    def _state_doc_locked(self) -> Dict[str, Any]:
        # tpulint: holds=_mu
        series_doc: Dict[str, Any] = {}
        for name, s in self._series.items():
            series_doc[name] = {
                "raw": [[t, v] for t, v in s.raw],
                "tiers": {
                    tname: {
                        "open": (tier.open.to_doc()
                                 if tier.open is not None else None),
                        "sealed": list(tier.sealed),
                    }
                    for tname, tier in s.tiers.items()
                },
            }
        return {
            "version": 1,
            "series": series_doc,
            "decisions": [
                [list(key), [r.to_doc() for r in dq]]
                for key, dq in self._decisions.items()
            ],
        }

    def _load_state_locked(self, doc: Dict[str, Any]) -> None:
        # tpulint: holds=_mu
        for name, sdoc in doc.get("series", {}).items():
            s = _Series(self.raw_capacity)
            for t, v in sdoc.get("raw", []):
                s.raw.append((float(t), float(v)))
            for tname, tdoc in sdoc.get("tiers", {}).items():
                tier = s.tiers.get(tname)
                if tier is None:
                    continue  # tier layout changed across versions
                if tdoc.get("open") is not None:
                    tier.open = _Bucket.from_doc(tdoc["open"])
                for b in tdoc.get("sealed", []):
                    tier.sealed.append(b)
            self._series[name] = s
            self._trim_series_locked()
        for key, docs in doc.get("decisions", []):
            dq = deque((DecisionRecord.from_doc(d) for d in docs),
                       maxlen=self.max_decisions_per_object)
            self._decisions[tuple(key)] = dq
            self._trim_decisions_locked()

    def fingerprint(self) -> str:
        """Stable digest of the full retained state (series rings, tier
        buckets, decisions). Equal before close and after reopen — the
        bench_history restore gate pins it."""
        with self._mu:
            doc = self._state_doc_locked()
        payload = json.dumps(doc, sort_keys=True, separators=(",", ":"))
        return hashlib.sha1(payload.encode(),
                            usedforsecurity=False).hexdigest()

    def checkpoint(self) -> None:
        """Fold segments into one atomic snapshot (write-temp + rename,
        the StoreWAL compaction discipline) and start a fresh segment."""
        if self.dirpath is None:
            return
        with self._mu:
            if self._seg_file is not None:
                self._seg_file.close()
                self._seg_file = None
            self._checkpoint_locked()
            self._open_segment_locked()

    def _checkpoint_locked(self) -> None:
        # tpulint: holds=_mu
        doc = self._state_doc_locked()
        tmp = os.path.join(self.dirpath, _SNAPSHOT_NAME + ".tmp")
        with open(tmp, "w", encoding="utf-8") as f:  # tpulint: disable=sleep-under-lock -- explicit checkpoint/rotation fold: durability IS the point; callers are shutdown/rare-rotate
            json.dump(doc, f, separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())  # tpulint: disable=sleep-under-lock -- snapshot must be durable before segment unlink
        os.replace(tmp, os.path.join(self.dirpath, _SNAPSHOT_NAME))
        for _, path in self._segments_locked():
            try:
                os.unlink(path)
            except OSError:
                pass

    def sync(self) -> None:
        """Flush buffered segment appends to the OS (no fsync — the
        snapshot is the durable artifact; segments are best-effort tail)."""
        with self._mu:
            if self._seg_file is not None:
                self._seg_file.flush()

    def close(self) -> None:
        if self.dirpath is None:
            return
        with self._mu:
            if self._seg_file is not None:
                self._seg_file.close()
                self._seg_file = None
            self._checkpoint_locked()


# -- rendering helpers (explain / top --history) ------------------------------

SPARK_CHARS = "▁▂▃▄▅▆▇█"


def sparkline(values: Sequence[float], width: int = 48) -> str:
    """Unicode sparkline over ``values`` downsampled to ``width`` slots
    (mean per slot), normalized min..max — the telemetry strip under an
    explain timeline."""
    vals = [float(v) for v in values]
    if not vals:
        return ""
    if len(vals) > width:
        # Mean-pool into exactly `width` slots.
        pooled = []
        n = len(vals)
        for i in range(width):
            lo = i * n // width
            hi = max(lo + 1, (i + 1) * n // width)
            chunk = vals[lo:hi]
            pooled.append(sum(chunk) / len(chunk))
        vals = pooled
    vmin, vmax = min(vals), max(vals)
    span = vmax - vmin
    if span <= 0:
        return SPARK_CHARS[0] * len(vals)
    return "".join(
        SPARK_CHARS[min(len(SPARK_CHARS) - 1,
                        int((v - vmin) / span * len(SPARK_CHARS)))]
        for v in vals)
