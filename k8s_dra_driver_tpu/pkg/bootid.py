"""Node boot-ID reader — checkpoint invalidation across reboots.

A checkpoint written before a node reboot describes device state that no
longer exists; comparing the recorded boot ID against the live one lets the
plugin discard it (reference: /root/reference/pkg/bootid/bootid.go:10-22 and
cmd/gpu-kubelet-plugin/device_state.go:246-284).
"""

from __future__ import annotations

import os

BOOT_ID_PATH = "/proc/sys/kernel/random/boot_id"
# Test/mock seam, same pattern as the reference's ALT_PROC_DEVICES_PATH
# (internal/common/nvcaps.go:33-56): redirect the boot-id source file.
ALT_BOOT_ID_PATH_ENV = "ALT_TPU_BOOT_ID_PATH"


def read_boot_id() -> str:
    path = os.environ.get(ALT_BOOT_ID_PATH_ENV, BOOT_ID_PATH)
    with open(path, "r", encoding="utf-8") as f:
        return f.read().strip()
