"""Slice-agent deployment-mode configuration — the pkg/imex analog.

Reference (/root/reference/pkg/imex/imex.go:25-99): deployment ``Mode``
(driverManaged vs hostManaged) and ``Isolation`` (domain vs channel),
with validation gated on the host-managed feature gate. TPU mapping: the
slice agent is either run by this driver's per-CD DaemonSet or assumed to
be part of the node image (GKE tpu-vm style); isolation decides whether
workloads are isolated per-domain or per-channel within a domain.
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum

from k8s_dra_driver_tpu.pkg import featuregates as fg


class Mode(str, Enum):
    DRIVER_MANAGED = "driverManaged"
    HOST_MANAGED = "hostManaged"


class Isolation(str, Enum):
    DOMAIN = "domain"
    CHANNEL = "channel"


class SliceConfigError(ValueError):
    pass


@dataclass(frozen=True)
class SliceAgentConfig:
    mode: Mode = Mode.DRIVER_MANAGED
    isolation: Isolation = Isolation.DOMAIN

    @classmethod
    def parse(cls, mode: str = "", isolation: str = "") -> "SliceAgentConfig":
        try:
            m = Mode(mode) if mode else Mode.DRIVER_MANAGED
        except ValueError:
            raise SliceConfigError(
                f"unknown mode {mode!r}; want one of {[x.value for x in Mode]}"
            ) from None
        try:
            i = Isolation(isolation) if isolation else Isolation.DOMAIN
        except ValueError:
            raise SliceConfigError(
                f"unknown isolation {isolation!r}; want one of {[x.value for x in Isolation]}"
            ) from None
        return cls(mode=m, isolation=i)

    @property
    def host_managed(self) -> bool:
        """The one mode test consumers branch on. Ungated by design:
        validate(gates) at startup is the single place the gate is checked
        (reference EffectiveHostManaged folds these together; splitting
        construction-time validation from runtime branching avoids passing
        gates through every consumer)."""
        return self.mode == Mode.HOST_MANAGED

    def validate(self, gates: fg.FeatureGates) -> None:
        if self.mode == Mode.HOST_MANAGED and not gates.enabled("HostManagedSliceAgent"):
            raise SliceConfigError(
                "mode hostManaged requires the HostManagedSliceAgent feature gate"
            )
        if self.isolation == Isolation.CHANNEL and self.mode == Mode.HOST_MANAGED:
            raise SliceConfigError(
                "channel isolation is not supported with host-managed agents"
            )
