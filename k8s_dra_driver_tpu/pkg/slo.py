"""SLO recording rules with multi-window burn-rate alerting.

The telemetry plane's top layer: declared objectives are evaluated
against observation streams (claim p95 duty cycle, domain ICI
utilization, scheduler time-to-running — anything that produces
``(time, value)`` pairs), and error-budget *burn rates* are alerted the
SRE-workbook way: an alert fires only when BOTH windows of a
(long, short) pair burn above threshold — the long window proves the
problem is sustained, the short window proves it is still happening, so
a recovered incident stops alerting immediately and a brief blip never
alerts at all.

Definitions: a sample is **bad** when its value crosses the objective's
``bound`` in direction ``op``; the **burn rate** over a window is
``bad_fraction / (1 - target)`` — burn 1.0 consumes exactly the error
budget the objective allows, burn N consumes it N times too fast.

Surfaced three ways, one per consumer:

- ``tpu_dra_slo_burn_rate{slo,window}`` — the max effective burn
  (min of the pair, worst subject) per window pair, for dashboards;
- ``tpu_dra_slo_violation_minutes_total{slo}`` — budget-burning wall
  minutes, for reports;
- ``SLOBurnRate`` warning Events on the violating subject (claim,
  domain, node), deduplicated by the recorder correlator like every
  other event in the driver.

Per-subject observation history is deque-bounded by the longest window
and subject state is LRU-bounded — no unbounded growth, mirroring the
event correlator's discipline.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

from k8s_dra_driver_tpu.pkg.events import REASON_SLO_BURN_RATE

# Classic SRE pairs (fast burn: 1h/5m, slow burn: 6h/30m). Sim-scale
# deployments pass their own pairs sized to the virtual clock.
DEFAULT_WINDOWS: Tuple[Tuple[float, float], ...] = (
    (3600.0, 300.0),
    (21600.0, 1800.0),
)
DEFAULT_BURN_THRESHOLD = 2.0
# Hard cap on samples per (slo, subject) — backstop for pathological
# observe rates; the time-based pruning is the real bound.
MAX_SAMPLES_PER_SUBJECT = 8192
MAX_SUBJECTS = 1024

# Elastic-domain heal latency as a burn-rate objective: fed from the
# same completed-epoch observations behind the
# ``tpu_dra_resize_time_to_healed_seconds`` histogram (the elastic
# controller's heal_observer hook). A fleet whose domains heal slower
# than the bound burns error budget and pages like any other SLO.
TIME_TO_HEALED_SLO = "domain-time-to-healed"


# Cross-cluster replication lag as a burn-rate objective: fed from the
# follower's head-minus-applied record lag (``ReplicaStore.
# lag_records()``), observed by the fleet harness each step. A partition
# drives lag above the bound, both windows burn, and the alert decays to
# zero after heal exactly like every other SLO here — no special-cased
# replication alarms.
REPLICATION_LAG_SLO = "replication-lag"


def replication_lag_objective(
    bound_records: float = 100.0,
    target: float = 0.95,
    windows: Tuple[Tuple[float, float], ...] = ((120.0, 30.0),),
    burn_threshold: float = DEFAULT_BURN_THRESHOLD,
) -> SLObjective:
    """The declared replication-lag objective: ``target`` of lag samples
    must stay at or under ``bound_records`` WAL records behind the
    leader head. Window pair sized like the heal-time rule (sim-scale
    virtual seconds); production fleets re-declare with wall-clock
    pairs."""
    return SLObjective(
        name=REPLICATION_LAG_SLO,
        description="follower replication lag stays under the record "
                    "bound (leader head minus applied watermark)",
        target=target, bound=bound_records, op="gt",
        windows=windows, burn_threshold=burn_threshold)


def heal_time_objective(
    bound_s: float = 30.0,
    target: float = 0.95,
    windows: Tuple[Tuple[float, float], ...] = ((120.0, 30.0),),
    burn_threshold: float = DEFAULT_BURN_THRESHOLD,
) -> SLObjective:
    """The declared time-to-healed objective (virtual seconds in the
    sim). Heals are rare events, so the default window pair is sized
    like the scheduler-time-to-running rule rather than the dense
    telemetry ones; operators/tests re-declare via :meth:`SLOEvaluator.
    add` with their own bound."""
    return SLObjective(
        name=TIME_TO_HEALED_SLO,
        description="resize epochs (heal/grow/spec) complete under the "
                    "time-to-healed bound",
        target=target, bound=bound_s, op="gt",
        windows=windows, burn_threshold=burn_threshold)


@dataclass(frozen=True)
class SLObjective:
    """One declared objective: ``target`` fraction of samples must stay
    on the good side of ``bound``."""

    name: str
    description: str = ""
    target: float = 0.99
    bound: float = 0.0
    op: str = "gt"  # value is BAD when value > bound ("gt") / < bound ("lt")
    windows: Tuple[Tuple[float, float], ...] = DEFAULT_WINDOWS
    burn_threshold: float = DEFAULT_BURN_THRESHOLD

    def is_bad(self, value: float) -> bool:
        return value > self.bound if self.op == "gt" else value < self.bound

    def __post_init__(self):
        if self.op not in ("gt", "lt"):
            raise ValueError(f"SLO {self.name}: op must be gt|lt, not {self.op!r}")
        if not 0.0 < self.target < 1.0:
            raise ValueError(f"SLO {self.name}: target must be in (0, 1)")


@dataclass
class BurnAlert:
    slo: str
    subject: Tuple[str, str]        # (namespace, name) or ("", node)
    burn_rate: float                # effective (min of the firing pair)
    window: Tuple[float, float]     # the pair that fired


@dataclass(frozen=True)
class ActiveAlert:
    """One (slo, subject) incident currently firing, as the controller-
    facing ``active_alerts()`` snapshot reports it: worst burn across the
    firing window pairs, and when THIS incident started (``since``
    carries over between evaluations while the subject keeps firing, and
    resets once it recovers)."""

    slo: str
    subject: Tuple[str, str]
    burn_rate: float
    window: Tuple[float, float]
    since: float


@dataclass
class _SubjectState:
    samples: Deque[Tuple[float, bool]] = field(
        default_factory=lambda: deque(maxlen=MAX_SAMPLES_PER_SUBJECT))
    ref: object = None              # involved-object for the Event


class SLOEvaluator:
    """Evaluates declared objectives over observed sample streams."""

    def __init__(self, metrics_registry, recorder=None,
                 max_subjects: int = MAX_SUBJECTS):
        from k8s_dra_driver_tpu.pkg.metrics import Counter, Gauge

        self.recorder = recorder
        self.max_subjects = max_subjects
        # Optional flight-recorder sink (pkg/history.py HistoryStore):
        # when set, every evaluate() pass also pushes per-(slo, window)
        # burn-rate series so explain can show the burn leading up to a
        # controller decision. Set once at wiring, before first use.
        self.history = None
        self._mu = threading.Lock()
        self._objectives: Dict[str, SLObjective] = {}  # tpulint: guarded-by=_mu
        self._subjects: Dict[Tuple[str, Tuple[str, str]], _SubjectState] = {}  # tpulint: guarded-by=_mu
        self._last_eval_t: Optional[float] = None  # tpulint: guarded-by=_mu
        self._window_labels: Dict[Tuple[float, float], str] = {}  # tpulint: guarded-by=_mu
        # Incidents firing as of the last evaluate() — the structured
        # snapshot scaling controllers consume instead of re-scraping
        # gauges. Keyed so `since` survives across passes while the
        # subject keeps firing; recovered incidents drop immediately.
        self._active: Dict[Tuple[str, Tuple[str, str]], ActiveAlert] = {}  # tpulint: guarded-by=_mu
        r = metrics_registry
        self.burn_gauge = r.register(Gauge(
            "tpu_dra_slo_burn_rate",
            "Max effective error-budget burn rate per SLO and window pair "
            "(min of the long/short pair, worst subject).",
            ("slo", "window")))
        self.violation_minutes = r.register(Counter(
            "tpu_dra_slo_violation_minutes_total",
            "Wall minutes an SLO spent burning above its alert threshold.",
            ("slo",)))

    def add(self, objective: SLObjective) -> SLObjective:
        with self._mu:
            self._objectives[objective.name] = objective
            # Window labels rendered ONCE here: the gauge's label
            # vocabulary is exactly the declared window pairs (bounded),
            # never a per-sample formatted value.
            for pair in objective.windows:
                self._window_labels.setdefault(
                    pair, f"{pair[0]:g}/{pair[1]:g}")
        return objective

    def objectives(self) -> List[SLObjective]:
        with self._mu:
            return list(self._objectives.values())

    def has(self, name: str) -> bool:
        with self._mu:
            return name in self._objectives

    def active_alerts(self) -> List[ActiveAlert]:
        """Incidents firing as of the last :meth:`evaluate` pass — the
        consumer-facing snapshot (subject, worst effective burn, firing
        window pair, since-timestamp). Controllers (the serving
        autoscaler) act on this instead of re-scraping burn gauges;
        recovered incidents are gone from the very next snapshot."""
        with self._mu:
            return sorted(self._active.values(),
                          key=lambda a: (a.slo, a.subject))

    # -- ingestion -----------------------------------------------------------

    def observe(self, slo: str, now: float, value: float,
                subject: Tuple[str, str] = ("", ""), ref=None) -> None:
        """Record one sample for (slo, subject). ``ref`` is the k8s object
        (or ObjectReference) an alert Event should be filed against."""
        with self._mu:
            obj = self._objectives.get(slo)
            if obj is None:
                raise KeyError(f"unknown SLO {slo!r}")
            key = (slo, subject)
            state = self._subjects.get(key)
            if state is None:
                state = self._subjects[key] = _SubjectState()
                self._trim_subjects_locked()
            else:
                # LRU touch.
                self._subjects[key] = self._subjects.pop(key)
            if ref is not None:
                state.ref = ref
            state.samples.append((now, obj.is_bad(value)))
            self._prune_locked(obj, state, now)

    def _prune_locked(self, obj: SLObjective, state: _SubjectState,
                      now: float) -> None:
        horizon = now - max(w[0] for w in obj.windows)
        while state.samples and state.samples[0][0] < horizon:
            state.samples.popleft()

    def _trim_subjects_locked(self) -> None:
        # tpulint: holds=_mu (LRU evict; every caller holds the lock)
        while len(self._subjects) > self.max_subjects:
            self._subjects.pop(next(iter(self._subjects)))

    # -- evaluation ----------------------------------------------------------

    @staticmethod
    def _burn(samples: Deque[Tuple[float, bool]], now: float,
              window: float, target: float) -> float:
        total = bad = 0
        lo = now - window
        for t, is_bad in samples:
            if t >= lo:
                total += 1
                bad += is_bad
        if total == 0:
            return 0.0
        return (bad / total) / max(1e-9, 1.0 - target)

    def evaluate(self, now: float) -> List[BurnAlert]:
        """One evaluation pass: recompute burn rates for every subject,
        publish the gauges, accumulate violation minutes, and emit one
        (deduplicated) SLOBurnRate event per currently-alerting subject."""
        alerts: List[BurnAlert] = []
        with self._mu:
            dt_min = ((now - self._last_eval_t) / 60.0
                      if self._last_eval_t is not None else 0.0)
            self._last_eval_t = now
            # Every declared (slo, window) pair publishes EVERY pass,
            # 0.0 when nothing burns: a subject whose samples aged out
            # (claim unprepared, incident over) must not leave the last
            # alert-level burn stuck on /metrics forever.
            worst: Dict[Tuple[str, Tuple[float, float]], float] = {
                (name, pair): 0.0
                for name, obj in self._objectives.items()
                for pair in obj.windows
            }
            burning_slos: Dict[str, bool] = {o: False for o in self._objectives}
            for (slo, subject), state in self._subjects.items():
                obj = self._objectives.get(slo)
                if obj is None or not state.samples:
                    continue
                self._prune_locked(obj, state, now)
                for pair in obj.windows:
                    long_w, short_w = pair
                    effective = min(
                        self._burn(state.samples, now, long_w, obj.target),
                        self._burn(state.samples, now, short_w, obj.target))
                    key = (slo, pair)
                    worst[key] = max(worst.get(key, 0.0), effective)
                    if effective >= obj.burn_threshold:
                        burning_slos[slo] = True
                        alerts.append(BurnAlert(
                            slo=slo, subject=subject,
                            burn_rate=effective, window=pair))
            # Structured incident snapshot for controllers: one entry per
            # firing (slo, subject) with the worst effective burn and a
            # stable `since`; anything not firing THIS pass drops — a
            # recovered incident disappears immediately.
            fresh: Dict[Tuple[str, Tuple[str, str]], ActiveAlert] = {}
            for a in alerts:
                key = (a.slo, a.subject)
                prev = self._active.get(key)
                since = prev.since if prev is not None else now
                cur = fresh.get(key)
                if cur is None or a.burn_rate > cur.burn_rate:
                    fresh[key] = ActiveAlert(
                        slo=a.slo, subject=a.subject, burn_rate=a.burn_rate,
                        window=a.window, since=since)
            self._active = fresh
            for (slo, pair), burn in worst.items():
                self.burn_gauge.set(
                    slo, self._window_labels[pair], value=burn)
            # Series names resolved under the lock (window labels are
            # guarded state); pushes issued after release — the history
            # store does its own locking.
            history_pushes = ([
                (f"slo-burn/{slo}/{self._window_labels[pair]}", burn)
                for (slo, pair), burn in worst.items()
            ] if self.history is not None else [])
            for slo, burning in burning_slos.items():
                if burning and dt_min > 0:
                    self.violation_minutes.inc(slo, by=dt_min)
            # Event refs resolved under the lock, emission after release
            # (the recorder does its own locking + API writes).
            to_emit = []
            if self.recorder is not None:
                seen = set()
                for a in alerts:
                    if (a.slo, a.subject) in seen:
                        continue  # one event per subject even if both pairs fire
                    seen.add((a.slo, a.subject))
                    state = self._subjects.get((a.slo, a.subject))
                    if state is not None and state.ref is not None:
                        obj = self._objectives[a.slo]
                        to_emit.append((state.ref, a, obj))
        for series, burn in history_pushes:
            self.history.push(series, now, burn)
        for ref, a, obj in to_emit:
            # Message carries no live numbers: repeats of one sustained
            # violation must dedup into ONE Event with a rising count.
            self.recorder.warning(
                ref, REASON_SLO_BURN_RATE,
                f"SLO {a.slo}: error budget burning >= "
                f"{obj.burn_threshold:g}x over the {a.window[0]:g}s/"
                f"{a.window[1]:g}s windows (objective {obj.target:g}, "
                f"bound {obj.bound:g} {obj.op})")
        return alerts
