"""Multi-process cluster-initialization proof — the function-proof job.

The reference proves an assembled IMEX domain works by running a real
collective across the fabric (demo/specs/imex/nvbandwidth-test-job.yaml);
this is the same proof for a driver-assembled TPU slice: every worker
process configures itself EXCLUSIVELY from the environment the channel
device's CDI spec injected (plugins/computedomain/computedomain.py
bootstrap_env), calls ``jax.distributed.initialize``, and runs a psum
across all processes. If the env the driver hands out is wrong in any way
— bad coordinator, inconsistent worker ids, wrong peer count — the
cluster never initializes or the reduction disagrees.

Derivation (exactly what libtpu/JAX do on a real slice):
- process_id         <- TPU_WORKER_ID
- num_processes      <- len(TPU_WORKER_HOSTNAMES)
- coordinator        <- MEGASCALE_COORDINATOR_ADDRESS (host:port)

Each worker contributes (process_id + 1) per local device; the psum must
equal N(N+1)/2 * devices-per-process on every process. The proof
VERIFIES this in-process and exits nonzero on mismatch — a job that
computes a wrong reduction must fail, not print a wrong number with
exit 0 for the harness to misread as success. Prints one JSON line
with the result (including ``expected`` and ``ok``).

Usage (as the container command of an Indexed Job on a ComputeDomain, or
spawned locally by the e2e harness on the CPU backend):

    python -m k8s_dra_driver_tpu.ops.psum_proof
"""

from __future__ import annotations

import json
import os
import sys


def run_proof(timeout_s: float = 60.0) -> dict:
    hosts = [h for h in os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",") if h]
    if not hosts:
        raise SystemExit("TPU_WORKER_HOSTNAMES missing: not a driver-assembled slice")
    process_id = int(os.environ["TPU_WORKER_ID"])
    coordinator = os.environ["MEGASCALE_COORDINATOR_ADDRESS"]
    num_processes = len(hosts)

    import jax

    # CPU-backend harness runs announce a collectives implementation via
    # JAX_CPU_COLLECTIVES_IMPLEMENTATION; jax versions around 0.4.3x ship
    # the gloo backend but ignore the env var (the flag is config-only),
    # so apply it explicitly before the first backend use. Real TPU slices
    # never set the variable.
    impl = os.environ.get("JAX_CPU_COLLECTIVES_IMPLEMENTATION", "")
    if impl and impl != "none":
        try:
            jax.config.update("jax_cpu_collectives_implementation", impl)
        except (AttributeError, ValueError):
            pass  # older/newer jax: flag absent or env-var honored natively

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
        initialization_timeout=int(timeout_s),
    )

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from k8s_dra_driver_tpu.parallel.mesh import family_mesh, get_shard_map

    shard_map = get_shard_map()
    devices = jax.devices()  # global: every process's devices
    # Bundle-ordered when the CDI handler injected TPU_DRA_MESH_BUNDLE
    # (psum is value-order-independent, so the proof's sum is unchanged).
    mesh = family_mesh(devices, (len(devices),), ("d",))
    # Every local device contributes this process's (id + 1); the psum is
    # a REAL cross-process collective over the distributed runtime.
    local = jnp.full((jax.local_device_count(), 1),
                     float(process_id + 1), jnp.float32)
    garr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("d")), np.asarray(local), (len(devices), 1)
    )

    @jax.jit
    def reduce(x):
        return shard_map(
            lambda y: jax.lax.psum(y, "d"),
            mesh=mesh, in_specs=P("d"), out_specs=P(None),
        )(x)

    total = float(np.asarray(jax.device_get(reduce(garr)))[0])
    # The expected reduction, derived in-process: every process p
    # contributes (p+1) on each of its local devices, and jax requires
    # uniform per-process device counts, so
    #   expected = sum_{p=0}^{N-1} (p+1) * (global_devices / N)
    #            = N(N+1)/2 * devices-per-process.
    n = jax.process_count()
    expected = float(n * (n + 1) // 2 * (len(devices) // n))
    return {
        "process_id": process_id,
        "num_processes": n,
        "local_devices": jax.local_device_count(),
        "global_devices": len(devices),
        "psum": total,
        "expected": expected,
        "ok": total == expected,
        "platform": devices[0].platform,
    }


def main() -> int:
    result = run_proof()
    print(json.dumps(result))
    if not result["ok"]:
        print(
            f"psum proof FAILED: got {result['psum']}, "
            f"expected {result['expected']} "
            f"({result['num_processes']} processes x "
            f"{result['local_devices']} local devices)",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
