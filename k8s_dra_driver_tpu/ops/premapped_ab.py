"""A/B probe: does TPU_PREMAPPED_BUFFER_SIZE bind on this runtime?

The driver's premapped sharing budget is enforced at Prepare (capacity
sums, conflicts) and handed to the workload as the real libtpu knob
``TPU_PREMAPPED_BUFFER_SIZE`` (power-of-two, sized from the budget). The
reference can program its device directly (sharing.go:139-474 drives MPS
daemons); libtpu's equivalent control surface is this env var — but
whether the runtime a pod actually talks to honors it depends on the
deployment (a remote/tunneled PJRT backend never sees client env).

This probe answers the question empirically for the current chip: it
launches two child processes, one with the knob clamped small (8 MiB)
and one unconstrained, times a large host->device transfer in each, and
reports whether the constrained run is observably slower (the premapped
buffer is the DMA staging path for transfers).

    python -m k8s_dra_driver_tpu.ops.premapped_ab [--size-mib 256]

Prints one JSON line: {"binds": bool, "small_s": ..., "large_s": ...,
"ratio": ...}. docs/guides/sharing.md records the measured answer for
the bench environment.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

_CHILD = """
import json, time
import numpy as np
import jax
x = np.ones(({mib} * 1024 * 1024) // 4, np.float32)
# Warm the backend (first transfer pays connection setup).
jax.device_put(np.ones(1024, np.float32)).block_until_ready()
best = min(
    (lambda t0: (jax.device_put(x).block_until_ready(), time.perf_counter() - t0)[1])(
        time.perf_counter())
    for _ in range(3)
)
print(json.dumps({{"transfer_s": best,
                   "platform": jax.devices()[0].platform}}))
"""


class ChildFailed(RuntimeError):
    """A probe child died; carries the stderr tail for diagnosis."""

    def __init__(self, returncode: int, stderr_tail: str):
        super().__init__(
            f"probe child exited {returncode}; stderr tail:\n{stderr_tail}")
        self.returncode = returncode
        self.stderr_tail = stderr_tail


def _run_child(size_mib: int, premapped: int | None) -> dict:
    env = dict(os.environ)
    env.pop("TPU_PREMAPPED_BUFFER_SIZE", None)
    if premapped is not None:
        env["TPU_PREMAPPED_BUFFER_SIZE"] = str(premapped)
    # check=False + explicit stderr surfacing: a libtpu init failure in the
    # child must reach the operator as its actual error text, not die as a
    # bare CalledProcessError with the diagnostic swallowed in .stderr.
    out = subprocess.run(
        [sys.executable, "-c", _CHILD.format(mib=size_mib)],
        env=env, capture_output=True, text=True, timeout=300, check=False,
    )
    if out.returncode != 0:
        raise ChildFailed(out.returncode, out.stderr.strip()[-2000:])
    return json.loads(out.stdout.strip().splitlines()[-1])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--size-mib", type=int, default=256)
    ap.add_argument("--small-bytes", type=int, default=8 << 20)
    args = ap.parse_args(argv)
    try:
        a = _run_child(args.size_mib, args.small_bytes)
        b = _run_child(args.size_mib, None)
    except ChildFailed as e:
        # Surface the child's stderr tail in the JSON error field so a
        # libtpu init failure is diagnosable from the probe output alone.
        print(json.dumps({
            "binds": None,
            "error": f"probe child exited {e.returncode}",
            "child_stderr_tail": e.stderr_tail,
        }))
        return 2
    small, large = a["transfer_s"], b["transfer_s"]
    platform = a.get("platform", "?")
    ratio = small / large if large > 0 else float("inf")
    result = {
        # "Binds" = the constrained run is OBSERVABLY slower at this
        # transfer size; 1.5x separates real constraint from run-to-run
        # noise (best-of-3 each side). binds=false does NOT distinguish
        # "env ignored" from "honored but not the bottleneck here" — it
        # only establishes the clamp has no observable effect.
        "binds": ratio > 1.5,
        "platform": platform,
        "small_s": round(small, 4),
        "large_s": round(large, 4),
        "ratio": round(ratio, 3),
        "size_mib": args.size_mib,
        "small_bytes": args.small_bytes,
    }
    if platform != "tpu":
        # A CPU fallback exercises no TPU runtime at all: the answer is
        # meaningless, not "false". Refuse to let it masquerade.
        result["binds"] = None
        result["error"] = (f"children ran on platform {platform!r}, not tpu "
                           f"— probe is inconclusive")
    print(json.dumps(result))
    return 0 if result.get("error") is None else 2


if __name__ == "__main__":
    sys.exit(main())
