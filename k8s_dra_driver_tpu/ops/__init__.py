"""Workload-side ops: collective benchmarks and TPU kernels.

These run *inside* claimed containers — the proof-of-function jobs the
framework schedules onto prepared slices, playing the role of the
reference's nvbandwidth test jobs (demo/specs/imex/nvbandwidth-test-job.yaml).
"""

from k8s_dra_driver_tpu.ops.allreduce_bench import psum_bandwidth  # noqa: F401
