"""Single-chip MFU sweep for the flagship train step.

Drives the same measurement as bench.py's flagship leg over a grid of
shapes (d_model, d_ff, seq_len, batch, attention, remat) to find — or
bound — the best achievable MFU on the attached chip. Prints one JSON
line per config plus a final "best" line; docs/benchmarks.md records the
outcome (the roofline/sweep evidence the benchmark config cites).

Usage:
    python -m k8s_dra_driver_tpu.ops.mfu_sweep            # default grid
    python -m k8s_dra_driver_tpu.ops.mfu_sweep --iters 20
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import time


def measure_step(cfg, batch_per_replica: int, iters: int) -> dict:
    """Marginal step time + MFU for one config (same two-loop-size
    subtraction as bench.py so the tunnel round-trip cancels)."""
    import jax

    from k8s_dra_driver_tpu.models.flagship import (
        make_sharded_train_step,
        matmul_param_count,
    )

    devices = jax.devices()
    step, state, batch = make_sharded_train_step(
        cfg, devices, batch_per_replica=batch_per_replica
    )
    state, loss = step(state, batch)
    float(loss)  # compile + sync (block_until_ready lies over the tunnel)

    def run(n: int) -> float:
        nonlocal state
        t0 = time.perf_counter()
        for _ in range(n):
            state, loss = step(state, batch)
        float(loss)
        return time.perf_counter() - t0

    iters = max(iters, 4)  # the subtraction below needs iters > n1
    n1 = max(1, iters // 4)
    t1 = min(run(n1) for _ in range(2))
    t2 = min(run(iters) for _ in range(2))
    noise_limited = t2 <= t1
    dt = t2 / iters if noise_limited else (t2 - t1) / (iters - n1)
    tokens = batch["tokens"].size
    flops = 6 * matmul_param_count(cfg) * tokens
    from bench import PEAK_BF16_FLOPS  # single source for peak numbers

    peak = PEAK_BF16_FLOPS.get(getattr(devices[0], "device_kind", ""), 0)
    out = {
        "d_model": cfg.d_model, "d_ff": cfg.d_ff, "n_layers": cfg.n_layers,
        "seq_len": cfg.seq_len, "batch": tokens // cfg.seq_len,
        "attention": cfg.attention, "remat": cfg.remat,
        "step_ms": round(dt * 1e3, 2),
        "tokens_per_s": round(tokens / dt, 1),
        "noise_limited": noise_limited,
    }
    if peak:
        out["mfu_pct"] = round(100 * flops / dt / (peak * len(devices)), 1)
    return out


def default_grid(base) -> list:
    """(cfg, batch_per_replica) pairs exploring around the shipped bench
    shape (r5 winner: d2048 / ff16384 / 2×1024 heads — the
    batch/remat/seq/attention/head/width axes; head ladder kept so the
    conventional-head-dim comparison numbers in docs/benchmarks.md stay
    reproducible)."""
    r = dataclasses.replace
    return [
        (base, 4),                                        # bench.py today (2×1024)
        (base, 8),                                        # amortize weights
        (r(base, n_heads=1), 4),                          # head_dim 2048 (regresses)
        (r(base, n_heads=4), 4),                          # head_dim 512 (r5a shape)
        (r(base, n_heads=8), 4),                          # head_dim 256 (r4 shape)
        (r(base, n_heads=16), 4),                         # head_dim 128
        (r(base, remat=True), 8),                         # remat buys batch
        (r(base, seq_len=2048), 4),                       # longer sequence
        (r(base, seq_len=2048, attention="flash"), 4),    # flash at 2k
        (r(base, d_ff=8192), 4),                          # FFN ratio 4 (r4 -11 pts)
        (r(base, d_model=3072, d_ff=24576, n_heads=12), 4),   # wider, ratio 8
    ]


def main() -> None:
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))))
    parser = argparse.ArgumentParser()
    parser.add_argument("--iters", type=int, default=16)
    args = parser.parse_args()

    from k8s_dra_driver_tpu.models.flagship import SliceProofConfig

    results = []
    for cfg, bpr in default_grid(SliceProofConfig.bench()):
        try:
            r = measure_step(cfg, bpr, args.iters)
        except Exception as e:  # noqa: BLE001 — OOM/compile fail is data too
            r = {
                "d_model": cfg.d_model, "d_ff": cfg.d_ff,
                "seq_len": cfg.seq_len, "batch": "-",
                "attention": cfg.attention, "remat": cfg.remat,
                "error": str(e)[:160],
            }
        results.append(r)
        print(json.dumps(r), flush=True)
    scored = [r for r in results if "mfu_pct" in r]
    if scored:
        best = max(scored, key=lambda r: r["mfu_pct"])
        print(json.dumps({"best": best}), flush=True)


if __name__ == "__main__":
    main()
