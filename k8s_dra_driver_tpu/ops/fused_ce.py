"""Fused unembed + softmax cross-entropy — a load-bearing Pallas kernel.

The standard large-vocab loss computes ``logits = h @ W_unembed`` and then
``logsumexp``/gather over them — materializing a ``[tokens, vocab]`` f32
tensor in HBM (at 4096 tokens x 128k vocab that is 2 GiB written + read
multiple times). This kernel streams vocab tiles through VMEM with an
online logsumexp, so the logits NEVER touch HBM: the forward writes only
``lse`` and the picked label logit per token (two [T] vectors), and the
backward re-materializes each tile once to form ``dx`` and ``dW``.

Forward per (t, v) tile: one MXU matmul [bt, D] x [D, bv] plus the online
(m, l) update — the flash-attention accumulation pattern applied to the
loss. Backward recomputes the tile's softmax from the saved ``lse`` (one
extra matmul vs a materializing implementation — FLOPs traded for HBM,
the profitable direction on TPU where HBM bandwidth is the bottleneck).

Measured v5e numbers live in docs/benchmarks.md: forward-only (scoring)
wins 1.4-1.5x at vocab >= 32k and is the only path when the logits
exceed HBM; training's fwd+bwd stays on the XLA loss (measured faster at
fitting sizes). The flagship's ``evaluate_nll`` is the wired consumer.
Vocab sizes that don't divide the block are padded internally and the
pad columns masked out of the reduction; the token dimension must divide
``block_t`` (callers pad, as evaluate_nll does).

Kernels run in interpreter mode off-TPU so CPU CI tests the same code.
Reference counterpart: none (the reference's workload tier has no loss
kernels); the pattern follows the public fused-CE formulation (e.g.
Liger); implementation is original, written against the Pallas TPU guide.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # noqa: BLE001 — no backend at all
        return False


# -- forward ------------------------------------------------------------------

def _fwd_kernel(x_ref, w_ref, lab_ref, lse_ref, pick_ref, m_ref, l_ref,
                pk_ref, *, bv: int, nv: int, vocab: int):
    v = pl.program_id(1)

    @pl.when(v == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref[...], -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref[...])
        pk_ref[...] = jnp.zeros_like(pk_ref[...])

    logits = jnp.dot(x_ref[...], w_ref[...],
                     preferred_element_type=jnp.float32)  # [bt, bv]
    cols = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1) + v * bv
    # Internal vocab padding: pad columns are not classes — mask them out
    # of the logsumexp entirely.
    logits = jnp.where(cols < vocab, logits, -jnp.inf)
    m_prev = m_ref[...]                                   # [bt, 1]
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=1, keepdims=True))
    l_ref[...] = (l_ref[...] * jnp.exp(m_prev - m_new)
                  + jnp.sum(jnp.exp(logits - m_new), axis=1, keepdims=True))
    m_ref[...] = m_new
    match = cols == lab_ref[...]                          # [bt, bv]
    pk_ref[...] += jnp.sum(jnp.where(match, logits, 0.0), axis=1,
                           keepdims=True)

    @pl.when(v == nv - 1)
    def _finish():
        lse_ref[...] = m_ref[...] + jnp.log(l_ref[...])
        pick_ref[...] = pk_ref[...]


def _pad_vocab(w, bv):
    vocab = w.shape[1]
    vpad = (-vocab) % bv
    if vpad:
        w = jnp.pad(w, ((0, 0), (0, vpad)))
    return w, vocab


def _fwd(x, w, labels2d, bt, bv, interpret):
    from jax.experimental.pallas import tpu as pltpu

    t_dim, d = x.shape
    w, vocab = _pad_vocab(w, bv)
    nt, nv = t_dim // bt, w.shape[1] // bv
    lse, picked = pl.pallas_call(
        functools.partial(_fwd_kernel, bv=bv, nv=nv, vocab=vocab),
        grid=(nt, nv),
        in_specs=[
            pl.BlockSpec((bt, d), lambda t, v: (t, 0)),
            pl.BlockSpec((d, bv), lambda t, v: (0, v)),
            pl.BlockSpec((bt, 1), lambda t, v: (t, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bt, 1), lambda t, v: (t, 0)),
            pl.BlockSpec((bt, 1), lambda t, v: (t, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t_dim, 1), jnp.float32),
            jax.ShapeDtypeStruct((t_dim, 1), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((bt, 1), jnp.float32) for _ in range(3)],
        interpret=interpret,
    )(x, w, labels2d)
    return lse, picked


# -- backward -----------------------------------------------------------------

def _dx_kernel(x_ref, w_ref, lab_ref, lse_ref, g_ref, dx_ref, acc_ref,
               *, bv: int, nv: int, vocab: int):
    v = pl.program_id(1)

    @pl.when(v == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref[...])

    logits = jnp.dot(x_ref[...], w_ref[...],
                     preferred_element_type=jnp.float32)
    cols = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1) + v * bv
    p = jnp.where(cols < vocab, jnp.exp(logits - lse_ref[...]), 0.0)
    p = (p - (cols == lab_ref[...]).astype(jnp.float32)) * g_ref[...]
    # [bt, bv] x [D, bv]^T -> [bt, D], contracting the vocab tile.
    acc_ref[...] += jax.lax.dot_general(
        p, w_ref[...].astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(v == nv - 1)
    def _finish():
        dx_ref[...] = acc_ref[...].astype(dx_ref.dtype)


def _dw_kernel(x_ref, w_ref, lab_ref, lse_ref, g_ref, dw_ref, acc_ref,
               *, bv: int, nt: int, vocab: int):
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref[...])

    v = pl.program_id(0)
    logits = jnp.dot(x_ref[...], w_ref[...],
                     preferred_element_type=jnp.float32)
    cols = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1) + v * bv
    p = jnp.where(cols < vocab, jnp.exp(logits - lse_ref[...]), 0.0)
    p = (p - (cols == lab_ref[...]).astype(jnp.float32)) * g_ref[...]
    # [bt, D]^T x [bt, bv] -> [D, bv], contracting the token tile.
    acc_ref[...] += jax.lax.dot_general(
        x_ref[...].astype(jnp.float32), p,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(t == nt - 1)
    def _finish():
        dw_ref[...] = acc_ref[...].astype(dw_ref.dtype)


# -- custom-vjp wrapper -------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def fused_ce_losses(x: jax.Array, w: jax.Array, labels: jax.Array,
                    block_t: int = 256, block_v: int = 512,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Per-token softmax cross-entropy of ``x @ w`` against ``labels``
    WITHOUT materializing the [T, vocab] logits.

    x: [T, D] (bf16/f32), w: [D, vocab], labels: [T] int32.
    Returns [T] float32 losses (mean them for the scalar loss). T must
    divide by block_t and vocab by block_v.
    """
    lse, picked = _fwd_parts(x, w, labels, block_t, block_v, interpret)
    return lse - picked


def _check(x, w, labels, bt, bv):
    t_dim, d = x.shape
    if t_dim % bt:
        raise ValueError(
            f"fused_ce needs T ({t_dim}) % block_t ({bt}) == 0 "
            f"(vocab is padded internally)")
    if w.shape[0] != d or labels.shape != (t_dim,):
        raise ValueError(f"shape mismatch: x {x.shape}, w {w.shape}, "
                         f"labels {labels.shape}")


def _fwd_parts(x, w, labels, bt, bv, interpret):
    _check(x, w, labels, bt, bv)
    if interpret is None:
        interpret = not _on_tpu()
    labels2d = labels.reshape(-1, 1).astype(jnp.int32)
    lse, picked = _fwd(x, w, labels2d, bt, bv, interpret)
    return lse[:, 0], picked[:, 0]


def _fused_ce_fwd(x, w, labels, block_t, block_v, interpret):
    lse, picked = _fwd_parts(x, w, labels, block_t, block_v, interpret)
    return lse - picked, (x, w, labels, lse)


def _fused_ce_bwd(block_t, block_v, interpret, res, g):
    x, w, labels, lse = res
    if interpret is None:
        interpret = not _on_tpu()
    t_dim, d = x.shape
    w, vocab = _pad_vocab(w, block_v)
    vpad_total = w.shape[1]
    nt, nv = t_dim // block_t, vpad_total // block_v
    labels2d = labels.reshape(-1, 1).astype(jnp.int32)
    lse2d = lse.reshape(-1, 1)
    g2d = g.reshape(-1, 1).astype(jnp.float32)
    from jax.experimental.pallas import tpu as pltpu

    dx = pl.pallas_call(
        functools.partial(_dx_kernel, bv=block_v, nv=nv, vocab=vocab),
        grid=(nt, nv),
        in_specs=[
            pl.BlockSpec((block_t, d), lambda t, v: (t, 0)),
            pl.BlockSpec((d, block_v), lambda t, v: (0, v)),
            pl.BlockSpec((block_t, 1), lambda t, v: (t, 0)),
            pl.BlockSpec((block_t, 1), lambda t, v: (t, 0)),
            pl.BlockSpec((block_t, 1), lambda t, v: (t, 0)),
        ],
        out_specs=pl.BlockSpec((block_t, d), lambda t, v: (t, 0)),
        out_shape=jax.ShapeDtypeStruct((t_dim, d), x.dtype),
        scratch_shapes=[pltpu.VMEM((block_t, d), jnp.float32)],
        interpret=interpret,
    )(x, w, labels2d, lse2d, g2d)
    dw = pl.pallas_call(
        functools.partial(_dw_kernel, bv=block_v, nt=nt, vocab=vocab),
        grid=(nv, nt),
        in_specs=[
            pl.BlockSpec((block_t, d), lambda v, t: (t, 0)),
            pl.BlockSpec((d, block_v), lambda v, t: (0, v)),
            pl.BlockSpec((block_t, 1), lambda v, t: (t, 0)),
            pl.BlockSpec((block_t, 1), lambda v, t: (t, 0)),
            pl.BlockSpec((block_t, 1), lambda v, t: (t, 0)),
        ],
        out_specs=pl.BlockSpec((d, block_v), lambda v, t: (0, v)),
        out_shape=jax.ShapeDtypeStruct((d, vpad_total), w.dtype),
        scratch_shapes=[pltpu.VMEM((d, block_v), jnp.float32)],
        interpret=interpret,
    )(x, w, labels2d, lse2d, g2d)
    return dx, dw[:, :vocab], None


fused_ce_losses.defvjp(_fused_ce_fwd, _fused_ce_bwd)


def reference_ce_losses(x, w, labels) -> jax.Array:
    """Materializing reference: logits -> log_softmax -> gather."""
    logits = (x.astype(jnp.float32) @ w.astype(jnp.float32))
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
