"""jax.psum allreduce bandwidth benchmark — the nvbandwidth analog.

The job a user schedules onto a freshly assembled ComputeDomain to prove the
ICI fabric delivers (BASELINE.md: "jax.psum GB/s on the allocated slice").
Runs under ``shard_map`` over every available device; algorithmic bus
bandwidth uses the ring-allreduce factor 2(n-1)/n, the convention NCCL
benchmarks report, so numbers compare 1:1 with the reference ecosystem's
nvbandwidth/nccl-tests figures.

Usage (inside a claimed container, or anywhere JAX sees devices):
    python -m k8s_dra_driver_tpu.ops.allreduce_bench [--size-mib 256] [--iters 30]
"""

from __future__ import annotations

import argparse
import json
import time
from functools import partial
from typing import Optional, Sequence


def psum_bandwidth(
    size_mib: float = 64.0,
    iters: int = 20,
    devices: Optional[Sequence] = None,
    warmup: int = 3,
) -> dict:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from k8s_dra_driver_tpu.parallel.mesh import (
        family_mesh,
        get_shard_map,
        revary as _revary,
    )

    shard_map = get_shard_map()

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    # Bundle-ordered when TPU_DRA_MESH_BUNDLE is ambient: the ring this
    # bench times is exactly the chain the compiler makes ICI-adjacent.
    mesh = family_mesh(devices, (n,), ("d",))
    per_device_elems = int(size_mib * (1 << 20) // 4)
    # Zeros: psum(0) == 0, so chained iterations inside the loop neither
    # overflow nor need a normalization op that would pollute the timing
    # (the collective moves the same bytes regardless of values).
    x = jax.device_put(
        jnp.zeros((n, per_device_elems), jnp.float32),
        NamedSharding(mesh, P("d", None)),
    )

    def make_loop(k: int):
        @jax.jit
        @partial(shard_map, mesh=mesh, in_specs=P("d", None), out_specs=P("d", None))
        def loop(x):
            # k back-to-back psums chained through the loop carry: one
            # dispatch covers k collectives, so host/dispatch round-trips
            # (large on tunneled backends) stay out of the per-iteration
            # time. block_until_ready can return before remote work
            # finishes there, so completion is forced by fetching a value.
            def body(i, y):
                del i
                if n == 1:
                    # A 1-device psum folds to identity and the whole loop
                    # constant-folds away (XLA strength-reduces y+c loops
                    # too); sqrt(y²+1) is a real read+write HBM pass per
                    # iteration it cannot fold, so the single-chip number
                    # reports in-chip memory bandwidth.
                    return jnp.sqrt(y * y + 1.0)
                # psum output is device-invariant; re-vary restores the
                # carry's varying-over-d type (no data movement).
                return _revary(jax.lax.psum(y, "d"), "d")

            return jax.lax.fori_loop(0, k, body, x)

        return loop

    # loop0 (zero iterations) measures the fixed dispatch+fetch cost alone;
    # subtracting it from the k-iteration loop leaves pure collective time.
    loop0, loopk = make_loop(0), make_loop(iters)

    def run(loop) -> float:
        t0 = time.perf_counter()
        out = loop(x)
        float(out.reshape(-1)[0])
        return time.perf_counter() - t0

    for _ in range(max(1, warmup)):  # compile both, warm the path
        run(loop0)
        run(loopk)
    # Best-of-3 filters host/tunnel jitter on each side.
    t0_fixed = min(run(loop0) for _ in range(3))
    tk = min(run(loopk) for _ in range(3))
    noise_limited = tk <= t0_fixed
    if noise_limited:
        # Jitter swamped the subtraction: fall back to the un-subtracted
        # total (dispatch included) — a conservative lower bound on
        # bandwidth — and say so rather than publish a clamped absurdity.
        dt = tk / iters
    else:
        dt = (tk - t0_fixed) / iters

    bytes_per_shard = per_device_elems * 4
    # Ring-allreduce algorithmic bus bandwidth (the NCCL busBw convention):
    # each device moves 2(n-1)/n * shard bytes over the fabric per
    # allreduce. n == 1 reports the in-chip HBM pass (read + write).
    bus_bytes = 2 * (n - 1) / n * bytes_per_shard if n > 1 else 2 * bytes_per_shard
    return {
        "metric": "psum_allreduce_bus_bandwidth",
        "value": round(bus_bytes / dt / 1e9, 3),
        "unit": "GB/s",
        "n_devices": n,
        "size_mib_per_device": size_mib,
        "time_per_allreduce_ms": round(dt * 1e3, 4),
        "noise_limited": noise_limited,
        "platform": devices[0].platform,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="allreduce-bench")
    parser.add_argument("--size-mib", type=float, default=64.0)
    parser.add_argument("--iters", type=int, default=20)
    args = parser.parse_args(argv)
    print(json.dumps(psum_bandwidth(size_mib=args.size_mib, iters=args.iters)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
