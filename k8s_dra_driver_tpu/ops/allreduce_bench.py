"""jax.psum allreduce bandwidth benchmark — the nvbandwidth analog.

The job a user schedules onto a freshly assembled ComputeDomain to prove the
ICI fabric delivers (BASELINE.md: "jax.psum GB/s on the allocated slice").
Runs under ``shard_map`` over every available device; algorithmic bus
bandwidth uses the ring-allreduce factor 2(n-1)/n, the convention NCCL
benchmarks report, so numbers compare 1:1 with the reference ecosystem's
nvbandwidth/nccl-tests figures.

Usage (inside a claimed container, or anywhere JAX sees devices):
    python -m k8s_dra_driver_tpu.ops.allreduce_bench [--size-mib 256] [--iters 30]
"""

from __future__ import annotations

import argparse
import json
import time
from functools import partial
from typing import Optional, Sequence


def psum_bandwidth(
    size_mib: float = 64.0,
    iters: int = 20,
    devices: Optional[Sequence] = None,
    warmup: int = 3,
) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    mesh = Mesh(np.array(devices), ("d",))
    per_device_elems = int(size_mib * (1 << 20) // 4)
    x = jax.device_put(
        jnp.ones((n, per_device_elems), jnp.float32),
        NamedSharding(mesh, P("d", None)),
    )

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=P("d", None), out_specs=P("d", None))
    def allreduce(x):
        return jax.lax.psum(x, "d")[None]

    # At least one untimed call: compilation must stay out of the timing.
    for _ in range(max(1, warmup)):
        out = allreduce(x)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = allreduce(x)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters

    bytes_per_shard = per_device_elems * 4
    # Ring-allreduce algorithmic bus bandwidth (the NCCL busBw convention):
    # each device moves 2(n-1)/n * shard bytes over the fabric per allreduce.
    bus_bytes = 2 * (n - 1) / n * bytes_per_shard if n > 1 else bytes_per_shard
    return {
        "metric": "psum_allreduce_bus_bandwidth",
        "value": round(bus_bytes / dt / 1e9, 3),
        "unit": "GB/s",
        "n_devices": n,
        "size_mib_per_device": size_mib,
        "time_per_allreduce_ms": round(dt * 1e3, 4),
        "platform": devices[0].platform,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="allreduce-bench")
    parser.add_argument("--size-mib", type=float, default=64.0)
    parser.add_argument("--iters", type=int, default=20)
    args = parser.parse_args(argv)
    print(json.dumps(psum_bandwidth(size_mib=args.size_mib, iters=args.iters)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
