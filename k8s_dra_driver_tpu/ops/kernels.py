"""Pallas TPU kernels for the workload hot path.

Two fused kernels the flagship workload leans on, written against the MXU/
VMEM model from the Pallas TPU guide: a fused RMSNorm (one VMEM round-trip
instead of three HBM-bound elementwise passes) and a tiled matmul with
float32 accumulation feeding the MXU in (8,128)-aligned blocks. Off-TPU the
kernels run in interpreter mode so CPU CI tests the same code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # noqa: BLE001 — no backend at all
        return False


# -- fused RMSNorm -----------------------------------------------------------

def _rmsnorm_kernel(x_ref, g_ref, o_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)
    r = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    o_ref[...] = (x * r * g_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_rows", "eps", "interpret"))
def rmsnorm(
    x: jax.Array,
    gain: jax.Array,
    *,
    block_rows: int = 256,
    eps: float = 1e-6,
    interpret: bool | None = None,
) -> jax.Array:
    """RMSNorm over the last dim. x: [..., d]; gain: [d]."""
    if interpret is None:
        interpret = not _on_tpu()
    orig_shape = x.shape
    d = orig_shape[-1]
    rows = 1
    for s in orig_shape[:-1]:
        rows *= s
    if rows == 0 or d == 0:
        return x
    x2 = x.reshape(rows, d)
    block_rows = min(block_rows, rows)
    if rows % block_rows != 0:
        block_rows = 1  # degenerate fallback keeps the grid exact
    out = pl.pallas_call(
        functools.partial(_rmsnorm_kernel, eps=eps),
        grid=(rows // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
        interpret=interpret,
    )(x2, gain)
    return out.reshape(orig_shape)


# -- tiled matmul ------------------------------------------------------------

def _matmul_kernel(a_ref, b_ref, o_ref):
    o_ref[...] = jnp.dot(
        a_ref[...], b_ref[...], preferred_element_type=jnp.float32
    ).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("bm", "bn", "interpret"))
def tiled_matmul(
    a: jax.Array,
    b: jax.Array,
    *,
    bm: int = 256,
    bn: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """a[M,K] @ b[K,N] with f32 accumulation, tiled (bm, bn) for the MXU."""
    if interpret is None:
        interpret = not _on_tpu()
    m, k = a.shape
    k2, n = b.shape
    assert k == k2, (a.shape, b.shape)
    bm = min(bm, m)
    bn = min(bn, n)
    if m == 0 or n == 0 or m % bm != 0 or n % bn != 0:
        # Shape not tileable: let XLA handle it (still fused fine).
        return jnp.dot(a, b, preferred_element_type=jnp.float32).astype(a.dtype)
    return pl.pallas_call(
        _matmul_kernel,
        grid=(m // bm, n // bn),
        in_specs=[
            pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
            pl.BlockSpec((k, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), a.dtype),
        interpret=interpret,
    )(a, b)
