"""Pallas TPU kernels, written against the MXU/VMEM model from the Pallas
TPU guide: a fused RMSNorm (one VMEM round-trip instead of three HBM-bound
elementwise passes; differentiable via an analytical custom VJP) and a
tiled matmul with float32 accumulation feeding the MXU in (8,128)-aligned
blocks. Off-TPU the kernels run in interpreter mode so CPU CI tests the
same code path.

Where they're used — and deliberately not: the models keep plain-jnp
RMSNorm in their *training* graphs because an A/B on v5e (r4, bench
config) measured the pallas version ~2% slower there — a pallas_call is a
fusion barrier, and XLA otherwise fuses the norm into its neighbors. The
kernel earns its keep standalone (inference-style whole-op use, where the
single VMEM pass beats three unfused HBM passes) and as the in-repo
reference for the Pallas authoring pattern. The flagship's TPU kernel in
the training hot path is flash attention (9.3× over einsum at seq 8k,
docs/benchmarks.md). The load-bearing in-repo kernel is the fused
cross-entropy (ops/fused_ce.py): the flagship's evaluate_nll scoring
path runs on it — 1.4-1.5× over the materializing loss at vocab ≥ 32k
on v5e, and the only path when the [tokens, vocab] logits exceed HBM.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # noqa: BLE001 — no backend at all
        return False


# -- fused RMSNorm -----------------------------------------------------------

def _rmsnorm_kernel(x_ref, g_ref, o_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)
    r = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    o_ref[...] = (x * r * g_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _rmsnorm_forward(x, gain, eps: float, block_rows: int, interpret: bool):
    orig_shape = x.shape
    d = orig_shape[-1]
    rows = 1
    for s in orig_shape[:-1]:
        rows *= s
    if rows == 0 or d == 0:
        return x
    x2 = x.reshape(rows, d)
    block_rows = min(block_rows, rows)
    if rows % block_rows != 0:
        block_rows = 1  # degenerate fallback keeps the grid exact
    out = pl.pallas_call(
        functools.partial(_rmsnorm_kernel, eps=eps),
        grid=(rows // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
        interpret=interpret,
    )(x2, gain)
    return out.reshape(orig_shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _rmsnorm_cv(x, gain, eps, block_rows, interpret):
    return _rmsnorm_forward(x, gain, eps, block_rows, interpret)


def _rmsnorm_cv_fwd(x, gain, eps, block_rows, interpret):
    return _rmsnorm_forward(x, gain, eps, block_rows, interpret), (x, gain)


def _rmsnorm_cv_bwd(eps, block_rows, interpret, res, dy):
    # Analytical backward in plain jnp (XLA fuses it): with
    # r = rsqrt(mean(x²)+eps) and y = x·r·g,
    #   dx = r·g·dy − x · r³/d · Σ_d(x·g·dy)
    #   dg = Σ_rows(x·r·dy)
    x, gain = res
    xf = x.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    gf = gain.astype(jnp.float32)
    d = x.shape[-1]
    r = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    gdy = gf * dyf
    dx = r * gdy - xf * (r ** 3 / d) * jnp.sum(xf * gdy, axis=-1, keepdims=True)
    dg = jnp.sum((xf * r) * dyf, axis=tuple(range(x.ndim - 1)))
    return dx.astype(x.dtype), dg.astype(gain.dtype)


_rmsnorm_cv.defvjp(_rmsnorm_cv_fwd, _rmsnorm_cv_bwd)


@functools.partial(jax.jit, static_argnames=("block_rows", "eps", "interpret"))
def rmsnorm(
    x: jax.Array,
    gain: jax.Array,
    *,
    block_rows: int = 256,
    eps: float = 1e-6,
    interpret: bool | None = None,
) -> jax.Array:
    """RMSNorm over the last dim. x: [..., d]; gain: [d]. Differentiable:
    the Pallas kernel runs the forward; the analytical VJP runs in plain
    jnp (a pallas_call has no autodiff rule, so without this the kernel
    would crash any training graph it appears in)."""
    if interpret is None:
        interpret = not _on_tpu()
    return _rmsnorm_cv(x, gain, eps, block_rows, interpret)


# -- tiled matmul ------------------------------------------------------------

def _matmul_kernel(a_ref, b_ref, o_ref):
    o_ref[...] = jnp.dot(
        a_ref[...], b_ref[...], preferred_element_type=jnp.float32
    ).astype(o_ref.dtype)


def _tiled_matmul_forward(a, b, bm: int, bn: int, interpret: bool):
    m, k = a.shape
    k2, n = b.shape
    assert k == k2, (a.shape, b.shape)
    bm = min(bm, m)
    bn = min(bn, n)
    if m == 0 or n == 0 or m % bm != 0 or n % bn != 0:
        # Shape not tileable: let XLA handle it (still fused fine).
        return jnp.dot(a, b, preferred_element_type=jnp.float32).astype(a.dtype)
    return pl.pallas_call(
        _matmul_kernel,
        grid=(m // bm, n // bn),
        in_specs=[
            pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
            pl.BlockSpec((k, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), a.dtype),
        interpret=interpret,
    )(a, b)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _tiled_matmul_cv(a, b, bm, bn, interpret):
    return _tiled_matmul_forward(a, b, bm, bn, interpret)


def _tiled_matmul_cv_fwd(a, b, bm, bn, interpret):
    return _tiled_matmul_forward(a, b, bm, bn, interpret), (a, b)


def _tiled_matmul_cv_bwd(bm, bn, interpret, res, dy):
    # The matmul VJP is two matmuls — run them through the same kernel
    # (transposes are free relayouts for XLA): dA = dY·Bᵀ, dB = Aᵀ·dY.
    a, b = res
    da = _tiled_matmul_forward(dy, b.T, bm, bn, interpret)
    db = _tiled_matmul_forward(a.T, dy, bm, bn, interpret)
    return da.astype(a.dtype), db.astype(b.dtype)


_tiled_matmul_cv.defvjp(_tiled_matmul_cv_fwd, _tiled_matmul_cv_bwd)


@functools.partial(jax.jit, static_argnames=("bm", "bn", "interpret"))
def tiled_matmul(
    a: jax.Array,
    b: jax.Array,
    *,
    bm: int = 256,
    bn: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """a[M,K] @ b[K,N] with f32 accumulation, tiled (bm, bn) for the MXU.
    Differentiable: the VJP's two matmuls run through the same kernel."""
    if interpret is None:
        interpret = not _on_tpu()
    return _tiled_matmul_cv(a, b, bm, bn, interpret)
