"""KubernetesAPIServer — the real-cluster adapter (client-go analog).

Implements the `k8s.APIServer` interface (create/get/try_get/list/update/
delete/update_with_retry/watch/stop_watch/list_and_watch) over the real
Kubernetes REST wire, so all five binaries run unmodified against a live
apiserver with ``--api-backend kubernetes``
(reference: /root/reference/pkg/flags/kubeclient.go builds the same three
clientsets from kubeconfig/in-cluster config).

Auth/endpoint resolution order:
  1. explicit base_url (tests / conformance server — plain HTTP)
  2. kubeconfig (--kubeconfig flag or $KUBECONFIG or ~/.kube/config):
     server URL, CA, bearer token or client cert/key (inline *-data
     variants are materialized to temp files for ssl)
  3. in-cluster service account ($KUBERNETES_SERVICE_HOST +
     /var/run/secrets/kubernetes.io/serviceaccount/{token,ca.crt})

Update semantics: kinds with a status subresource get two-phase writes —
PUT the main resource (apiserver ignores status changes), then PUT
.../status with the returned resourceVersion — because a real apiserver
silently drops status edits on the main resource once the subresource is
enabled. Watch uses JSON-lines streaming with the same
reconnect-and-resync discipline as RemoteAPIServer (synthesized DELETED
events after an outage, seeded from list_and_watch snapshots).
"""

from __future__ import annotations

import base64
import json
import logging
import os
import queue
import ssl
import tempfile
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from k8s_dra_driver_tpu.k8s.k8swire import (
    RESOURCE_MAP,
    api_path,
    from_k8s_wire,
    group_version_split,
    served_versions,
    to_k8s_wire,
)
from k8s_dra_driver_tpu.k8s.k8sapiserver import STATUS_SUBRESOURCE_KINDS
from k8s_dra_driver_tpu.k8s.objects import (
    AlreadyExistsError,
    ApiError,
    ConflictError,
    K8sObject,
    NotFoundError,
)
from k8s_dra_driver_tpu.k8s.store import WatchEvent

log = logging.getLogger(__name__)

SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

_REASON_ERROR = {
    "NotFound": NotFoundError,
    "AlreadyExists": AlreadyExistsError,
    "Conflict": ConflictError,
}


class KubeConfigError(ApiError):
    pass


def _materialize(data_b64: str, suffix: str) -> str:
    """Write inline base64 kubeconfig data to a temp file for ssl."""
    f = tempfile.NamedTemporaryFile(
        mode="wb", suffix=suffix, prefix="tpu-dra-kube-", delete=False
    )
    f.write(base64.b64decode(data_b64))
    f.close()
    return f.name


class KubeAuth:
    """Resolved endpoint + credentials."""

    def __init__(self, server: str, token: str = "",
                 ca_file: str = "", client_cert: str = "", client_key: str = "",
                 insecure: bool = False):
        self.server = server.rstrip("/")
        self.token = token
        self.ca_file = ca_file
        self.client_cert = client_cert
        self.client_key = client_key
        self.insecure = insecure

    def ssl_context(self) -> Optional[ssl.SSLContext]:
        if not self.server.startswith("https"):
            return None
        if self.insecure:
            ctx = ssl.create_default_context()
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        elif self.ca_file:
            ctx = ssl.create_default_context(cafile=self.ca_file)
        else:
            ctx = ssl.create_default_context()
        if self.client_cert:
            ctx.load_cert_chain(self.client_cert, self.client_key or None)
        return ctx

    @staticmethod
    def from_kubeconfig(path: str, context: str = "") -> "KubeAuth":
        import yaml

        with open(path, "r", encoding="utf-8") as f:
            cfg = yaml.safe_load(f) or {}
        ctx_name = context or cfg.get("current-context", "")
        ctx = next(
            (c["context"] for c in cfg.get("contexts", [])
             if c.get("name") == ctx_name),
            None,
        )
        if ctx is None:
            raise KubeConfigError(
                f"kubeconfig {path}: context {ctx_name!r} not found"
            )
        cluster = next(
            (c["cluster"] for c in cfg.get("clusters", [])
             if c.get("name") == ctx.get("cluster")),
            None,
        )
        user = next(
            (u["user"] for u in cfg.get("users", [])
             if u.get("name") == ctx.get("user")),
            {},
        )
        if cluster is None or not cluster.get("server"):
            raise KubeConfigError(f"kubeconfig {path}: no cluster server")
        ca_file = cluster.get("certificate-authority", "")
        if not ca_file and cluster.get("certificate-authority-data"):
            ca_file = _materialize(cluster["certificate-authority-data"], ".crt")
        cert = user.get("client-certificate", "")
        if not cert and user.get("client-certificate-data"):
            cert = _materialize(user["client-certificate-data"], ".crt")
        key = user.get("client-key", "")
        if not key and user.get("client-key-data"):
            key = _materialize(user["client-key-data"], ".key")
        return KubeAuth(
            server=cluster["server"],
            token=user.get("token", ""),
            ca_file=ca_file,
            client_cert=cert,
            client_key=key,
            insecure=bool(cluster.get("insecure-skip-tls-verify", False)),
        )

    @staticmethod
    def in_cluster(sa_dir: str = SERVICE_ACCOUNT_DIR) -> "KubeAuth":
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        if not host:
            raise KubeConfigError(
                "not in-cluster: KUBERNETES_SERVICE_HOST unset"
            )
        token_path = os.path.join(sa_dir, "token")
        with open(token_path, "r", encoding="utf-8") as f:
            token = f.read().strip()
        ca = os.path.join(sa_dir, "ca.crt")
        return KubeAuth(
            server=f"https://{host}:{port}",
            token=token,
            ca_file=ca if os.path.exists(ca) else "",
        )

    @staticmethod
    def resolve(kubeconfig: str = "", context: str = "") -> "KubeAuth":
        """Kubeconfig (explicit > $KUBECONFIG > ~/.kube/config) else
        in-cluster — the kubeclient.go resolution order."""
        path = kubeconfig or os.environ.get("KUBECONFIG", "")
        if not path:
            default = os.path.expanduser("~/.kube/config")
            if os.path.exists(default):
                path = default
        if path:
            return KubeAuth.from_kubeconfig(path, context)
        return KubeAuth.in_cluster()


class KubernetesAPIServer:
    """APIServer-interface adapter over the real k8s REST wire."""

    def __init__(self, auth: Optional[KubeAuth] = None, base_url: str = "",
                 timeout: float = 30.0):
        if auth is None:
            if not base_url:
                raise KubeConfigError("KubernetesAPIServer needs auth or base_url")
            auth = KubeAuth(server=base_url)
        self.auth = auth
        self.timeout = timeout
        self._ssl = auth.ssl_context()
        self._watch_stops: Dict[int, threading.Event] = {}
        self._watch_known: Dict[int, Dict[Tuple[str, str], K8sObject]] = {}
        # group -> negotiated bare version (filled lazily via discovery).
        self._group_version: Dict[str, str] = {}
        self._group_version_lock = threading.Lock()

    # -- version negotiation -------------------------------------------------

    def _negotiated_version(self, kind: str) -> str:
        """For multi-version kinds, pick the newest version this client
        speaks that the server serves (client-go discovery analog): GET
        /apis/<group>, intersect with our served list, prefer ours first.
        Returns '' for single-version kinds (use the RESOURCE_MAP path)."""
        ours = served_versions(kind)
        if len(ours) == 1:
            return ""
        group, _ = group_version_split(RESOURCE_MAP[kind][0])
        with self._group_version_lock:
            cached = self._group_version.get(group)
        if cached:
            return cached
        chosen = ours[0]
        try:
            doc = self._request("GET", f"/apis/{group}")
            theirs = {v.get("version") for v in doc.get("versions") or []}
            chosen = next((v for v in ours if v in theirs), ours[0])
        except (ApiError, OSError) as e:
            # 1.30-ish servers may 403 discovery to anonymous users; fall
            # back to our preferred version rather than failing closed —
            # but do NOT cache, so a transient startup failure doesn't pin
            # the wrong version for the life of the process.
            log.warning("discovery for group %s failed (%s); assuming %s",
                        group, e, chosen)
            return chosen
        with self._group_version_lock:
            self._group_version[group] = chosen
        return chosen

    def _path(self, kind: str, namespace: str = "", name: str = "") -> str:
        return api_path(kind, namespace, name,
                        api_version=self._negotiated_version(kind))

    def _to_wire(self, obj: K8sObject) -> dict:
        return to_k8s_wire(obj, self._negotiated_version(obj.kind))

    # -- plumbing ----------------------------------------------------------

    def _headers(self) -> Dict[str, str]:
        h = {"Content-Type": "application/json", "Accept": "application/json"}
        if self.auth.token:
            h["Authorization"] = f"Bearer {self.auth.token}"
        return h

    def _request(self, method: str, path: str,
                 body: Optional[dict] = None) -> dict:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.auth.server + path, data=data, method=method,
            headers=self._headers(),
        )
        try:
            with urllib.request.urlopen(
                req, timeout=self.timeout, context=self._ssl
            ) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            doc: Dict = {}
            try:
                doc = json.loads(e.read() or b"{}")
            except json.JSONDecodeError:
                pass
            reason = doc.get("reason", "")
            err_cls = _REASON_ERROR.get(reason)
            if err_cls is None:
                err_cls = {404: NotFoundError, 409: ConflictError}.get(
                    e.code, ApiError
                )
            raise err_cls(doc.get("message", str(e))) from None

    # -- interface ----------------------------------------------------------

    def create(self, obj: K8sObject) -> K8sObject:
        path = self._path(obj.kind, obj.meta.namespace)
        return from_k8s_wire(self._request("POST", path, self._to_wire(obj)))

    def get(self, kind: str, name: str, namespace: str = "",
            copy: bool = False) -> K8sObject:
        # ``copy`` is signature parity with the in-process store's
        # zero-copy reads: wire deserialization already yields a private
        # mutable object, so there is nothing further to copy.
        return from_k8s_wire(
            self._request("GET", self._path(kind, namespace, name))
        )

    def try_get(self, kind: str, name: str, namespace: str = "",
                copy: bool = False) -> Optional[K8sObject]:
        try:
            return self.get(kind, name, namespace)
        except NotFoundError:
            return None

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        copy: bool = False,
    ) -> List[K8sObject]:
        path = self._path(kind, namespace or "")
        params = {}
        if label_selector:
            params["labelSelector"] = ",".join(
                f"{k}={v}" for k, v in sorted(label_selector.items())
            )
        if params:
            path += "?" + urllib.parse.urlencode(params)
        doc = self._request("GET", path)
        return [from_k8s_wire(d) for d in doc.get("items", [])]

    def update(self, obj: K8sObject) -> K8sObject:
        path = self._path(obj.kind, obj.meta.namespace, obj.meta.name)
        wire = self._to_wire(obj)
        updated = from_k8s_wire(self._request("PUT", path, wire))
        if obj.kind in STATUS_SUBRESOURCE_KINDS:
            # Second phase: the main PUT ignored status changes; write them
            # through the subresource with the fresh resourceVersion.
            wire["metadata"]["resourceVersion"] = str(
                updated.meta.resource_version
            )
            try:
                updated = from_k8s_wire(
                    self._request("PUT", path + "/status", wire)
                )
            except NotFoundError:
                # The main PUT completed a finalizer-gated deletion (last
                # finalizer removed on a deleting object) — the object is
                # legitimately gone; the main result is the final word.
                pass
        return updated

    def delete(self, kind: str, name: str, namespace: str = "") -> None:
        self._request("DELETE", self._path(kind, namespace, name))

    def update_with_retry(
        self, kind: str, name: str, namespace: str,
        mutate: Callable[[K8sObject], None], attempts: int = 10,
    ) -> K8sObject:
        last: Optional[ConflictError] = None
        for _ in range(attempts):
            obj = self.get(kind, name, namespace)
            mutate(obj)
            try:
                return self.update(obj)
            except ConflictError as e:
                last = e
        raise last  # type: ignore[misc]

    # -- watch ---------------------------------------------------------------

    def _watch_path(self, kind: str, name: Optional[str],
                    namespace: Optional[str]) -> str:
        path = self._path(kind, namespace or "")
        params: Dict[str, str] = {"watch": "true"}
        if name:
            params["fieldSelector"] = f"metadata.name={name}"
        return path + "?" + urllib.parse.urlencode(params)

    def watch(
        self, kind: str, name: Optional[str] = None,
        namespace: Optional[str] = None, maxsize: int = 0,
    ) -> "queue.Queue[WatchEvent]":
        # ``maxsize`` keeps the APIServer.watch signature (informers and
        # the sim pass it); the client queue stays unbounded like
        # RemoteAPIServer's — the reader thread drains it, and a cap here
        # would stall replay_list() against a slow consumer.
        q: "queue.Queue[WatchEvent]" = queue.Queue()
        stop = threading.Event()
        connected = threading.Event()
        self._watch_stops[id(q)] = stop
        known: Dict[Tuple[str, str], K8sObject] = {}
        self._watch_known[id(q)] = known
        path = self._watch_path(kind, name, namespace)

        def emit(ev_type: str, obj: K8sObject) -> None:
            key = (obj.namespace or "", obj.meta.name)
            if ev_type == "DELETED":
                known.pop(key, None)
            else:
                known[key] = obj
            q.put(WatchEvent(ev_type, obj))

        def replay_list() -> None:
            live = {}
            for obj in self.list(kind, namespace=namespace):
                if name is None or obj.meta.name == name:
                    live[(obj.namespace or "", obj.meta.name)] = obj
            for key, obj in list(known.items()):
                if key not in live:
                    emit("DELETED", obj)
            for obj in live.values():
                emit("ADDED", obj)

        def stream_once(resync: bool) -> None:
            req = urllib.request.Request(
                self.auth.server + path, headers=self._headers()
            )
            with urllib.request.urlopen(
                req, timeout=None, context=self._ssl
            ) as resp:
                # Response headers arrived: the server registered the
                # subscription before sending them, so events emitted after
                # watch() returns are guaranteed delivered (the wire has no
                # SYNC marker; this ordering is the handshake).
                connected.set()
                if resync:
                    # Reconnected: diff current state against what this
                    # watch had delivered; informers absorb the replays.
                    replay_list()
                for raw in resp:
                    if stop.is_set():
                        return
                    doc = json.loads(raw)
                    ev_type = doc.get("type", "")
                    if ev_type in ("BOOKMARK", "ERROR"):
                        continue
                    emit(ev_type, from_k8s_wire(doc.get("object") or {}))

        def reader() -> None:
            first = True
            try:
                while not stop.is_set():
                    try:
                        stream_once(resync=not first)
                        if not stop.is_set():
                            log.warning("k8s watch for %s ended; reconnecting",
                                        kind)
                    except (OSError, json.JSONDecodeError, ApiError, ValueError):
                        if stop.is_set():
                            return
                        log.warning("k8s watch for %s errored; reconnecting",
                                    kind)
                    first = False
                    connected.set()  # never leave the caller blocked
                    stop.wait(timeout=1.0)
            finally:
                connected.set()

        threading.Thread(target=reader, name=f"k8s-watch-{kind}",
                         daemon=True).start()
        connected.wait(timeout=self.timeout)
        return q

    def stop_watch(self, kind: str, q: "queue.Queue[WatchEvent]") -> None:
        self._watch_known.pop(id(q), None)
        stop = self._watch_stops.pop(id(q), None)
        if stop:
            stop.set()

    def list_and_watch(
        self, kind: str, name: Optional[str] = None,
        namespace: Optional[str] = None, maxsize: int = 0,
    ) -> Tuple[List[K8sObject], "queue.Queue[WatchEvent]"]:
        """Watch-then-list: at-least-once, like RemoteAPIServer — events
        racing the list may duplicate snapshot objects; informer caches
        absorb replays."""
        q = self.watch(kind, name=name, namespace=namespace, maxsize=maxsize)
        objs = self.list(kind, namespace=namespace)
        if name is not None:
            objs = [o for o in objs if o.meta.name == name]
        known = self._watch_known.get(id(q))
        if known is not None:
            for obj in objs:
                known.setdefault((obj.namespace or "", obj.meta.name), obj)
        return objs, q
