"""Internal objects <-> real Kubernetes JSON wire format.

The sim/http tiers speak the compact internal wire (`serialize.py`); a real
cluster speaks the Kubernetes API forms — `resource.k8s.io/v1beta1`
ResourceSlice/ResourceClaim (KEP-4381 shapes), `apps/v1` DaemonSet,
`coordination.k8s.io/v1` Lease, and the `resource.tpu.google.com/v1beta1`
CRDs this driver ships. This codec is the client-go-generated-types analog
(reference: /root/reference/pkg/nvidia.com + vendored k8s.io/api): one
encode/decode pair per kind, exercised from both sides by the conformance
apiserver (`k8sapiserver.py`) and the real-cluster adapter
(`kubeclient.py`).

Lossiness is deliberate and one-way only: sim-only fields (Pod.injected_*)
do not encode; unknown incoming fields are ignored the way client-go drops
unknown JSON members.
"""

from __future__ import annotations

import datetime
import re
from typing import Any, Dict, List, Optional, Tuple

from k8s_dra_driver_tpu.api.computedomain import (
    ComputeDomain,
    ComputeDomainChannelSpec,
    ComputeDomainClique,
    ComputeDomainDaemonInfo,
    ComputeDomainNode,
    ComputeDomainPlacement,
    ComputeDomainResize,
    ComputeDomainSpec,
    ComputeDomainStatus,
)
from k8s_dra_driver_tpu.api.servinggroup import (
    ServingGroup,
    ServingGroupSpec,
    ServingGroupStatus,
    ServingReplicaTemplate,
    ServingScalingPolicy,
    ServingSLO,
    ServingTraffic,
    ServingTrafficStatus,
)
from k8s_dra_driver_tpu.api.tenantquota import (
    TenantQuota,
    TenantQuotaSpec,
    TenantQuotaStatus,
)
from k8s_dra_driver_tpu.k8s.conditions import Condition
from k8s_dra_driver_tpu.pkg.meshgen import MeshBundle, MeshDevice
from k8s_dra_driver_tpu.k8s.core import (
    AllocationResult,
    Container,
    Counter,
    CounterSet,
    DaemonSet,
    Deployment,
    Device,
    DeviceClaimConfig,
    DeviceClass,
    DeviceCounterConsumption,
    DeviceRequest,
    DeviceRequestAllocationResult,
    DeviceTaint,
    Node,
    NodeTaint,
    ObservedFootprint,
    OpaqueDeviceConfig,
    Pod,
    PodCondition,
    PodResourceClaimRef,
    PodTemplate,
    ResourceClaim,
    ResourceClaimConsumer,
    ResourceClaimTemplate,
    ResourcePool,
    ResourceSlice,
    UtilizationSummary,
    RegisteredWebhook,
    ValidatingWebhookConfiguration,
    WebhookClientConfig,
    WebhookRule,
)
from k8s_dra_driver_tpu.k8s.objects import K8sObject, ObjectMeta, OwnerReference
from k8s_dra_driver_tpu.pkg.leaderelection import Lease

# kind -> (apiVersion, plural, namespaced)
RESOURCE_MAP: Dict[str, Tuple[str, str, bool]] = {
    "Pod": ("v1", "pods", True),
    "Node": ("v1", "nodes", False),
    "DaemonSet": ("apps/v1", "daemonsets", True),
    "Deployment": ("apps/v1", "deployments", True),
    # resource.k8s.io is served at BOTH v1 (GA, 1.34+; the preferred wire
    # here, matching the reference's demo/specs/quickstart/v1) and v1beta1
    # (see SERVED_VERSIONS); kubeclient negotiates via discovery.
    "ResourceClaim": ("resource.k8s.io/v1", "resourceclaims", True),
    "ResourceClaimTemplate": ("resource.k8s.io/v1", "resourceclaimtemplates", True),
    "ResourceSlice": ("resource.k8s.io/v1", "resourceslices", False),
    "DeviceClass": ("resource.k8s.io/v1", "deviceclasses", False),
    "ComputeDomain": ("resource.tpu.google.com/v1beta1", "computedomains", True),
    "ComputeDomainClique": ("resource.tpu.google.com/v1beta1", "computedomaincliques", True),
    "ServingGroup": ("resource.tpu.google.com/v1beta1", "servinggroups", True),
    "TenantQuota": ("resource.tpu.google.com/v1beta1", "tenantquotas", True),
    "Lease": ("coordination.k8s.io/v1", "leases", True),
    "ValidatingWebhookConfiguration": (
        "admissionregistration.k8s.io/v1", "validatingwebhookconfigurations",
        False,
    ),
}

# group -> every served version, preferred first. Groups not listed serve
# only their RESOURCE_MAP version.
SERVED_VERSIONS: Dict[str, List[str]] = {
    "resource.k8s.io": ["v1", "v1beta1"],
}

_PLURAL_TO_KIND = {plural: kind for kind, (_, plural, _ns) in RESOURCE_MAP.items()}


def kind_for_plural(plural: str) -> Optional[str]:
    return _PLURAL_TO_KIND.get(plural)


def group_version_split(api_version: str) -> Tuple[str, str]:
    """'resource.k8s.io/v1' -> ('resource.k8s.io', 'v1'); 'v1' -> ('', 'v1')."""
    if "/" in api_version:
        group, _, version = api_version.rpartition("/")
        return group, version
    return "", api_version


def served_versions(kind: str) -> List[str]:
    api_version, _, _ = RESOURCE_MAP[kind]
    group, version = group_version_split(api_version)
    return SERVED_VERSIONS.get(group, [version])


def api_path(kind: str, namespace: str = "", name: str = "",
             api_version: str = "") -> str:
    """REST path for a kind: /api/v1/... (core) or /apis/<group>/...
    `api_version` (bare version like 'v1beta1') overrides the preferred."""
    preferred, plural, namespaced = RESOURCE_MAP[kind]
    group, version = group_version_split(preferred)
    if api_version:
        version = api_version
    root = f"/api/{version}" if not group else f"/apis/{group}/{version}"
    path = root
    if namespaced and namespace:
        path += f"/namespaces/{namespace}"
    path += f"/{plural}"
    if name:
        path += f"/{name}"
    return path


# -- timestamps -------------------------------------------------------------


def _ts_encode(epoch: Optional[float]) -> Optional[str]:
    if not epoch:
        return None
    dt = datetime.datetime.fromtimestamp(epoch, tz=datetime.timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%SZ")


def _ts_encode_micro(epoch: Optional[float]) -> Optional[str]:
    if not epoch:
        return None
    dt = datetime.datetime.fromtimestamp(epoch, tz=datetime.timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%S.%fZ")


def _ts_decode(s: Optional[str]) -> float:
    if not s:
        return 0.0
    s = s.replace("Z", "+00:00")
    try:
        return datetime.datetime.fromisoformat(s).timestamp()
    except ValueError:
        return 0.0


# -- metadata ---------------------------------------------------------------


def _meta_encode(meta: ObjectMeta) -> Dict[str, Any]:
    md: Dict[str, Any] = {"name": meta.name}
    if meta.namespace:
        md["namespace"] = meta.namespace
    if meta.uid:
        md["uid"] = meta.uid
    if meta.resource_version:
        md["resourceVersion"] = str(meta.resource_version)
    if meta.generation:
        md["generation"] = meta.generation
    if meta.labels:
        md["labels"] = dict(meta.labels)
    if meta.annotations:
        md["annotations"] = dict(meta.annotations)
    if meta.finalizers:
        md["finalizers"] = list(meta.finalizers)
    if meta.owner_references:
        md["ownerReferences"] = [
            {
                "apiVersion": RESOURCE_MAP.get(r.kind, ("v1",))[0],
                "kind": r.kind,
                "name": r.name,
                "uid": r.uid,
                "controller": r.controller,
            }
            for r in meta.owner_references
        ]
    if meta.creation_timestamp:
        md["creationTimestamp"] = _ts_encode(meta.creation_timestamp)
    if meta.deletion_timestamp is not None:
        md["deletionTimestamp"] = _ts_encode(meta.deletion_timestamp)
    return md


def _meta_decode(md: Dict[str, Any]) -> ObjectMeta:
    rv_raw = md.get("resourceVersion", "0")
    try:
        rv = int(rv_raw)
    except (TypeError, ValueError):
        # Opaque non-decimal resourceVersion: keep CAS semantics by hashing
        # into an int — the adapter echoes the original string on writes.
        rv = abs(hash(rv_raw)) % (1 << 62)
    return ObjectMeta(
        name=md.get("name", ""),
        namespace=md.get("namespace", ""),
        uid=md.get("uid", ""),
        resource_version=rv,
        generation=md.get("generation", 0),
        labels=dict(md.get("labels") or {}),
        annotations=dict(md.get("annotations") or {}),
        finalizers=list(md.get("finalizers") or []),
        owner_references=[
            OwnerReference(
                kind=r.get("kind", ""),
                name=r.get("name", ""),
                uid=r.get("uid", ""),
                controller=bool(r.get("controller", True)),
            )
            for r in md.get("ownerReferences") or []
        ],
        creation_timestamp=_ts_decode(md.get("creationTimestamp")),
        deletion_timestamp=(
            _ts_decode(md["deletionTimestamp"])
            if md.get("deletionTimestamp")
            else None
        ),
    )


# -- status conditions -------------------------------------------------------


def _conditions_encode(conditions: List[Condition]) -> List[Dict[str, Any]]:
    """metav1.Condition wire shape. Always emits type/status; reason,
    message, and lastTransitionTime only when set (matching how the
    apiserver prunes empty optionals)."""
    out = []
    for c in conditions:
        doc: Dict[str, Any] = {"type": c.type, "status": c.status}
        if c.reason:
            doc["reason"] = c.reason
        if c.message:
            doc["message"] = c.message
        if c.last_transition_time:
            doc["lastTransitionTime"] = _ts_encode(c.last_transition_time)
        out.append(doc)
    return out


def _conditions_decode(docs: List[Dict[str, Any]]) -> List[Condition]:
    return [
        Condition(
            type=d.get("type", ""),
            status=d.get("status", "Unknown"),
            reason=d.get("reason", ""),
            message=d.get("message", ""),
            last_transition_time=_ts_decode(d.get("lastTransitionTime")),
        )
        for d in docs or []
    ]


# -- containers / pod templates ---------------------------------------------


def _container_encode(c: Container) -> Dict[str, Any]:
    env: List[Dict[str, Any]] = [
        {"name": k, "value": v} for k, v in c.env.items()
    ]
    env += [
        {"name": k, "valueFrom": {"fieldRef": {"fieldPath": fp}}}
        for k, fp in c.downward_env.items()
    ]
    doc: Dict[str, Any] = {"name": c.name, "image": c.image}
    if c.command:
        doc["command"] = list(c.command)
    if env:
        doc["env"] = env
    if c.readiness_probe:
        doc["readinessProbe"] = {"exec": {"command": list(c.readiness_probe)}}
    return doc


def _container_decode(doc: Dict[str, Any]) -> Container:
    env: Dict[str, str] = {}
    downward: Dict[str, str] = {}
    for e in doc.get("env") or []:
        if "valueFrom" in e:
            fp = (e["valueFrom"].get("fieldRef") or {}).get("fieldPath", "")
            if fp:
                downward[e["name"]] = fp
        else:
            env[e["name"]] = str(e.get("value", ""))
    probe = ((doc.get("readinessProbe") or {}).get("exec") or {}).get("command", [])
    return Container(
        name=doc.get("name", "main"),
        image=doc.get("image", ""),
        command=list(doc.get("command") or []),
        env=env,
        downward_env=downward,
        readiness_probe=list(probe),
    )


def _claim_refs_encode(refs: List[PodResourceClaimRef]) -> List[Dict[str, Any]]:
    out = []
    for r in refs:
        doc: Dict[str, Any] = {"name": r.name}
        if r.resource_claim_name:
            doc["resourceClaimName"] = r.resource_claim_name
        if r.resource_claim_template_name:
            doc["resourceClaimTemplateName"] = r.resource_claim_template_name
        out.append(doc)
    return out


def _claim_refs_decode(docs: List[Dict[str, Any]]) -> List[PodResourceClaimRef]:
    return [
        PodResourceClaimRef(
            name=d.get("name", ""),
            resource_claim_name=d.get("resourceClaimName", ""),
            resource_claim_template_name=d.get("resourceClaimTemplateName", ""),
        )
        for d in docs or []
    ]


# -- Pod --------------------------------------------------------------------


def _pod_encode(p: Pod) -> Dict[str, Any]:
    spec: Dict[str, Any] = {
        "containers": [_container_encode(c) for c in p.containers],
    }
    if p.node_name:
        spec["nodeName"] = p.node_name
    if p.resource_claims:
        spec["resourceClaims"] = _claim_refs_encode(p.resource_claims)
    if p.priority_tier:
        spec["priorityTier"] = p.priority_tier
    conditions = [{"type": c.type, "status": c.status} for c in p.conditions]
    if p.ready and not any(c["type"] == "Ready" for c in conditions):
        conditions.append({"type": "Ready", "status": "True"})
    status: Dict[str, Any] = {"phase": p.phase}
    if p.pod_ip:
        status["podIP"] = p.pod_ip
    if conditions:
        status["conditions"] = conditions
    return {"spec": spec, "status": status}


def _pod_decode(doc: Dict[str, Any]) -> Pod:
    spec = doc.get("spec") or {}
    status = doc.get("status") or {}
    conditions = [
        PodCondition(type=c.get("type", ""), status=c.get("status", "False"))
        for c in status.get("conditions") or []
    ]
    ready = any(c.type == "Ready" and c.status == "True" for c in conditions)
    return Pod(
        meta=_meta_decode(doc.get("metadata") or {}),
        node_name=spec.get("nodeName", ""),
        containers=[_container_decode(c) for c in spec.get("containers") or []],
        resource_claims=_claim_refs_decode(spec.get("resourceClaims") or []),
        priority_tier=int(spec.get("priorityTier", 0)),
        phase=status.get("phase", "Pending"),
        pod_ip=status.get("podIP", ""),
        ready=ready,
        conditions=conditions,
    )


# -- Node -------------------------------------------------------------------


def _node_encode(n: Node) -> Dict[str, Any]:
    spec: Dict[str, Any] = {}
    if n.taints:
        spec["taints"] = [
            {"key": t.key, "value": t.value, "effect": t.effect} for t in n.taints
        ]
    status: Dict[str, Any] = {}
    if n.addresses:
        status["addresses"] = [
            {"type": k, "address": v} for k, v in n.addresses.items()
        ]
    if n.allocatable:
        status["allocatable"] = {k: str(v) for k, v in n.allocatable.items()}
    return {"spec": spec, "status": status}


def _node_decode(doc: Dict[str, Any]) -> Node:
    spec = doc.get("spec") or {}
    status = doc.get("status") or {}
    allocatable = {}
    for k, v in (status.get("allocatable") or {}).items():
        try:
            allocatable[k] = int(v)
        except (TypeError, ValueError):
            continue
    return Node(
        meta=_meta_decode(doc.get("metadata") or {}),
        taints=[
            NodeTaint(key=t.get("key", ""), value=t.get("value", ""),
                      effect=t.get("effect", "NoSchedule"))
            for t in spec.get("taints") or []
        ],
        addresses={
            a.get("type", ""): a.get("address", "")
            for a in status.get("addresses") or []
        },
        allocatable=allocatable,
    )


# -- DaemonSet / Deployment --------------------------------------------------


def _template_encode(t: PodTemplate, node_selector: Dict[str, str]) -> Dict[str, Any]:
    pod_spec: Dict[str, Any] = {
        "containers": [_container_encode(c) for c in t.containers],
    }
    if node_selector:
        pod_spec["nodeSelector"] = dict(node_selector)
    if t.resource_claims:
        pod_spec["resourceClaims"] = _claim_refs_encode(t.resource_claims)
    if t.env:
        # Template-level env applies to all containers at render time; keep
        # it as a pod annotation would be lossy — fold into each container.
        for c in pod_spec["containers"]:
            existing = {e["name"] for e in c.get("env", [])}
            c.setdefault("env", []).extend(
                {"name": k, "value": v} for k, v in t.env.items()
                if k not in existing
            )
    return {"metadata": {"labels": dict(t.labels)}, "spec": pod_spec}


def _template_decode(doc: Dict[str, Any]) -> Tuple[PodTemplate, Dict[str, str]]:
    md = doc.get("metadata") or {}
    spec = doc.get("spec") or {}
    tmpl = PodTemplate(
        labels=dict(md.get("labels") or {}),
        containers=[_container_decode(c) for c in spec.get("containers") or []],
        resource_claims=_claim_refs_decode(spec.get("resourceClaims") or []),
    )
    return tmpl, dict(spec.get("nodeSelector") or {})


def _daemonset_encode(ds: DaemonSet) -> Dict[str, Any]:
    return {
        "spec": {
            "selector": {"matchLabels": dict(ds.selector)},
            "template": _template_encode(ds.template, ds.node_selector),
        },
        "status": {
            "desiredNumberScheduled": ds.desired,
            "numberReady": ds.ready,
        },
    }


def _daemonset_decode(doc: Dict[str, Any]) -> DaemonSet:
    spec = doc.get("spec") or {}
    status = doc.get("status") or {}
    tmpl, node_selector = _template_decode(spec.get("template") or {})
    return DaemonSet(
        meta=_meta_decode(doc.get("metadata") or {}),
        selector=dict((spec.get("selector") or {}).get("matchLabels") or {}),
        node_selector=node_selector,
        template=tmpl,
        desired=status.get("desiredNumberScheduled", 0),
        ready=status.get("numberReady", 0),
    )


def _deployment_encode(d: Deployment) -> Dict[str, Any]:
    return {
        "spec": {
            "replicas": d.replicas,
            "selector": {"matchLabels": dict(d.selector)},
            "template": _template_encode(d.template, {}),
        },
        "status": {"readyReplicas": d.ready},
    }


def _deployment_decode(doc: Dict[str, Any]) -> Deployment:
    spec = doc.get("spec") or {}
    status = doc.get("status") or {}
    tmpl, _ = _template_decode(spec.get("template") or {})
    return Deployment(
        meta=_meta_decode(doc.get("metadata") or {}),
        replicas=spec.get("replicas", 1),
        selector=dict((spec.get("selector") or {}).get("matchLabels") or {}),
        template=tmpl,
        ready=status.get("readyReplicas", 0),
    )


# -- DRA: requests / configs / allocations ----------------------------------


def _requests_encode(requests: List[DeviceRequest],
                     version: str = "v1") -> List[Dict[str, Any]]:
    """v1 nests the one-of under `exactly:` (reference quickstart
    demo/specs/quickstart/v1/gpu-test1.yaml:10-21); v1beta1 is flat."""
    out = []
    for r in requests:
        inner: Dict[str, Any] = {
            "deviceClassName": r.device_class_name,
            "allocationMode": r.allocation_mode,
        }
        if r.allocation_mode == "ExactCount":
            inner["count"] = r.count
        if r.selectors:
            # Legacy attr=value strings are a sim-only convenience with no
            # wire representation; dropping them silently would let a
            # round-tripped claim over-match (the constraint vanishes).
            raise ValueError(
                f"request {r.name!r} carries legacy attr=value selectors "
                f"{r.selectors}; real-API claims must use CEL "
                f"(cel_selectors / the {{cel: {{expression}}}} manifest form)"
            )
        if r.cel_selectors:
            inner["selectors"] = [{"cel": {"expression": s}} for s in r.cel_selectors]
        if version == "v1beta1":
            out.append({"name": r.name, **inner})
        else:
            out.append({"name": r.name, "exactly": inner})
    return out


def _requests_decode(docs: List[Dict[str, Any]]) -> List[DeviceRequest]:
    out = []
    for d in docs or []:
        # v1beta1 wraps exactly-one-of in "exactly"; flat form also accepted.
        inner = d.get("exactly") or d
        out.append(DeviceRequest(
            name=d.get("name", ""),
            device_class_name=inner.get("deviceClassName", ""),
            allocation_mode=inner.get("allocationMode", "ExactCount"),
            count=inner.get("count", 1),
            cel_selectors=[
                expr for s in inner.get("selectors") or []
                if (expr := (s.get("cel") or {}).get("expression", ""))
            ],
        ))
    return out


def _configs_encode(configs: List[DeviceClaimConfig]) -> List[Dict[str, Any]]:
    out = []
    for c in configs:
        doc: Dict[str, Any] = {}
        if c.requests:
            doc["requests"] = list(c.requests)
        if c.opaque:
            doc["opaque"] = {
                "driver": c.opaque.driver,
                "parameters": dict(c.opaque.parameters),
            }
        out.append(doc)
    return out


def _configs_decode(docs: List[Dict[str, Any]], source: str) -> List[DeviceClaimConfig]:
    out = []
    for d in docs or []:
        op = d.get("opaque")
        out.append(DeviceClaimConfig(
            requests=list(d.get("requests") or []),
            opaque=OpaqueDeviceConfig(
                driver=op.get("driver", ""),
                parameters=dict(op.get("parameters") or {}),
            ) if op else None,
            source=source,
        ))
    return out


def _claim_encode(rc: ResourceClaim, version: str = "v1") -> Dict[str, Any]:
    spec: Dict[str, Any] = {
        "devices": {
            "requests": _requests_encode(rc.requests, version),
            "config": _configs_encode(rc.config),
        }
    }
    if rc.priority_tier:
        spec["priorityTier"] = rc.priority_tier
    status: Dict[str, Any] = {}
    if rc.allocation:
        alloc: Dict[str, Any] = {
            "devices": {
                "results": [
                    {
                        "request": r.request,
                        "driver": r.driver,
                        "pool": r.pool,
                        "device": r.device,
                    }
                    for r in rc.allocation.devices
                ]
            }
        }
        if rc.allocation.node_name:
            alloc["nodeSelector"] = {
                "nodeSelectorTerms": [{
                    "matchFields": [{
                        "key": "metadata.name",
                        "operator": "In",
                        "values": [rc.allocation.node_name],
                    }]
                }]
            }
        status["allocation"] = alloc
    if rc.reserved_for:
        status["reservedFor"] = [
            {"resource": "pods", "name": c.name, "uid": c.uid}
            for c in rc.reserved_for
        ]
    if rc.conditions:
        status["conditions"] = _conditions_encode(rc.conditions)
    if rc.utilization is not None:
        status["utilizationSummary"] = _utilization_encode(rc.utilization)
    if rc.observed_footprint is not None:
        status["observedFootprint"] = _footprint_encode(rc.observed_footprint)
    return {"spec": spec, "status": status}


# -- utilization summary ------------------------------------------------------
#
# Shared by ResourceClaim.status and ComputeDomain.status: the telemetry
# aggregator's quantized window roll-up. Wire shape mirrors the dataclass
# field-for-field so the wire-drift checker audits both directions.


def _utilization_encode(u: UtilizationSummary) -> Dict[str, Any]:
    return {
        "windowSeconds": u.window_seconds,
        "samples": u.samples,
        "dutyCycleP95": u.duty_cycle_p95,
        "hbmUsedP95Bytes": u.hbm_used_p95_bytes,
        "hbmTotalBytes": u.hbm_total_bytes,
        "iciUtilizationP95": u.ici_utilization_p95,
        "updatedAt": u.updated_at,
    }


def _utilization_decode(doc: Optional[Dict[str, Any]]) -> Optional[UtilizationSummary]:
    if not doc:
        return None
    return UtilizationSummary(
        window_seconds=float(doc.get("windowSeconds", 0.0)),
        samples=int(doc.get("samples", 0)),
        duty_cycle_p95=float(doc.get("dutyCycleP95", 0.0)),
        hbm_used_p95_bytes=int(doc.get("hbmUsedP95Bytes", 0)),
        hbm_total_bytes=int(doc.get("hbmTotalBytes", 0)),
        ici_utilization_p95=float(doc.get("iciUtilizationP95", 0.0)),
        updated_at=float(doc.get("updatedAt", 0.0)),
    )


def _footprint_encode(f: ObservedFootprint) -> Dict[str, Any]:
    return {
        "phaseSeconds": {k: f.phase_seconds[k]
                         for k in sorted(f.phase_seconds)},
        "peakHbmBytes": f.peak_hbm_bytes,
        "dutyP95": f.duty_p95,
        "updatedAt": f.updated_at,
    }


def _footprint_decode(doc: Optional[Dict[str, Any]]) -> Optional[ObservedFootprint]:
    if not doc:
        return None
    return ObservedFootprint(
        phase_seconds={str(k): float(v)
                       for k, v in (doc.get("phaseSeconds") or {}).items()},
        peak_hbm_bytes=int(doc.get("peakHbmBytes", 0)),
        duty_p95=float(doc.get("dutyP95", 0.0)),
        updated_at=float(doc.get("updatedAt", 0.0)),
    )


def _alloc_node_name(alloc_doc: Dict[str, Any]) -> str:
    for term in (alloc_doc.get("nodeSelector") or {}).get("nodeSelectorTerms") or []:
        for f in term.get("matchFields") or []:
            if f.get("key") == "metadata.name" and f.get("values"):
                return f["values"][0]
    return ""


def _claim_decode(doc: Dict[str, Any]) -> ResourceClaim:
    spec = doc.get("spec") or {}
    devices = spec.get("devices") or {}
    status = doc.get("status") or {}
    allocation = None
    if "allocation" in status:
        alloc_doc = status["allocation"] or {}
        allocation = AllocationResult(
            devices=[
                DeviceRequestAllocationResult(
                    request=r.get("request", ""),
                    driver=r.get("driver", ""),
                    pool=r.get("pool", ""),
                    device=r.get("device", ""),
                )
                for r in (alloc_doc.get("devices") or {}).get("results") or []
            ],
            node_name=_alloc_node_name(alloc_doc),
        )
    return ResourceClaim(
        meta=_meta_decode(doc.get("metadata") or {}),
        requests=_requests_decode(devices.get("requests") or []),
        config=_configs_decode(devices.get("config") or [], source="claim"),
        priority_tier=int(spec.get("priorityTier", 0)),
        allocation=allocation,
        reserved_for=[
            ResourceClaimConsumer(
                kind="Pod", name=c.get("name", ""), uid=c.get("uid", "")
            )
            for c in status.get("reservedFor") or []
        ],
        conditions=_conditions_decode(status.get("conditions") or []),
        utilization=_utilization_decode(status.get("utilizationSummary")),
        observed_footprint=_footprint_decode(status.get("observedFootprint")),
    )


def _claim_template_encode(t: ResourceClaimTemplate,
                           version: str = "v1") -> Dict[str, Any]:
    tmpl_meta: Dict[str, Any] = {}
    if t.spec_meta_labels:
        tmpl_meta["labels"] = dict(t.spec_meta_labels)
    if t.spec_meta_annotations:
        tmpl_meta["annotations"] = dict(t.spec_meta_annotations)
    return {
        "spec": {
            "metadata": tmpl_meta,
            "spec": {
                "devices": {
                    "requests": _requests_encode(t.requests, version),
                    "config": _configs_encode(t.config),
                }
            },
        }
    }


def _claim_template_decode(doc: Dict[str, Any]) -> ResourceClaimTemplate:
    spec = doc.get("spec") or {}
    tmpl_meta = spec.get("metadata") or {}
    inner = (spec.get("spec") or {}).get("devices") or {}
    return ResourceClaimTemplate(
        meta=_meta_decode(doc.get("metadata") or {}),
        spec_meta_labels=dict(tmpl_meta.get("labels") or {}),
        spec_meta_annotations=dict(tmpl_meta.get("annotations") or {}),
        requests=_requests_decode(inner.get("requests") or []),
        config=_configs_decode(inner.get("config") or [], source="claim"),
    )


# -- ResourceSlice ----------------------------------------------------------


def _attr_encode(v: Any) -> Dict[str, Any]:
    if isinstance(v, bool):
        return {"bool": v}
    if isinstance(v, int):
        return {"int": v}
    return {"string": str(v)}


def _attr_decode(doc: Dict[str, Any]) -> Any:
    if "bool" in doc:
        return bool(doc["bool"])
    if "int" in doc:
        return int(doc["int"])
    if "version" in doc:
        return doc["version"]
    return doc.get("string", "")


def _counters_encode(counters: Dict[str, Counter]) -> Dict[str, Any]:
    return {k: {"value": str(c.value)} for k, c in counters.items()}


def _counters_decode(doc: Dict[str, Any]) -> Dict[str, Counter]:
    out = {}
    for k, v in (doc or {}).items():
        try:
            out[k] = Counter(value=int(v.get("value", 0)))
        except (TypeError, ValueError, AttributeError):
            out[k] = Counter(value=0)
    return out


def _slice_encode(rs: ResourceSlice, version: str = "v1") -> Dict[str, Any]:
    devices = []
    for d in rs.devices:
        basic: Dict[str, Any] = {
            "attributes": {k: _attr_encode(v) for k, v in d.attributes.items()},
        }
        if d.capacity:
            basic["capacity"] = {k: {"value": str(v)} for k, v in d.capacity.items()}
        if d.taints:
            basic["taints"] = [
                {"key": t.key, "value": t.value, "effect": t.effect}
                for t in d.taints
            ]
        if d.consumes_counters:
            basic["consumesCounters"] = [
                {
                    "counterSet": cc.counter_set,
                    "counters": _counters_encode(cc.counters),
                }
                for cc in d.consumes_counters
            ]
        # v1 flattened the Device one-of; v1beta1 wraps it in "basic".
        if version == "v1beta1":
            devices.append({"name": d.name, "basic": basic})
        else:
            devices.append({"name": d.name, **basic})
    spec: Dict[str, Any] = {
        "driver": rs.driver,
        "pool": {
            "name": rs.pool.name,
            "generation": rs.pool.generation,
            "resourceSliceCount": rs.pool.resource_slice_count,
        },
        "devices": devices,
    }
    if rs.node_name:
        spec["nodeName"] = rs.node_name
    if rs.shared_counters:
        spec["sharedCounters"] = [
            {"name": cs.name, "counters": _counters_encode(cs.counters)}
            for cs in rs.shared_counters
        ]
    return {"spec": spec}


def _slice_decode(doc: Dict[str, Any]) -> ResourceSlice:
    spec = doc.get("spec") or {}
    pool = spec.get("pool") or {}
    devices = []
    for d in spec.get("devices") or []:
        basic = d.get("basic") or d  # v1 dropped the "basic" wrapper
        devices.append(Device(
            name=d.get("name", ""),
            attributes={
                k: _attr_decode(v) for k, v in (basic.get("attributes") or {}).items()
            },
            capacity={
                k: v.get("value", "") for k, v in (basic.get("capacity") or {}).items()
            },
            taints=[
                DeviceTaint(key=t.get("key", ""), value=t.get("value", ""),
                            effect=t.get("effect", "NoSchedule"))
                for t in basic.get("taints") or []
            ],
            consumes_counters=[
                DeviceCounterConsumption(
                    counter_set=cc.get("counterSet", ""),
                    counters=_counters_decode(cc.get("counters")),
                )
                for cc in basic.get("consumesCounters") or []
            ],
        ))
    return ResourceSlice(
        meta=_meta_decode(doc.get("metadata") or {}),
        driver=spec.get("driver", ""),
        node_name=spec.get("nodeName", ""),
        pool=ResourcePool(
            name=pool.get("name", ""),
            generation=pool.get("generation", 0),
            resource_slice_count=pool.get("resourceSliceCount", 1),
        ),
        devices=devices,
        shared_counters=[
            CounterSet(name=cs.get("name", ""),
                       counters=_counters_decode(cs.get("counters")))
            for cs in spec.get("sharedCounters") or []
        ],
    )


# -- DeviceClass ------------------------------------------------------------

_CEL_DRIVER_RE = re.compile(r'''device\.driver\s*==\s*['"]([^'"]+)['"]''')


def _deviceclass_encode(dc: DeviceClass) -> Dict[str, Any]:
    spec: Dict[str, Any] = {}
    if dc.cel_selectors:
        # Raw expressions round-trip verbatim (the chart's own strings).
        # The driver must survive the trip even when no expression names
        # it — the allocator's per-driver slice lookup depends on it.
        selectors = list(dc.cel_selectors)
        if dc.driver and not any("device.driver" in e for e in selectors):
            selectors.insert(0, f'device.driver == "{dc.driver}"')
        spec["selectors"] = [{"cel": {"expression": e}} for e in selectors]
    else:
        exprs = []
        if dc.driver:
            exprs.append(f'device.driver == "{dc.driver}"')
        for k, v in dc.match_attributes.items():
            if isinstance(v, bool):
                lit = "true" if v else "false"
            elif isinstance(v, int):
                lit = str(v)
            else:
                lit = f'"{v}"'
            exprs.append(f'device.attributes["{k}"] == {lit}')
        if exprs:
            spec["selectors"] = [{"cel": {"expression": " && ".join(exprs)}}]
    if dc.config:
        spec["config"] = _configs_encode(dc.config)
    return {"spec": spec}


def _deviceclass_decode(doc: Dict[str, Any]) -> DeviceClass:
    spec = doc.get("spec") or {}
    driver = ""
    cel_selectors: List[str] = []
    for sel in spec.get("selectors") or []:
        expr = (sel.get("cel") or {}).get("expression", "")
        if expr:
            # Keep the raw expression (celmini evaluates it); the driver is
            # still extracted for the allocator's per-driver slice lookup.
            cel_selectors.append(expr)
        m = _CEL_DRIVER_RE.search(expr)
        if m:
            driver = m.group(1)
    return DeviceClass(
        meta=_meta_decode(doc.get("metadata") or {}),
        driver=driver,
        cel_selectors=cel_selectors,
        config=_configs_decode(spec.get("config") or [], source="class"),
    )


# -- ComputeDomain CRDs ------------------------------------------------------


def _meshbundle_encode(mb: MeshBundle) -> Dict[str, Any]:
    """status.meshBundle — the Placement→JAX mesh compiler output. The
    wire shape matches MeshBundle.to_json_obj (the TPU_DRA_MESH_BUNDLE
    env uses the same keys), spelled out here so the wire-drift checker
    sees every field cross the boundary."""
    return {
        "revision": mb.revision,
        "sliceTopology": mb.slice_topology,
        "hostTopology": mb.host_topology,
        "processBounds": mb.process_bounds,
        "axisNames": list(mb.axis_names),
        "axisSizes": list(mb.axis_sizes),
        "deviceOrder": [
            {"node": d.node, "worker": d.worker, "chip": d.chip,
             "coord": list(d.coord)}
            for d in mb.device_order
        ],
        "partitionRules": [list(r) for r in mb.partition_rules],
        "hopScore": mb.hop_score,
        "naiveHopScore": mb.naive_hop_score,
        "brokenLinks": [list(b) for b in mb.broken_links],
    }


def _meshbundle_decode(doc: Dict[str, Any]) -> MeshBundle:
    return MeshBundle(
        revision=int(doc.get("revision", 0)),
        slice_topology=doc.get("sliceTopology", ""),
        host_topology=doc.get("hostTopology", ""),
        process_bounds=doc.get("processBounds", ""),
        axis_names=[str(a) for a in doc.get("axisNames") or []],
        axis_sizes=[int(s) for s in doc.get("axisSizes") or []],
        device_order=[
            MeshDevice(node=d.get("node", ""),
                       worker=int(d.get("worker", 0)),
                       chip=int(d.get("chip", 0)),
                       coord=tuple(int(c) for c in d.get("coord") or ()))
            for d in doc.get("deviceOrder") or []
        ],
        partition_rules=[list(r) for r in doc.get("partitionRules") or []],
        hop_score=int(doc.get("hopScore", 0)),
        naive_hop_score=int(doc.get("naiveHopScore", 0)),
        broken_links=[list(b) for b in doc.get("brokenLinks") or []],
    )


def _placement_encode(p) -> Dict[str, Any]:
    """ComputeDomainPlacement wire doc — shared by status.placement and
    the resize record's prior/new placement snapshots."""
    return {
        "iciDomain": p.ici_domain,
        "blockOrigin": p.block_origin,
        "blockShape": p.block_shape,
        "nodes": list(p.nodes),
    }


def _placement_decode(doc) -> Optional[ComputeDomainPlacement]:
    if not doc:
        return None
    return ComputeDomainPlacement(
        ici_domain=doc.get("iciDomain", ""),
        block_origin=doc.get("blockOrigin", ""),
        block_shape=doc.get("blockShape", ""),
        nodes=list(doc.get("nodes") or []),
    )


def _resize_encode(r: ComputeDomainResize) -> Dict[str, Any]:
    doc: Dict[str, Any] = {
        "phase": r.phase,
        "trigger": r.trigger,
        "targetNodes": r.target_nodes,
        "attempts": r.attempts,
        "startedAt": r.started_at,
        "priorDesired": r.prior_desired,
    }
    if r.lost_nodes:
        doc["lostNodes"] = list(r.lost_nodes)
    if r.new_placement is not None:
        doc["newPlacement"] = _placement_encode(r.new_placement)
    if r.prior_placement is not None:
        doc["priorPlacement"] = _placement_encode(r.prior_placement)
    return doc


def _resize_decode(doc) -> Optional[ComputeDomainResize]:
    if not doc:
        return None
    return ComputeDomainResize(
        phase=doc.get("phase", ""),
        trigger=doc.get("trigger", ""),
        target_nodes=int(doc.get("targetNodes", 0)),
        lost_nodes=list(doc.get("lostNodes") or []),
        new_placement=_placement_decode(doc.get("newPlacement")),
        prior_placement=_placement_decode(doc.get("priorPlacement")),
        prior_desired=int(doc.get("priorDesired", 0)),
        attempts=int(doc.get("attempts", 0)),
        started_at=float(doc.get("startedAt", 0.0)),
    )


def _computedomain_encode(cd: ComputeDomain) -> Dict[str, Any]:
    spec: Dict[str, Any] = {"numNodes": cd.spec.num_nodes}
    if cd.spec.topology:
        spec["topology"] = cd.spec.topology
    if cd.spec.channel.resource_claim_template_name:
        spec["channel"] = {
            "resourceClaimTemplate": {
                "name": cd.spec.channel.resource_claim_template_name
            }
        }
    status: Dict[str, Any] = {"status": cd.status.status}
    if cd.status.nodes:
        status["nodes"] = [
            {
                "name": n.name,
                "ipAddress": n.ip_address,
                "iciDomain": n.ici_domain,
                "workerId": n.worker_id,
                "status": n.status,
            }
            for n in cd.status.nodes
        ]
    if cd.status.placement is not None:
        status["placement"] = _placement_encode(cd.status.placement)
    if cd.status.epoch:
        status["epoch"] = cd.status.epoch
    if cd.status.desired_nodes:
        status["desiredNodes"] = cd.status.desired_nodes
    if cd.status.resize is not None:
        status["resize"] = _resize_encode(cd.status.resize)
    if cd.status.mesh_bundle is not None:
        status["meshBundle"] = _meshbundle_encode(cd.status.mesh_bundle)
    if cd.status.utilization is not None:
        status["utilizationSummary"] = _utilization_encode(cd.status.utilization)
    if cd.status.conditions:
        status["conditions"] = _conditions_encode(cd.status.conditions)
    return {"spec": spec, "status": status}


def _computedomain_decode(doc: Dict[str, Any]) -> ComputeDomain:
    spec = doc.get("spec") or {}
    status = doc.get("status") or {}
    chan = ((spec.get("channel") or {}).get("resourceClaimTemplate") or {})
    return ComputeDomain(
        meta=_meta_decode(doc.get("metadata") or {}),
        spec=ComputeDomainSpec(
            num_nodes=spec.get("numNodes", 0),
            topology=spec.get("topology", ""),
            channel=ComputeDomainChannelSpec(
                resource_claim_template_name=chan.get("name", "")
            ),
        ),
        status=ComputeDomainStatus(
            status=status.get("status", "NotReady"),
            nodes=[
                ComputeDomainNode(
                    name=n.get("name", ""),
                    ip_address=n.get("ipAddress", ""),
                    ici_domain=n.get("iciDomain", ""),
                    worker_id=n.get("workerId", -1),
                    status=n.get("status", "NotReady"),
                )
                for n in status.get("nodes") or []
            ],
            placement=_placement_decode(status.get("placement")),
            epoch=int(status.get("epoch", 0)),
            desired_nodes=int(status.get("desiredNodes", 0)),
            resize=_resize_decode(status.get("resize")),
            mesh_bundle=(
                _meshbundle_decode(status["meshBundle"])
                if status.get("meshBundle") else None
            ),
            utilization=_utilization_decode(status.get("utilizationSummary")),
            conditions=_conditions_decode(status.get("conditions") or []),
        ),
    )


def _servinggroup_encode(sg: ServingGroup) -> Dict[str, Any]:
    """resource.tpu.google.com/v1beta1 ServingGroup. Spelled out
    field-for-field so the wire-drift checker audits the whole object
    graph on both sides."""
    s = sg.spec
    spec: Dict[str, Any] = {
        "replicas": s.replicas,
        "profile": s.profile,
        "template": {
            "image": s.template.image,
            "env": dict(s.template.env),
        },
        "slo": {
            "latencyP95Ms": s.slo.latency_p95_ms,
            "dutyBound": s.slo.duty_bound,
        },
        "traffic": {
            "trace": s.traffic.trace,
            "peakQps": s.traffic.peak_qps,
            "qpsPerChip": s.traffic.qps_per_chip,
            "baseLatencyMs": s.traffic.base_latency_ms,
        },
        "policy": {
            "minReplicas": s.policy.min_replicas,
            "maxReplicas": s.policy.max_replicas,
            "targetDuty": s.policy.target_duty,
            "scaleUpCooldownSeconds": s.policy.scale_up_cooldown_s,
            "scaleDownCooldownSeconds": s.policy.scale_down_cooldown_s,
            "stabilizationWindowSeconds": s.policy.stabilization_window_s,
            "downTierDuty": s.policy.down_tier_duty,
            "tierCooldownSeconds": s.policy.tier_cooldown_s,
        },
    }
    if s.tiers:
        spec["tiers"] = list(s.tiers)
    st = sg.status
    status: Dict[str, Any] = {
        "desiredReplicas": st.desired_replicas,
        "readyReplicas": st.ready_replicas,
        "profile": st.profile,
    }
    if st.last_scale_up:
        status["lastScaleUp"] = st.last_scale_up
    if st.last_scale_down:
        status["lastScaleDown"] = st.last_scale_down
    if st.last_retier:
        status["lastRetier"] = st.last_retier
    if st.traffic is not None:
        t = st.traffic
        status["traffic"] = {
            "qps": t.qps,
            "latencyMs": t.latency_ms,
            "latencyRatio": t.latency_ratio,
            "utilization": t.utilization,
            "readyReplicas": t.ready_replicas,
            "updatedAt": t.updated_at,
        }
    if st.conditions:
        status["conditions"] = _conditions_encode(st.conditions)
    return {"spec": spec, "status": status}


def _servinggroup_decode(doc: Dict[str, Any]) -> ServingGroup:
    spec = doc.get("spec") or {}
    status = doc.get("status") or {}
    tmpl = spec.get("template") or {}
    slo = spec.get("slo") or {}
    traffic = spec.get("traffic") or {}
    policy = spec.get("policy") or {}
    tdoc = status.get("traffic")
    return ServingGroup(
        meta=_meta_decode(doc.get("metadata") or {}),
        spec=ServingGroupSpec(
            replicas=int(spec.get("replicas", 1)),
            profile=spec.get("profile", ""),
            tiers=[str(t) for t in spec.get("tiers") or []],
            template=ServingReplicaTemplate(
                image=tmpl.get("image", "serving"),
                env={k: str(v) for k, v in (tmpl.get("env") or {}).items()},
            ),
            slo=ServingSLO(
                latency_p95_ms=float(slo.get("latencyP95Ms", 50.0)),
                duty_bound=float(slo.get("dutyBound", 0.95)),
            ),
            traffic=ServingTraffic(
                trace=traffic.get("trace", ""),
                peak_qps=float(traffic.get("peakQps", 100.0)),
                qps_per_chip=float(traffic.get("qpsPerChip", 10.0)),
                base_latency_ms=float(traffic.get("baseLatencyMs", 10.0)),
            ),
            policy=ServingScalingPolicy(
                min_replicas=int(policy.get("minReplicas", 1)),
                max_replicas=int(policy.get("maxReplicas", 64)),
                target_duty=float(policy.get("targetDuty", 0.6)),
                scale_up_cooldown_s=float(
                    policy.get("scaleUpCooldownSeconds", 15.0)),
                scale_down_cooldown_s=float(
                    policy.get("scaleDownCooldownSeconds", 60.0)),
                stabilization_window_s=float(
                    policy.get("stabilizationWindowSeconds", 120.0)),
                down_tier_duty=float(policy.get("downTierDuty", 0.25)),
                tier_cooldown_s=float(policy.get("tierCooldownSeconds", 300.0)),
            ),
        ),
        status=ServingGroupStatus(
            desired_replicas=int(status.get("desiredReplicas", 0)),
            ready_replicas=int(status.get("readyReplicas", 0)),
            profile=status.get("profile", ""),
            last_scale_up=float(status.get("lastScaleUp", 0.0)),
            last_scale_down=float(status.get("lastScaleDown", 0.0)),
            last_retier=float(status.get("lastRetier", 0.0)),
            traffic=(
                ServingTrafficStatus(
                    qps=float(tdoc.get("qps", 0.0)),
                    latency_ms=float(tdoc.get("latencyMs", 0.0)),
                    latency_ratio=float(tdoc.get("latencyRatio", 0.0)),
                    utilization=float(tdoc.get("utilization", 0.0)),
                    ready_replicas=int(tdoc.get("readyReplicas", 0)),
                    updated_at=float(tdoc.get("updatedAt", 0.0)),
                )
                if tdoc else None
            ),
            conditions=_conditions_decode(status.get("conditions") or []),
        ),
    )


def _tenantquota_encode(tq: TenantQuota) -> Dict[str, Any]:
    """resource.tpu.google.com/v1beta1 TenantQuota. Spelled out
    field-for-field so the wire-drift checker audits both sides."""
    s = tq.spec
    spec: Dict[str, Any] = {
        "weight": s.weight,
        "chipQuota": s.chip_quota,
        "priorityFloor": s.priority_floor,
    }
    st = tq.status
    status: Dict[str, Any] = {
        "chipsUsed": st.chips_used,
        "podsPending": st.pods_pending,
        "virtualTime": st.virtual_time,
    }
    if st.updated_at:
        status["updatedAt"] = st.updated_at
    return {"spec": spec, "status": status}


def _tenantquota_decode(doc: Dict[str, Any]) -> TenantQuota:
    spec = doc.get("spec") or {}
    status = doc.get("status") or {}
    return TenantQuota(
        meta=_meta_decode(doc.get("metadata") or {}),
        spec=TenantQuotaSpec(
            weight=float(spec.get("weight", 1.0)),
            chip_quota=int(spec.get("chipQuota", 0)),
            priority_floor=int(spec.get("priorityFloor", 0)),
        ),
        status=TenantQuotaStatus(
            chips_used=int(status.get("chipsUsed", 0)),
            pods_pending=int(status.get("podsPending", 0)),
            virtual_time=float(status.get("virtualTime", 0.0)),
            updated_at=float(status.get("updatedAt", 0.0)),
        ),
    )


def _clique_encode(cl: ComputeDomainClique) -> Dict[str, Any]:
    return {
        "domainUid": cl.domain_uid,
        "iciDomain": cl.ici_domain,
        "nodes": [
            {
                "nodeName": n.node_name,
                "ipAddress": n.ip_address,
                "dnsName": n.dns_name,
                "index": n.index,
                "ready": n.ready,
            }
            for n in cl.nodes
        ],
        "released": {k: v for k, v in sorted(cl.released.items())},
    }


def _clique_decode(doc: Dict[str, Any]) -> ComputeDomainClique:
    return ComputeDomainClique(
        meta=_meta_decode(doc.get("metadata") or {}),
        domain_uid=doc.get("domainUid", ""),
        ici_domain=doc.get("iciDomain", ""),
        nodes=[
            ComputeDomainDaemonInfo(
                node_name=n.get("nodeName", ""),
                ip_address=n.get("ipAddress", ""),
                dns_name=n.get("dnsName", ""),
                index=n.get("index", -1),
                ready=bool(n.get("ready", False)),
            )
            for n in doc.get("nodes") or []
        ],
        released={str(k): int(v)
                  for k, v in (doc.get("released") or {}).items()},
    )


# -- Lease ------------------------------------------------------------------


def _lease_encode(lease: Lease) -> Dict[str, Any]:
    spec: Dict[str, Any] = {
        "leaseDurationSeconds": int(lease.lease_duration_s),
    }
    if lease.holder:
        spec["holderIdentity"] = lease.holder
    if lease.acquired_at:
        spec["acquireTime"] = _ts_encode_micro(lease.acquired_at)
    if lease.renewed_at:
        spec["renewTime"] = _ts_encode_micro(lease.renewed_at)
    return {"spec": spec}


def _lease_decode(doc: Dict[str, Any]) -> Lease:
    spec = doc.get("spec") or {}
    return Lease(
        meta=_meta_decode(doc.get("metadata") or {}),
        holder=spec.get("holderIdentity", ""),
        acquired_at=_ts_decode(spec.get("acquireTime")),
        renewed_at=_ts_decode(spec.get("renewTime")),
        lease_duration_s=float(spec.get("leaseDurationSeconds", 15)),
    )


# -- ValidatingWebhookConfiguration ------------------------------------------


def _vwc_encode(vwc: ValidatingWebhookConfiguration) -> Dict[str, Any]:
    hooks = []
    for wh in vwc.webhooks:
        cc: Dict[str, Any] = {}
        if wh.client_config.url:
            cc["url"] = wh.client_config.url
        if wh.client_config.service_name:
            cc["service"] = {
                "name": wh.client_config.service_name,
                "namespace": wh.client_config.service_namespace,
                "path": wh.client_config.service_path,
            }
        if wh.client_config.ca_bundle:
            cc["caBundle"] = wh.client_config.ca_bundle
        hooks.append({
            "name": wh.name,
            "clientConfig": cc,
            "rules": [{
                "apiGroups": r.api_groups,
                "apiVersions": r.api_versions,
                "operations": r.operations,
                "resources": r.resources,
            } for r in wh.rules],
            "failurePolicy": wh.failure_policy,
            "sideEffects": wh.side_effects,
            "admissionReviewVersions": wh.admission_review_versions,
        })
    return {"webhooks": hooks}


def _vwc_decode(doc: Dict[str, Any]) -> ValidatingWebhookConfiguration:
    hooks = []
    for wh in doc.get("webhooks") or []:
        cc = wh.get("clientConfig") or {}
        svc = cc.get("service") or {}
        hooks.append(RegisteredWebhook(
            name=wh.get("name", ""),
            client_config=WebhookClientConfig(
                url=cc.get("url", ""),
                service_name=svc.get("name", ""),
                service_namespace=svc.get("namespace", ""),
                service_path=svc.get("path", ""),
                ca_bundle=cc.get("caBundle", ""),
            ),
            rules=[WebhookRule(
                api_groups=r.get("apiGroups") or [],
                api_versions=r.get("apiVersions") or [],
                operations=r.get("operations") or [],
                resources=r.get("resources") or [],
            ) for r in wh.get("rules") or []],
            failure_policy=wh.get("failurePolicy", "Fail"),
            side_effects=wh.get("sideEffects", "None"),
            admission_review_versions=wh.get("admissionReviewVersions") or ["v1"],
        ))
    return ValidatingWebhookConfiguration(
        meta=_meta_decode(doc.get("metadata") or {}), webhooks=hooks
    )


# -- top level ---------------------------------------------------------------

_ENCODERS = {
    "Pod": _pod_encode,
    "Node": _node_encode,
    "DaemonSet": _daemonset_encode,
    "Deployment": _deployment_encode,
    "ResourceClaim": _claim_encode,
    "ResourceClaimTemplate": _claim_template_encode,
    "ResourceSlice": _slice_encode,
    "DeviceClass": _deviceclass_encode,
    "ComputeDomain": _computedomain_encode,
    "ComputeDomainClique": _clique_encode,
    "ServingGroup": _servinggroup_encode,
    "TenantQuota": _tenantquota_encode,
    "Lease": _lease_encode,
    "ValidatingWebhookConfiguration": _vwc_encode,
}

_DECODERS = {
    "Pod": _pod_decode,
    "Node": _node_decode,
    "DaemonSet": _daemonset_decode,
    "Deployment": _deployment_decode,
    "ResourceClaim": _claim_decode,
    "ResourceClaimTemplate": _claim_template_decode,
    "ResourceSlice": _slice_decode,
    "DeviceClass": _deviceclass_decode,
    "ComputeDomain": _computedomain_decode,
    "ComputeDomainClique": _clique_decode,
    "ServingGroup": _servinggroup_decode,
    "TenantQuota": _tenantquota_decode,
    "Lease": _lease_decode,
    "ValidatingWebhookConfiguration": _vwc_decode,
}


# Kinds whose wire shape differs between served versions; their encoders
# take (obj, version).
_VERSIONED_KINDS = {"ResourceClaim", "ResourceClaimTemplate", "ResourceSlice"}


def to_k8s_wire(obj: K8sObject, api_version: str = "") -> Dict[str, Any]:
    """Encode an internal object as real Kubernetes JSON. `api_version` is
    a bare version ('v1beta1') selecting among the kind's served versions;
    default is the preferred (RESOURCE_MAP) version."""
    if obj.kind not in _ENCODERS:
        raise ValueError(f"kind {obj.kind!r} has no k8s wire mapping")
    preferred, _, _ = RESOURCE_MAP[obj.kind]
    group, version = group_version_split(preferred)
    if api_version:
        if api_version not in served_versions(obj.kind):
            raise ValueError(
                f"{obj.kind} is not served at {api_version!r} "
                f"(served: {served_versions(obj.kind)})"
            )
        version = api_version
    full = f"{group}/{version}" if group else version
    doc = {"apiVersion": full, "kind": obj.kind,
           "metadata": _meta_encode(obj.meta)}
    if obj.kind in _VERSIONED_KINDS:
        doc.update(_ENCODERS[obj.kind](obj, version))
    else:
        doc.update(_ENCODERS[obj.kind](obj))
    return doc


def from_k8s_wire(doc: Dict[str, Any]) -> K8sObject:
    """Decode real Kubernetes JSON into the internal object model."""
    kind = doc.get("kind", "")
    if kind not in _DECODERS:
        raise ValueError(f"kind {kind!r} has no k8s wire mapping")
    return _DECODERS[kind](doc)
