"""Store persistence: append-only WAL + periodic snapshot compaction.

An 8192-node sim takes minutes of claim storm to populate; without
persistence a restart re-runs the storm. This module makes the APIServer
durable the way etcd is — a write-ahead log of every mutation plus
periodic full snapshots — scoped to what a deterministic sim needs:

- **Group-commit mode (default, ``fsync=False``).** Records ride the
  store's dispatch ring: built inside the shard lock (per-key order is
  write order) but appended to ONE ``wal.<epoch>.jsonl`` by the off-lock
  watch dispatcher, single-threaded by construction — WAL I/O never
  extends a shard's critical section.
- **Durable mode (``fsync=True``).** The write path appends and fsyncs
  its record *before the write returns*, under the owning shard's lock,
  into that shard's own ``wal-<shard>.<epoch>.jsonl``. Per-shard files
  are what make durability scale: fsync releases the GIL, so eight
  writer threads overlap eight fsyncs across shards, while the
  single-lock baseline serializes every flush behind one lock — the
  sharded-vs-baseline throughput gate in bench_scale measures exactly
  this. A kind lives in one shard, so per-key order is per-file order
  and replay never needs a global sort.
- **Snapshot watermark + epoch rotation.** Compaction dumps the whole
  store under the canonical ordered all-shard lock together with the
  dispatch-ring sequence at that instant, rotates every WAL file to a
  fresh epoch *under the same lock* (so every record in an old epoch is
  at or below the watermark), then serializes the snapshot OUTSIDE the
  locks, atomically renames it, and only then deletes the old epochs.
  A crash at any point leaves a readable (snapshot, wal*) pair: replay
  skips records at or below the snapshot's watermark, and PUT/DEL
  records are idempotent upserts keyed by (kind, ns, name).
- **Fingerprint-token fidelity.** Every record carries the post-write
  ``kind_fingerprint`` token and every object its stamped
  resourceVersion/uid/generation, so a restore reproduces not just the
  contents but the exact change-detection tokens — the sim's quiescence
  detection and the allocator's caches resume as if the process never
  died (pinned by the restore acceptance test).

Feature-gated in the sim behind ``StorePersistence``; the store itself is
persistence-agnostic (``attach_wal`` is the only coupling).
"""

from __future__ import annotations

import glob
import json
import logging
import os
import re
import threading
import time
from typing import Dict, List, Optional, Tuple

from k8s_dra_driver_tpu.k8s import serialize
from k8s_dra_driver_tpu.k8s.store import APIServer, DEFAULT_STORE_SHARDS

log = logging.getLogger(__name__)

SNAPSHOT_FILE = "snapshot.json"
FORMAT_VERSION = 1

_WAL_NAME = re.compile(r"^wal(?:-(\d+))?\.(\d+)\.jsonl$")


# Paths already warned about by discover_wal_files' zero-length skip —
# dedup only; never consulted for correctness.
_warned_empty: set = set()


def discover_wal_files(dirpath: str,
                       include_empty: bool = False) -> List[Tuple[int, int, str]]:
    """The ONE place WAL files are discovered on disk: every
    ``wal[-<shard>].<epoch>.jsonl`` under ``dirpath`` as
    ``(epoch, shard, path)`` tuples in NUMERIC (epoch, shard) order —
    lexicographic glob order would replay epoch 10 before epoch 9 at
    every digit-length boundary, resurrecting stale values when a crash
    mid-compaction left two epochs on disk. A key lives in one shard, so
    epoch-then-shard ordering is per-key write order. ``shard`` is -1
    for the shared group-commit file.

    Zero-length strays (a crash between open() and the first append, or
    a copy truncated mid-transfer) are skipped LOUDLY — they carry no
    records, but silently globbing them up has historically masked
    half-copied replication/restore directories. ``include_empty=True``
    (compaction's deletion sweep) returns them so old-epoch cleanup
    still removes the husks. The warning fires once per path — the
    replication tailer re-sweeps several times a second and a freshly
    rotated epoch is legitimately empty until its first append."""
    out: List[Tuple[int, int, str]] = []
    for path in glob.glob(os.path.join(dirpath, "wal*.jsonl")):
        m = _WAL_NAME.match(os.path.basename(path))
        if m is None:
            continue
        entry = (int(m.group(2)), int(m.group(1) or -1), path)
        try:
            empty = os.path.getsize(path) == 0
        except OSError:
            continue  # unlinked mid-scan (compaction racing discovery)
        if empty and not include_empty:
            if path not in _warned_empty:
                _warned_empty.add(path)
                log.warning("skipping zero-length WAL file %s (crash between "
                            "open and first append, or a truncated copy)",
                            path)
            continue
        out.append(entry)
    out.sort()
    return out


def _fsync(fd: int) -> None:
    """All WAL/snapshot durability funnels through this seam so the
    concurrency sanitizer (analysis/sanitizer) can interleave thread
    schedules at fsync boundaries; production behavior is os.fsync."""
    os.fsync(fd)

# Compact once this many WAL records accumulate past the last snapshot:
# bounds replay work to one snapshot decode + this many record applies.
DEFAULT_COMPACT_EVERY = 50_000


def _encode_rec(seq: int, op: str, key, obj, fp) -> Tuple[str, int]:
    """One WAL record line, serialize-once: the object body is
    ``serialize.wire_json`` — computed once per published snapshot and
    cached on the frozen instance, so the group-commit record, a durable
    re-log, and the next compaction all splice the SAME string instead of
    re-walking the object graph. Returns ``(line, shared_bytes)`` where
    ``shared_bytes`` counts body bytes served from the cache."""
    head = json.dumps({"seq": seq, "op": op, "key": list(key),
                       "fp": list(fp)}, separators=(",", ":"))
    if obj is None:
        return head[:-1] + ',"obj":null}', 0
    body, reused = serialize.wire_json(obj)
    return head[:-1] + ',"obj":' + body + "}", len(body) if reused else 0


class StoreWAL:
    """Append side of the log. Group-commit appends (``append``) are
    called only by the store's single active dispatcher; durable appends
    (``write_sync``) by write paths holding their shard's lock — one
    writer per file in both cases, so ``_mu`` only guards the epoch
    rotation and the shared record counter."""

    def __init__(self, dirpath: str, compact_every: int = DEFAULT_COMPACT_EVERY,
                 fsync: bool = False):
        os.makedirs(dirpath, exist_ok=True)
        self.dirpath = dirpath
        self.compact_every = compact_every
        self.fsync = fsync
        self._mu = threading.Lock()
        self._epoch = 1 + max(
            (epoch for epoch, _, _ in discover_wal_files(dirpath,
                                                         include_empty=True)),
            default=0)
        self._files: Dict[int, object] = {}  # tpulint: guarded-by=_mu
        self._since_snapshot = 0  # tpulint: guarded-by=_mu
        self._metrics = None

    @property
    def snapshot_path(self) -> str:
        return os.path.join(self.dirpath, SNAPSHOT_FILE)

    def _file(self, shard_idx: Optional[int]):
        """The current-epoch file handle for one shard (durable mode) or
        the shared group-commit file (``None``). The open() itself — a
        blocking filesystem call, first write of each epoch only — runs
        OUTSIDE ``_mu`` (sleep-under-lock) and installs under a
        double-check: if compaction rotated the epoch meanwhile, the
        stale handle is discarded and the lookup retries against the new
        epoch. Per-key callers are already serialized (group-commit by
        the single dispatcher, durable by the owning shard's lock), so
        the same key is never opened twice concurrently."""
        key = -1 if shard_idx is None else shard_idx
        with self._mu:
            f = self._files.get(key)
            if f is not None:
                return f
            epoch = self._epoch
        name = (f"wal.{epoch}.jsonl" if shard_idx is None
                else f"wal-{shard_idx}.{epoch}.jsonl")
        nf = open(os.path.join(self.dirpath, name), "a", encoding="utf-8")
        with self._mu:
            if self._epoch == epoch and key not in self._files:
                self._files[key] = nf
                return nf
        nf.close()
        return self._file(shard_idx)

    def attach_metrics(self, registry) -> None:
        from k8s_dra_driver_tpu.pkg.metrics import Counter

        self._metrics = {
            "records": registry.register(Counter(
                "tpu_dra_wal_records_total",
                "Mutation records appended to the store write-ahead log.")),
            "bytes": registry.register(Counter(
                "tpu_dra_wal_bytes_total",
                "Bytes appended to the store write-ahead log.")),
            "record_bytes": registry.register(Counter(
                "tpu_dra_wal_record_bytes_total",
                "WAL bytes by append path (bytes-per-record = this over "
                "tpu_dra_wal_records_total, per path).",
                label_names=("path",))),
            "snapshots": registry.register(Counter(
                "tpu_dra_wal_snapshots_total",
                "Snapshot compactions of the store write-ahead log.")),
            "shared_bytes": registry.register(Counter(
                "tpu_dra_store_snapshot_shared_bytes",
                "Encoded bytes served from the per-snapshot cached wire "
                "encoding (serialize-once) instead of re-serializing the "
                "object graph — WAL records, snapshot compaction.")),
        }

    def _note(self, records: int, nbytes: int, shared: int = 0,
              path: str = "group") -> None:
        with self._mu:
            self._since_snapshot += records
        if self._metrics is not None:
            self._metrics["records"].inc(by=float(records))
            self._metrics["bytes"].inc(by=float(nbytes))
            self._metrics["record_bytes"].inc(path, by=float(nbytes))
            if shared:
                self._metrics["shared_bytes"].inc(by=float(shared))

    # -- append paths --------------------------------------------------------

    def append(self, recs) -> None:
        """Group-commit: records drained from the dispatch ring by the
        single active dispatcher. Each rec is ``(seq, op, key, obj, fp)``
        with ``obj`` the published frozen snapshot itself (serialized
        once here, off every shard lock; the encoding is cached on the
        snapshot for compaction and any later re-log to reuse)."""
        lines, shared = [], 0
        for rec in recs:
            line, reused = _encode_rec(*rec)
            lines.append(line)
            shared += reused
        data = "\n".join(lines) + "\n"
        f = self._file(None)
        f.write(data)
        f.flush()
        if self.fsync:  # pragma: no cover — durable runs use write_sync
            _fsync(f.fileno())
        self._note(len(recs), len(data), shared, path="group")

    def write_sync(self, shard_idx: int, rec) -> None:
        """Durable append: serialize, write, and fsync ONE record into the
        owning shard's file before the caller's write returns. The caller
        holds that shard's lock, which is what serializes this file;
        fsync releases the GIL, so shards flush in parallel."""
        line, shared = _encode_rec(*rec)
        data = line + "\n"
        f = self._file(shard_idx)
        f.write(data)
        f.flush()
        _fsync(f.fileno())
        self._note(1, len(data), shared, path="durable")

    # -- compaction ----------------------------------------------------------

    def maybe_compact(self, store: APIServer) -> None:
        with self._mu:
            due = self._since_snapshot >= self.compact_every
        if due:
            self.compact(store)

    def compact(self, store: APIServer) -> None:
        """Snapshot + epoch rotation. Under the store's ordered all-shard
        lock (no write in flight): dump the state and rotate every WAL
        file to the next epoch — making "old epoch" synonymous with "at
        or below the snapshot watermark". The heavy serialization then
        happens outside the locks; the snapshot lands via atomic rename
        and only after that are the old epochs deleted."""
        with store._locked_all():
            state = store.dump_state()
            with self._mu:
                for f in self._files.values():
                    f.close()
                self._files.clear()
                self._epoch += 1
                self._since_snapshot = 0
        head = json.dumps({
            "version": FORMAT_VERSION,
            "epoch": self._epoch,
            "watermark": state["watermark"],
            "rv": state["rv"],
            "fps": {kind: list(fp) for kind, fp in state["fps"].items()},
        }, separators=(",", ":"))
        # Serialize-once: each stored object is a frozen snapshot whose
        # wire encoding was (or is now) computed exactly once and cached
        # on the instance — the snapshot body splices those strings, so a
        # compaction after a group-commit epoch re-serializes nothing.
        bodies, shared = [], 0
        for o in state["objects"]:
            s, reused = serialize.wire_json(o)
            bodies.append(s)
            if reused:
                shared += len(s)
        if self._metrics is not None and shared:
            self._metrics["shared_bytes"].inc(by=float(shared))
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(head[:-1] + ',"objects":[' + ",".join(bodies) + "]}")
            f.flush()
            _fsync(f.fileno())
        os.replace(tmp, self.snapshot_path)
        for epoch, _, path in discover_wal_files(self.dirpath,
                                                 include_empty=True):
            if epoch < self._epoch:
                os.unlink(path)
        if self._metrics is not None:
            self._metrics["snapshots"].inc()

    def close(self) -> None:
        with self._mu:
            for f in self._files.values():
                if not f.closed:
                    f.flush()
                    f.close()
            self._files.clear()


def _load_disk_state(dirpath: str) -> Tuple[Dict[tuple, dict],
                                            Dict[str, Tuple[int, int]], int]:
    """Read snapshot + every WAL file into (key -> object doc,
    kind -> fp token, rv). Records at or below the snapshot watermark are
    already reflected in the snapshot and are skipped. Files apply one at
    a time — a kind lives in one shard, so per-key (and per-kind
    fingerprint) order is per-file order; the per-kind winner is the
    record with the highest seq."""
    objects: Dict[tuple, dict] = {}
    fps: Dict[str, Tuple[int, int]] = {}
    fp_seq: Dict[str, int] = {}
    rv = 0
    watermark = 0
    snap_path = os.path.join(dirpath, SNAPSHOT_FILE)
    if os.path.exists(snap_path):
        with open(snap_path, encoding="utf-8") as f:
            doc = json.load(f)
        if doc.get("version") != FORMAT_VERSION:
            raise ValueError(
                f"unsupported store snapshot version {doc.get('version')!r}")
        watermark = int(doc.get("watermark", 0))
        rv = int(doc.get("rv", 0))
        fps = {k: (int(v[0]), int(v[1])) for k, v in doc.get("fps", {}).items()}
        for obj_doc in doc.get("objects", ()):
            key = (obj_doc.get("kind", ""),
                   obj_doc.get("meta", {}).get("namespace", ""),
                   obj_doc.get("meta", {}).get("name", ""))
            objects[key] = obj_doc
    for _, _, path in discover_wal_files(dirpath):
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    break  # torn tail write (crash mid-append): stop this file
                seq = int(rec.get("seq", 0))
                if seq <= watermark:
                    continue
                key = tuple(rec["key"])
                if rec["op"] == "PUT":
                    objects[key] = rec["obj"]
                else:
                    objects.pop(key, None)
                fp = rec.get("fp") or (0, 0)
                if seq >= fp_seq.get(key[0], 0):
                    fps[key[0]] = (int(fp[0]), int(fp[1]))
                    fp_seq[key[0]] = seq
                rv = max(rv, int(fp[1]))
    return objects, fps, rv


def open_persistent_store(dirpath: str, shards: int = DEFAULT_STORE_SHARDS,
                          batch_fanout: bool = True,
                          compact_every: int = DEFAULT_COMPACT_EVERY,
                          fsync: bool = False) -> APIServer:
    """Open (or create) a persistent APIServer backed by ``dirpath``.
    Existing snapshot+WAL are replayed into the fresh store — identical
    contents AND identical per-kind fingerprint tokens — then immediately
    compacted so the restore point is the new snapshot and every later
    run replays at most ``compact_every`` records on top of it. Attach
    any metrics registry *after* this returns (the store forwards it to
    the WAL)."""
    t0 = time.perf_counter()
    api = APIServer(shards=shards, batch_fanout=batch_fanout)
    objects, fps, rv = _load_disk_state(dirpath)
    if objects or fps:
        api.load_state((serialize.from_wire(doc) for doc in objects.values()),
                       fps, rv)
    wal = StoreWAL(dirpath, compact_every=compact_every, fsync=fsync)
    api.attach_wal(wal)
    wal.compact(api)
    api.restore_seconds = time.perf_counter() - t0
    api.restored_objects = len(objects)
    return api
