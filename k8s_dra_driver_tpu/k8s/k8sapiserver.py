"""Conformance kube-apiserver: serves the real Kubernetes REST wire protocol
over the in-process store.

This is the mock-NVML-kind-cluster analog for the API seam (reference CI:
/root/reference/hack/ci/mock-nvml, .github/workflows/mock-nvml-e2e.yaml):
`KubernetesAPIServer` (kubeclient.py) — the adapter the five binaries use
with ``--api-backend kubernetes`` — is exercised against this server in CI,
so the codec and REST/watch plumbing that will face a live cluster are
tested on every run without one.

Protocol surface (the subset the driver exercises, matching a real
apiserver's behavior):

    GET/POST       /api/v1/namespaces/{ns}/pods            core, namespaced
    GET/PUT/DELETE /api/v1/namespaces/{ns}/pods/{name}
    GET/POST       /apis/{group}/{version}/{plural}        cluster-scoped
    GET            ...?labelSelector=k%3Dv,k2%3Dv2
    GET            ...?watch=true[&fieldSelector=metadata.name%3Dx]
                   -> JSON-lines {"type": ADDED|MODIFIED|DELETED, "object"}
    PUT            .../{name}/status                        status subresource
    errors         -> application/json k8s Status objects (404/409/422)

List responses are `<Kind>List` envelopes. Writes to a resource with a
status subresource ignore status changes (and vice versa), as on a real
apiserver — the adapter must split updates accordingly.
"""

from __future__ import annotations

import base64
import json
import logging
import queue
import ssl
import threading
import time
import urllib.parse
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from k8s_dra_driver_tpu.k8s.k8swire import (
    RESOURCE_MAP,
    from_k8s_wire,
    group_version_split,
    kind_for_plural,
    served_versions,
    to_k8s_wire,
)
from k8s_dra_driver_tpu.k8s.objects import (
    AlreadyExistsError,
    ApiError,
    ConflictError,
    NotFoundError,
)
from k8s_dra_driver_tpu.k8s.store import APIServer

log = logging.getLogger(__name__)

# Kinds whose /status is a separate subresource on a real apiserver. The
# ComputeDomain CRD declares `subresources: status: {}` (helm/crds), and the
# built-ins below behave this way upstream.
STATUS_SUBRESOURCE_KINDS = {
    "Pod", "Node", "DaemonSet", "Deployment", "ResourceClaim", "ComputeDomain",
}

# Internal dataclass fields that live under .status on the k8s wire, per
# kind — used to split main-resource writes from status writes.
_STATUS_FIELDS = {
    "Pod": ("phase", "pod_ip", "ready", "conditions"),
    "Node": ("addresses", "allocatable"),
    "DaemonSet": ("desired", "ready"),
    "Deployment": ("ready",),
    "ResourceClaim": ("allocation", "reserved_for"),
    "ComputeDomain": ("status",),
}

WATCH_HEARTBEAT_S = 5.0


def _status_error(e: Exception) -> Tuple[int, Dict[str, Any]]:
    code, reason = {
        NotFoundError: (404, "NotFound"),
        AlreadyExistsError: (409, "AlreadyExists"),
        ConflictError: (409, "Conflict"),
        AdmissionDeniedError: (400, "Invalid"),
        AdmissionUnreachableError: (500, "InternalError"),
    }.get(type(e), (500, "InternalError"))
    return code, {
        "kind": "Status",
        "apiVersion": "v1",
        "status": "Failure",
        "message": str(e),
        "reason": reason,
        "code": code,
    }


def _parse_label_selector(raw: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for part in raw.split(","):
        part = part.strip()
        if "=" in part:
            k, _, v = part.partition("=")
            out[k.strip().rstrip("!")] = v.strip().lstrip("=")
    return out


def _parse_field_selector(raw: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for part in raw.split(","):
        if "=" in part:
            k, _, v = part.partition("=")
            out[k.strip()] = v.strip()
    return out


def _merge_status(kind: str, base, incoming):
    """Copy the status-backed fields of `incoming` onto a copy of `base`."""
    out = base.deepcopy()
    for f in _STATUS_FIELDS.get(kind, ()):  # noqa: B905
        setattr(out, f, getattr(incoming, f))
    return out


def _merge_main(kind: str, base, incoming):
    """Copy everything EXCEPT status-backed fields from `incoming` onto a
    copy of `base` (metadata travels with the main resource)."""
    out = incoming.deepcopy()
    for f in _STATUS_FIELDS.get(kind, ()):  # noqa: B905
        setattr(out, f, getattr(base, f))
    return out


class _Route:
    """Decomposed request path: kind, version, namespace, name, subresource."""

    def __init__(self, kind: str, namespace: str, name: str, subresource: str,
                 version: str = ""):
        self.kind = kind
        self.namespace = namespace
        self.name = name
        self.subresource = subresource
        self.version = version  # bare version from the path, e.g. "v1beta1"


def _parse_path(path: str) -> Optional[_Route]:
    parts = [p for p in path.split("/") if p]
    # /api/v1/... (core) or /apis/<group>/<version>/...
    if len(parts) >= 2 and parts[0] == "api" and parts[1] == "v1":
        group, version = "", "v1"
        rest = parts[2:]
    elif len(parts) >= 3 and parts[0] == "apis":
        group, version = parts[1], parts[2]
        rest = parts[3:]
    else:
        return None
    namespace = ""
    if len(rest) >= 2 and rest[0] == "namespaces":
        namespace = rest[1]
        rest = rest[2:]
    if not rest:
        return None
    plural, rest = rest[0], rest[1:]
    kind = kind_for_plural(plural)
    if kind is None:
        return None
    # Wrong group or unserved version for a known resource -> no route
    # (404), as upstream.
    kind_group, _ = group_version_split(RESOURCE_MAP[kind][0])
    if group != kind_group or version not in served_versions(kind):
        return None
    name = rest[0] if rest else ""
    subresource = rest[1] if len(rest) > 1 else ""
    return _Route(kind, namespace, name, subresource, version=version)


def _discovery_doc(path: str) -> Optional[Dict[str, Any]]:
    """Serve /apis (APIGroupList) and /apis/<group> (APIGroup) so clients
    can negotiate versions the way client-go discovery does."""
    parts = [p for p in path.split("/") if p]
    groups: Dict[str, List[str]] = {}
    for kind, (api_version, _, _) in RESOURCE_MAP.items():
        group, _version = group_version_split(api_version)
        if not group:
            continue
        groups.setdefault(group, [v for v in served_versions(kind)])
    if parts == ["apis"]:
        return {
            "kind": "APIGroupList", "apiVersion": "v1",
            "groups": [_group_doc(g, vs) for g, vs in sorted(groups.items())],
        }
    if len(parts) == 2 and parts[0] == "apis" and parts[1] in groups:
        return _group_doc(parts[1], groups[parts[1]])
    return None


def _group_doc(group: str, versions: List[str]) -> Dict[str, Any]:
    return {
        "kind": "APIGroup", "apiVersion": "v1", "name": group,
        "versions": [
            {"groupVersion": f"{group}/{v}", "version": v} for v in versions
        ],
        "preferredVersion": {
            "groupVersion": f"{group}/{versions[0]}", "version": versions[0],
        },
    }


class AdmissionDeniedError(ApiError):
    """Webhook disallowed the request (HTTP 400, reason Invalid)."""


class AdmissionUnreachableError(ApiError):
    """failurePolicy=Fail webhook could not be reached (HTTP 500)."""


def _webhook_matches(rule_sets, plural: str, group: str, version: str,
                     operation: str) -> bool:
    for rule in rule_sets:
        groups_ok = "*" in rule.api_groups or group in rule.api_groups
        vers_ok = (not rule.api_versions or "*" in rule.api_versions
                   or version in rule.api_versions)
        res_ok = "*" in rule.resources or plural in rule.resources
        op_ok = "*" in rule.operations or operation in rule.operations
        if groups_ok and vers_ok and res_ok and op_ok:
            return True
    return False


def _call_admission_webhook(wh, review: Dict[str, Any],
                            timeout: float = 10.0) -> Dict[str, Any]:
    """POST an AdmissionReview to one registered webhook over (m)TLS or
    plain HTTP, verifying the serving cert against the caBundle — the
    apiserver side of the reference's webhook contract."""
    url = wh.client_config.url
    if not url and wh.client_config.service_name:
        # Service refs resolve via cluster DNS on a real apiserver; this
        # conformance server has no DNS, so only url-style configs work.
        raise AdmissionUnreachableError(
            f"webhook {wh.name}: service-ref clientConfig not resolvable "
            "outside a cluster; use clientConfig.url"
        )
    ctx = None
    if url.startswith("https"):
        ctx = ssl.create_default_context()
        if wh.client_config.ca_bundle:
            pem = base64.b64decode(wh.client_config.ca_bundle).decode()
            ctx.load_verify_locations(cadata=pem)
    req = urllib.request.Request(
        url, data=json.dumps(review).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout, context=ctx) as resp:
        body = resp.read()
    try:
        return json.loads(body)
    except ValueError as e:
        # Non-AdmissionReview 2xx body (misconfigured proxy, HTML error
        # page): treat like an unreachable webhook so failurePolicy applies.
        raise AdmissionUnreachableError(
            f"webhook {wh.name}: non-JSON response: {e}"
        ) from None


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    api: APIServer
    stopping: threading.Event

    def _admit(self, route: _Route, doc: Dict[str, Any], operation: str) -> None:
        """Run registered validating webhooks for this write; raises
        AdmissionDeniedError / AdmissionUnreachableError accordingly.
        For DELETE, `doc` is the existing object and is sent as oldObject
        (request.object is null), per the admission.k8s.io/v1 contract."""
        try:
            configs = self.api.list("ValidatingWebhookConfiguration")
        except Exception:  # store may predate the kind
            return
        if not configs:
            return
        api_version, plural, _ = RESOURCE_MAP[route.kind]
        group, _ = group_version_split(api_version)
        version = route.version
        for vwc in configs:
            for wh in vwc.webhooks:
                if not _webhook_matches(wh.rules, plural, group, version,
                                        operation):
                    continue
                review = {
                    "apiVersion": "admission.k8s.io/v1",
                    "kind": "AdmissionReview",
                    "request": {
                        "uid": uuid.uuid4().hex,
                        "kind": {"group": group,
                                 "version": version,
                                 "kind": route.kind},
                        "operation": operation,
                        "namespace": route.namespace,
                        "object": None if operation == "DELETE" else doc,
                        "oldObject": doc if operation == "DELETE" else None,
                    },
                }
                try:
                    out = _call_admission_webhook(wh, review)
                except AdmissionUnreachableError as e:
                    if wh.failure_policy == "Ignore":
                        log.warning("ignoring failed webhook %s: %s",
                                    wh.name, e)
                        continue
                    raise
                except OSError as e:
                    if wh.failure_policy == "Ignore":
                        log.warning("ignoring unreachable webhook %s: %s",
                                    wh.name, e)
                        continue
                    raise AdmissionUnreachableError(
                        f"webhook {wh.name} unreachable: {e}"
                    ) from None
                resp = out.get("response") or {}
                if not resp.get("allowed", False):
                    msg = (resp.get("status") or {}).get("message", "denied")
                    raise AdmissionDeniedError(
                        f"admission webhook {wh.name!r} denied the request: "
                        f"{msg}"
                    )

    def log_message(self, *args: object) -> None:  # quiet
        pass

    def _send_json(self, status: int, doc: dict) -> None:
        body = json.dumps(doc).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_err(self, e: Exception) -> None:
        code, doc = _status_error(e)
        self._send_json(code, doc)

    def _body(self) -> dict:
        n = int(self.headers.get("Content-Length", "0"))
        return json.loads(self.rfile.read(n) or b"{}")

    def _route_and_query(self) -> Tuple[Optional[_Route], Dict[str, List[str]]]:
        parsed = urllib.parse.urlparse(self.path)
        return _parse_path(parsed.path), urllib.parse.parse_qs(parsed.query)

    # -- verbs -------------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802
        route, q = self._route_and_query()
        try:
            if route is None:
                raw_path = urllib.parse.urlparse(self.path).path
                if raw_path in ("/healthz", "/readyz"):
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", "2")
                    self.end_headers()
                    self.wfile.write(b"ok")
                    return
                disc = _discovery_doc(raw_path)
                if disc is not None:
                    self._send_json(200, disc)
                    return
                raise NotFoundError(f"no route for {self.path}")
            if q.get("watch", ["false"])[0] == "true":
                self._stream_watch(route, q)
                return
            if route.name:
                obj = self.api.get(route.kind, route.name, route.namespace)
                self._send_json(200, to_k8s_wire(obj, route.version))
                return
            labels = None
            if "labelSelector" in q:
                labels = _parse_label_selector(q["labelSelector"][0])
            ns: Optional[str] = route.namespace or None
            objs = self.api.list(route.kind, namespace=ns, label_selector=labels)
            fields = _parse_field_selector(q.get("fieldSelector", [""])[0])
            want_name = fields.get("metadata.name")
            if want_name:
                objs = [o for o in objs if o.meta.name == want_name]
            api_version, _, _ = RESOURCE_MAP[route.kind]
            group, _v = group_version_split(api_version)
            self._send_json(200, {
                "apiVersion": (f"{group}/{route.version}" if group
                               else route.version),
                "kind": f"{route.kind}List",
                "metadata": {"resourceVersion": str(int(time.time() * 1000))},
                "items": [to_k8s_wire(o, route.version) for o in objs],
            })
        except ApiError as e:
            self._send_err(e)
        except (BrokenPipeError, ConnectionResetError):
            pass
        except (ValueError, KeyError) as e:
            self._send_json(400, _status_error(e)[1] | {"code": 400, "reason": "BadRequest"})

    def do_POST(self) -> None:  # noqa: N802
        route, _ = self._route_and_query()
        try:
            if route is None or route.name:
                raise NotFoundError(f"no route for POST {self.path}")
            doc = self._body()
            obj = from_k8s_wire(doc)
            if route.namespace and not obj.meta.namespace:
                obj.meta.namespace = route.namespace
            self._admit(route, doc, "CREATE")
            created = self.api.create(obj)
            self._send_json(201, to_k8s_wire(created, route.version))
        except ApiError as e:
            self._send_err(e)
        except (ValueError, KeyError) as e:
            self._send_json(400, _status_error(e)[1] | {"code": 400, "reason": "BadRequest"})

    def do_PUT(self) -> None:  # noqa: N802
        route, _ = self._route_and_query()
        try:
            if route is None or not route.name:
                raise NotFoundError(f"no route for PUT {self.path}")
            doc = self._body()
            incoming = from_k8s_wire(doc)
            if route.namespace and not incoming.meta.namespace:
                incoming.meta.namespace = route.namespace
            if route.subresource != "status":
                self._admit(route, doc, "UPDATE")
            current = self.api.get(route.kind, route.name, route.namespace)
            if route.subresource == "status":
                # Status writes: only status fields change; CAS on the
                # incoming resourceVersion.
                merged = _merge_status(route.kind, current, incoming)
                merged.meta.resource_version = incoming.meta.resource_version
            elif route.kind in STATUS_SUBRESOURCE_KINDS:
                # Main-resource writes ignore status changes, like a real
                # apiserver with the status subresource enabled.
                merged = _merge_main(route.kind, current, incoming)
            else:
                merged = incoming
            updated = self.api.update(merged)
            self._send_json(200, to_k8s_wire(updated, route.version))
        except ApiError as e:
            self._send_err(e)
        except (ValueError, KeyError) as e:
            self._send_json(400, _status_error(e)[1] | {"code": 400, "reason": "BadRequest"})

    def do_DELETE(self) -> None:  # noqa: N802
        route, _ = self._route_and_query()
        try:
            if route is None or not route.name:
                raise NotFoundError(f"no route for DELETE {self.path}")
            current = self.api.get(route.kind, route.name, route.namespace)
            self._admit(route, to_k8s_wire(current, route.version), "DELETE")
            self.api.delete(route.kind, route.name, route.namespace)
            self._send_json(200, {
                "kind": "Status", "apiVersion": "v1", "status": "Success",
            })
        except ApiError as e:
            self._send_err(e)

    # -- watch -------------------------------------------------------------

    def _stream_watch(self, route: _Route, q: Dict[str, List[str]]) -> None:
        fields = _parse_field_selector(q.get("fieldSelector", [""])[0])
        name = fields.get("metadata.name") or (route.name or None)
        labels = (_parse_label_selector(q["labelSelector"][0])
                  if "labelSelector" in q else None)
        wq = self.api.watch(route.kind, name=name,
                            namespace=route.namespace or None)
        try:
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def write_line(doc: dict) -> None:
                line = (json.dumps(doc) + "\n").encode()
                self.wfile.write(f"{len(line):x}\r\n".encode() + line + b"\r\n")
                self.wfile.flush()

            # resourceVersion semantics: a client that lists then watches
            # passes the list rv. The store keeps no event history, so
            # replay the current snapshot as ADDED — at-least-once, which
            # informer caches absorb (same property as list+watch replays
            # against a real apiserver after a 410).
            if q.get("resourceVersion", [""])[0] not in ("", "0"):
                for obj in self.api.list(route.kind,
                                         namespace=route.namespace or None,
                                         label_selector=labels):
                    if name and obj.meta.name != name:
                        continue
                    write_line({"type": "ADDED",
                                "object": to_k8s_wire(obj, route.version)})
            last_beat = time.monotonic()
            while not self.stopping.is_set():
                try:
                    ev = wq.get(timeout=0.5)
                except queue.Empty:
                    if time.monotonic() - last_beat >= WATCH_HEARTBEAT_S:
                        # BOOKMARK doubles as liveness signal; real
                        # apiservers emit these with allowWatchBookmarks.
                        write_line({"type": "BOOKMARK", "object": {
                            "kind": route.kind,
                            "metadata": {"resourceVersion": "0"},
                        }})
                        last_beat = time.monotonic()
                    continue
                if labels is not None:
                    obj_labels = ev.obj.meta.labels
                    if any(obj_labels.get(k) != v for k, v in labels.items()):
                        continue
                write_line({"type": ev.type,
                            "object": to_k8s_wire(ev.obj, route.version)})
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            self.api.stop_watch(route.kind, wq)


class K8sAPIServer:
    """Hosts the conformance apiserver on a background thread."""

    def __init__(self, api: Optional[APIServer] = None, host: str = "127.0.0.1",
                 port: int = 0):
        self.api = api or APIServer()

        class Handler(_Handler):
            pass

        Handler.api = self.api
        Handler.stopping = self._stopping = threading.Event()
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "K8sAPIServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="k8s-apiserver", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stopping.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)


def main(argv=None) -> int:
    import argparse
    import signal

    parser = argparse.ArgumentParser(
        "tpu-dra-k8sapiserver",
        description="conformance apiserver speaking the real k8s REST wire",
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8002)
    args = parser.parse_args(argv)
    srv = K8sAPIServer(host=args.host, port=args.port).start()
    print(f"serving k8s wire on {srv.url}", flush=True)
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *a: stop.set())
    stop.wait()
    srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
