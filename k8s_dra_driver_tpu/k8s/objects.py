"""Object model: metadata, ownership, errors — and snapshot freezing.

Deliberately small: the fields the driver actually exercises (the same
subset the reference touches through client-go) — names/namespaces/uids,
labels, optimistic-concurrency resourceVersions, finalizers + deletion
timestamps, and owner references.

Published-snapshot immutability (the zero-copy store contract): the store
freezes every object at publish time via :func:`freeze`, which walks the
dataclass graph, swaps ``list``/``dict`` containers for sealed subclasses
(:class:`FrozenList`/:class:`FrozenDict`), and sets a ``_sealed`` flag
enforced by a per-class ``__setattr__`` wrapper. Reads (`get`/`list`/watch
fan-out) then hand out *references* instead of deep copies; any attempt to
mutate a published snapshot raises :class:`FrozenSnapshotError` (and first
reports through :func:`_frozen_mutation_hook`, the seam the concurrency
sanitizer instruments as its write-after-publish detector).

Thawing is deliberately the same operation as deep-copying: sealed classes
pickle *without* the seal (``__getstate__`` drops the flag and the cached
wire encoding) and the frozen containers reduce to plain ``list``/``dict``
— so ``obj.deepcopy()`` / ``obj.thaw()`` of a frozen snapshot is a fully
mutable working copy, which is exactly what ``update_with_retry`` hands
its mutator on the copy-on-write path.
"""

from __future__ import annotations

import copy
import pickle
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


class ApiError(Exception):
    pass


class NotFoundError(ApiError):
    pass


class AlreadyExistsError(ApiError):
    pass


class ConflictError(ApiError):
    """resourceVersion mismatch — the CAS failure callers retry on."""


class FrozenSnapshotError(AttributeError):
    """Mutation of a published (frozen) store snapshot. Reads hand out
    references; mutate a working copy instead — inside an
    ``update_with_retry`` closure, or via ``obj.thaw()``/``obj.deepcopy()``."""


# -- snapshot freezing -------------------------------------------------------

def _frozen_mutation_hook(obj: Any, op: str) -> None:
    """Seam called on every attempted mutation of a frozen snapshot,
    immediately before FrozenSnapshotError is raised. Production no-op;
    the concurrency sanitizer (analysis/sanitizer) patches it to record a
    write-after-publish violation with both stacks."""


# Instance attrs that are seal bookkeeping, never content: excluded from
# pickling (so deepcopy == thaw) and from __getstate__-based copies.
_SEAL_STATE_ATTRS = ("_sealed", "_wire_json")


def _raise_frozen(obj: Any, op: str) -> None:
    _frozen_mutation_hook(obj, op)
    raise FrozenSnapshotError(
        f"cannot {op} on a published store snapshot "
        f"({type(obj).__name__}); mutate a working copy via "
        f"update_with_retry, .thaw(), or .deepcopy()"
    )


class FrozenList(list):
    """A sealed list inside a published snapshot. Compares equal to (and
    serializes like) a plain list; every mutator raises. Reduces to a
    plain list so pickling (deepcopy/thaw) yields a mutable copy."""

    __slots__ = ()

    def _frozen(self, op: str):
        _raise_frozen(self, op)

    def __reduce__(self):
        return (list, (list(self),))

    def __setitem__(self, *a):    self._frozen("__setitem__")
    def __delitem__(self, *a):    self._frozen("__delitem__")
    def __iadd__(self, *a):       self._frozen("__iadd__")
    def __imul__(self, *a):       self._frozen("__imul__")
    def append(self, *a):         self._frozen("append")
    def extend(self, *a):         self._frozen("extend")
    def insert(self, *a):         self._frozen("insert")
    def pop(self, *a):            self._frozen("pop")
    def remove(self, *a):         self._frozen("remove")
    def clear(self):              self._frozen("clear")
    def sort(self, *a, **kw):     self._frozen("sort")
    def reverse(self):            self._frozen("reverse")


class FrozenDict(dict):
    """A sealed dict inside a published snapshot; see FrozenList."""

    __slots__ = ()

    def _frozen(self, op: str):
        _raise_frozen(self, op)

    def __reduce__(self):
        return (dict, (dict(self),))

    def __setitem__(self, *a):    self._frozen("__setitem__")
    def __delitem__(self, *a):    self._frozen("__delitem__")
    def __ior__(self, *a):        self._frozen("__ior__")
    def pop(self, *a):            self._frozen("pop")
    def popitem(self):            self._frozen("popitem")
    def clear(self):              self._frozen("clear")
    def update(self, *a, **kw):   self._frozen("update")
    def setdefault(self, *a):     self._frozen("setdefault")


# Classes whose __setattr__/__delattr__ have been wrapped with the seal
# check. The wrap happens lazily, once per class, the first time freeze()
# meets an instance — unfrozen instances of a wrapped class pay one dict
# lookup per attribute write, nothing else changes.
_SEALED_CLASSES: set = set()


def _install_seal(cls: type) -> None:
    if cls in _SEALED_CLASSES:
        return
    orig_set = cls.__setattr__
    orig_del = cls.__delattr__

    def __setattr__(self, name, value, _orig=orig_set):
        if self.__dict__.get("_sealed"):
            _raise_frozen(self, f"set .{name}")
        _orig(self, name, value)

    def __delattr__(self, name, _orig=orig_del):
        if self.__dict__.get("_sealed"):
            _raise_frozen(self, f"delete .{name}")
        _orig(self, name)

    def __getstate__(self):
        # Pickling (deepcopy/thaw) and copy.copy drop the seal and the
        # cached wire encoding: a copy of a snapshot is a working copy.
        # Unfrozen instances of a wrapped class carry neither key and
        # skip the filtering pass (the write path's copy-in pickles the
        # caller's unfrozen object — keep that hot path allocation-free).
        d = self.__dict__
        if "_sealed" not in d and "_wire_json" not in d:
            return d
        return {k: v for k, v in d.items()
                if k not in _SEAL_STATE_ATTRS}

    cls.__setattr__ = __setattr__  # type: ignore[method-assign]
    cls.__delattr__ = __delattr__  # type: ignore[method-assign]
    cls.__getstate__ = __getstate__  # type: ignore[attr-defined]
    _SEALED_CLASSES.add(cls)


def is_frozen(obj: Any) -> bool:
    """True if ``obj`` is a sealed published snapshot (or part of one)."""
    if isinstance(obj, (FrozenList, FrozenDict)):
        return True
    d = getattr(obj, "__dict__", None)
    return bool(d) and bool(d.get("_sealed"))


def freeze(obj: Any) -> Any:
    """Recursively seal a dataclass graph in place and return it. Plain
    lists/dicts are replaced by their frozen twins; already-frozen
    subtrees (structural sharing with a prior revision) are left alone —
    the short-circuit is what makes a status-only copy-on-write commit
    O(changed fields), not O(object)."""
    return _freeze_value(obj)


# Leaf types freeze() meets constantly and never touches: one set lookup
# instead of the full isinstance/is_dataclass dispatch ladder. freeze()
# sits on every store write, so the common case (a string field) must be
# near-free — this check alone cut the per-write freeze cost ~40%.
_ATOMIC_TYPES = frozenset(
    {str, int, float, bool, bytes, type(None), complex, frozenset})


def _freeze_value(v: Any) -> Any:
    cls = v.__class__
    if cls in _ATOMIC_TYPES:
        return v
    if cls is FrozenList or cls is FrozenDict:
        return v  # already-frozen subtree (shared with a prior revision)
    if cls is list:
        return FrozenList(_freeze_value(x) for x in v)
    if cls is dict:
        return FrozenDict((k, _freeze_value(x)) for k, x in v.items())
    if cls is tuple:
        return tuple(_freeze_value(x) for x in v)
    if hasattr(cls, "__dataclass_fields__") and not isinstance(v, type):
        d = v.__dict__
        if d.get("_sealed"):
            return v  # shared frozen subtree
        _install_seal(cls)
        for name, val in list(d.items()):
            nv = _freeze_value(val)
            if nv is not val:
                d[name] = nv  # direct slot write: object not sealed yet
        d["_sealed"] = True
        return v
    return v


def thaw(obj: Any) -> Any:
    """Mutable deep working copy of a (possibly frozen) object graph."""
    if isinstance(obj, K8sObject):
        return obj.deepcopy()
    try:
        return pickle.loads(pickle.dumps(obj, pickle.HIGHEST_PROTOCOL))
    except Exception:  # noqa: BLE001 — unpicklable extras: generic copier
        return copy.deepcopy(obj)


@dataclass
class OwnerReference:
    kind: str
    name: str
    uid: str
    controller: bool = True


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = ""
    uid: str = ""
    resource_version: int = 0
    generation: int = 0
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    finalizers: List[str] = field(default_factory=list)
    owner_references: List[OwnerReference] = field(default_factory=list)
    creation_timestamp: float = 0.0
    deletion_timestamp: Optional[float] = None


@dataclass
class K8sObject:
    """Base for every stored object. ``kind`` is the type key; subclasses
    add ``spec``/``status``-shaped fields (plain dataclasses or dicts)."""

    kind: str = ""
    meta: ObjectMeta = field(default_factory=ObjectMeta)

    # -- convenience -------------------------------------------------------

    @property
    def name(self) -> str:
        return self.meta.name

    @property
    def namespace(self) -> str:
        return self.meta.namespace

    @property
    def uid(self) -> str:
        return self.meta.uid

    @property
    def key(self) -> str:
        return f"{self.meta.namespace}/{self.meta.name}" if self.meta.namespace else self.meta.name

    @property
    def deleting(self) -> bool:
        return self.meta.deletion_timestamp is not None

    def deepcopy(self):
        # Pickle round-trip: the same deep-clone semantics for plain
        # dataclass trees at C speed — 2-4x cheaper than copy.deepcopy
        # (measured 16->7us on a Pod, 262->59us on a 4-chip
        # ResourceSlice). Since the zero-copy store reads hand out
        # references, this runs only on the write path's one copy-in and
        # on explicit working copies. Copying a FROZEN snapshot thaws it
        # (sealed classes pickle without the seal, frozen containers
        # reduce to plain list/dict) — deepcopy and thaw are the same
        # operation. Objects carrying unpicklable extras fall back to
        # the generic copier.
        try:
            return pickle.loads(pickle.dumps(self, pickle.HIGHEST_PROTOCOL))
        except Exception:  # noqa: BLE001 — any unpicklable attr: full fallback
            return copy.deepcopy(self)

    def thaw(self):
        """Mutable deep working copy of this (possibly frozen) object —
        the explicit opt-out from the store's reference-handout reads."""
        return self.deepcopy()

    @property
    def frozen(self) -> bool:
        return bool(self.__dict__.get("_sealed"))

    def owned_by(self, owner: "K8sObject") -> bool:
        return any(r.uid == owner.uid for r in self.meta.owner_references)

    def add_owner(self, owner: "K8sObject") -> None:
        if not self.owned_by(owner):
            self.meta.owner_references.append(
                OwnerReference(kind=owner.kind, name=owner.name, uid=owner.uid)
            )


def new_meta(name: str, namespace: str = "", labels: Optional[Dict[str, str]] = None,
             **kw: Any) -> ObjectMeta:
    return ObjectMeta(name=name, namespace=namespace, labels=dict(labels or {}), **kw)


def fresh_uid() -> str:
    return uuid.uuid4().hex


def now() -> float:
    return time.time()
