"""Object model: metadata, ownership, errors.

Deliberately small: the fields the driver actually exercises (the same
subset the reference touches through client-go) — names/namespaces/uids,
labels, optimistic-concurrency resourceVersions, finalizers + deletion
timestamps, and owner references.
"""

from __future__ import annotations

import copy
import pickle
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


class ApiError(Exception):
    pass


class NotFoundError(ApiError):
    pass


class AlreadyExistsError(ApiError):
    pass


class ConflictError(ApiError):
    """resourceVersion mismatch — the CAS failure callers retry on."""


@dataclass
class OwnerReference:
    kind: str
    name: str
    uid: str
    controller: bool = True


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = ""
    uid: str = ""
    resource_version: int = 0
    generation: int = 0
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    finalizers: List[str] = field(default_factory=list)
    owner_references: List[OwnerReference] = field(default_factory=list)
    creation_timestamp: float = 0.0
    deletion_timestamp: Optional[float] = None


@dataclass
class K8sObject:
    """Base for every stored object. ``kind`` is the type key; subclasses
    add ``spec``/``status``-shaped fields (plain dataclasses or dicts)."""

    kind: str = ""
    meta: ObjectMeta = field(default_factory=ObjectMeta)

    # -- convenience -------------------------------------------------------

    @property
    def name(self) -> str:
        return self.meta.name

    @property
    def namespace(self) -> str:
        return self.meta.namespace

    @property
    def uid(self) -> str:
        return self.meta.uid

    @property
    def key(self) -> str:
        return f"{self.meta.namespace}/{self.meta.name}" if self.meta.namespace else self.meta.name

    @property
    def deleting(self) -> bool:
        return self.meta.deletion_timestamp is not None

    def deepcopy(self):
        # Pickle round-trip: the same deep-clone semantics for plain
        # dataclass trees at C speed — 2-4x cheaper than copy.deepcopy
        # (measured 16->7us on a Pod, 262->59us on a 4-chip
        # ResourceSlice), and the store clones on EVERY read and write,
        # so this is the single hottest call in a cluster storm. Objects
        # carrying unpicklable extras fall back to the generic copier.
        try:
            return pickle.loads(pickle.dumps(self, pickle.HIGHEST_PROTOCOL))
        except Exception:  # noqa: BLE001 — any unpicklable attr: full fallback
            return copy.deepcopy(self)

    def owned_by(self, owner: "K8sObject") -> bool:
        return any(r.uid == owner.uid for r in self.meta.owner_references)

    def add_owner(self, owner: "K8sObject") -> None:
        if not self.owned_by(owner):
            self.meta.owner_references.append(
                OwnerReference(kind=owner.kind, name=owner.name, uid=owner.uid)
            )


def new_meta(name: str, namespace: str = "", labels: Optional[Dict[str, str]] = None,
             **kw: Any) -> ObjectMeta:
    return ObjectMeta(name=name, namespace=namespace, labels=dict(labels or {}), **kw)


def fresh_uid() -> str:
    return uuid.uuid4().hex


def now() -> float:
    return time.time()
