"""K8sObject <-> JSON wire format for the HTTP API server and clients.

The reference's equivalent is client-go's generated codecs; here one generic
typed codec covers every registered kind: encode via dataclasses.asdict,
decode by walking the dataclass field annotations (nested dataclasses,
List[...], Dict[...], Optional[...]). The kind registry is built from
K8sObject subclasses, so new kinds serialize without codec changes.
"""

from __future__ import annotations

import dataclasses
import json
import typing
from typing import Any, Dict, Optional, Tuple, Type

from k8s_dra_driver_tpu.k8s.objects import K8sObject

# Importing for side effect: registers every kind as a K8sObject subclass.
import k8s_dra_driver_tpu.k8s.core  # noqa: F401
import k8s_dra_driver_tpu.api.computedomain  # noqa: F401
import k8s_dra_driver_tpu.api.servinggroup  # noqa: F401
import k8s_dra_driver_tpu.api.tenantquota  # noqa: F401


def _all_subclasses(cls: type) -> list[type]:
    out = []
    for sub in cls.__subclasses__():
        out.append(sub)
        out.extend(_all_subclasses(sub))
    return out


def kind_registry() -> Dict[str, Type[K8sObject]]:
    reg: Dict[str, Type[K8sObject]] = {}
    for cls in _all_subclasses(K8sObject):
        if not dataclasses.is_dataclass(cls):
            continue
        for f in dataclasses.fields(cls):
            if f.name == "kind" and isinstance(f.default, str) and f.default:
                reg[f.default] = cls
    return reg


_REGISTRY = kind_registry()


def to_wire(obj: K8sObject) -> Dict[str, Any]:
    return dataclasses.asdict(obj)


def wire_json(obj: K8sObject) -> Tuple[str, bool]:
    """Compact JSON wire encoding of one object — **serialized once per
    published snapshot**. Frozen store snapshots are immutable, so the
    first encoding is cached on the instance (``_wire_json``, dropped by
    thaw/deepcopy) and every later consumer — the WAL record, durable
    group-commit, snapshot compaction, the HTTP watch stream — reuses the
    same string. Returns ``(encoding, reused)``; ``reused`` is True when
    the cached encoding was served without re-serializing (the
    ``tpu_dra_store_snapshot_shared_bytes`` accounting seam)."""
    d = getattr(obj, "__dict__", None)
    if d is not None:
        cached = d.get("_wire_json")
        if cached is not None:
            return cached, True
    s = json.dumps(to_wire(obj), separators=(",", ":"))
    if d is not None and d.get("_sealed"):
        # Direct slot write: the cache is seal bookkeeping, not content
        # (sealed __setattr__ would reject it). Benign if two threads
        # race — both compute the identical string.
        d["_wire_json"] = s
    return s, False


def _decode_value(tp: Any, value: Any) -> Any:
    if value is None:
        return None
    origin = typing.get_origin(tp)
    if origin is typing.Union:  # Optional[X] and friends
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        return _decode_value(args[0], value) if len(args) == 1 else value
    if dataclasses.is_dataclass(tp) and isinstance(value, dict):
        return _decode_dataclass(tp, value)
    if origin in (list, tuple):
        args = typing.get_args(tp)
        if origin is tuple and len(args) == 2 and args[1] is Ellipsis:
            elem = args[0]  # variadic Tuple[X, ...]
        elif origin is tuple and len(args) > 1:
            # Heterogeneous Tuple[X, Y, ...]: decode positionally; a wire
            # arity mismatch is corruption, not something to truncate away.
            if len(value) != len(args):
                raise ValueError(
                    f"expected {len(args)}-tuple on the wire, "
                    f"got {len(value)} elements")
            return tuple(_decode_value(a, v) for a, v in zip(args, value))
        else:
            elem = args[0] if args else Any
        seq = [_decode_value(elem, v) for v in value]
        return tuple(seq) if origin is tuple else seq
    if origin is dict:
        args = typing.get_args(tp)
        vt = args[1] if len(args) == 2 else Any
        return {k: _decode_value(vt, v) for k, v in value.items()}
    return value


_HINTS_CACHE: Dict[type, Dict[str, Any]] = {}


def _decode_dataclass(cls: type, data: Dict[str, Any]):
    # get_type_hints walks the MRO and evaluates string annotations on
    # every call — cached per class, it is ~all of the decode cost for a
    # large restore (an 8192-node store replays ~30k objects).
    hints = _HINTS_CACHE.get(cls)
    if hints is None:
        hints = _HINTS_CACHE[cls] = typing.get_type_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name not in data:
            continue
        kwargs[f.name] = _decode_value(hints.get(f.name, Any), data[f.name])
    return cls(**kwargs)


def from_wire(doc: Dict[str, Any]) -> K8sObject:
    kind = doc.get("kind", "")
    cls = _REGISTRY.get(kind)
    if cls is None:
        raise ValueError(f"unknown kind {kind!r}; known: {sorted(_REGISTRY)}")
    return _decode_dataclass(cls, doc)  # type: ignore[return-value]
