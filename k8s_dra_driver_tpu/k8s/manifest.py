"""Shared JSON/YAML manifest parsing for DRA device specs.

Single decoder for the ``spec.devices.{requests,config}`` shape (and the
RCT ``spec.spec`` unwrap) used by both the kubectl-apply loader
(sim/kubectl.py) and the admission webhook — one place to evolve when the
manifest schema grows.
"""

from __future__ import annotations

from typing import Any, Dict, List

from k8s_dra_driver_tpu.k8s.core import (
    DeviceClaimConfig,
    DeviceRequest,
    OpaqueDeviceConfig,
)


def unwrap_template_spec(spec: Dict[str, Any]) -> Dict[str, Any]:
    """ResourceClaimTemplate nests the claim spec at spec.spec."""
    return spec.get("spec", spec)


def device_requests_from_spec(spec: Dict[str, Any]) -> List[DeviceRequest]:
    return [
        DeviceRequest(
            name=r.get("name", "device"),
            device_class_name=r.get("deviceClassName", ""),
            allocation_mode=r.get("allocationMode", "ExactCount"),
            count=r.get("count", 1),
            selectors=r.get("selectors", []),
        )
        for r in spec.get("devices", {}).get("requests", [])
    ]


def device_configs_from_spec(spec: Dict[str, Any]) -> List[DeviceClaimConfig]:
    out = []
    for c in spec.get("devices", {}).get("config", []):
        opaque = c.get("opaque")
        out.append(
            DeviceClaimConfig(
                requests=c.get("requests", []),
                opaque=OpaqueDeviceConfig(
                    driver=opaque.get("driver", ""),
                    parameters=opaque.get("parameters", {}),
                )
                if opaque
                else None,
            )
        )
    return out
