"""Shared JSON/YAML manifest parsing for DRA device specs.

Single decoder for the ``spec.devices.{requests,config}`` shape (and the
RCT ``spec.spec`` unwrap) used by both the kubectl-apply loader
(sim/kubectl.py) and the admission webhook — one place to evolve when the
manifest schema grows.
"""

from __future__ import annotations

from typing import Any, Dict, List

from k8s_dra_driver_tpu.k8s.core import (
    DeviceClaimConfig,
    DeviceRequest,
    OpaqueDeviceConfig,
)


def unwrap_template_spec(spec: Dict[str, Any]) -> Dict[str, Any]:
    """ResourceClaimTemplate nests the claim spec at spec.spec."""
    return spec.get("spec", spec)


def _split_selectors(raw) -> tuple:
    """Discriminate selectors by manifest *shape*, not content: the k8s
    form ``{cel: {expression: ...}}`` is CEL; a plain string is the sim's
    legacy ``attr=value``. Tagging here (instead of sniffing for
    "device." downstream) means a legacy value containing "device." can't
    be misrouted to the CEL evaluator, and a CEL literal like ``true``
    can't be misread as malformed attr=value."""
    legacy: List[str] = []
    cel: List[str] = []
    for s in raw or []:
        if isinstance(s, str):
            legacy.append(s)
        elif isinstance(s, dict):
            expr = ((s.get("cel") or {}).get("expression", ""))
            if expr:
                cel.append(expr)
    return legacy, cel


def device_requests_from_spec(spec: Dict[str, Any]) -> List[DeviceRequest]:
    out = []
    for r in spec.get("devices", {}).get("requests", []):
        # resource.k8s.io/v1 nests the one-of under "exactly"; v1beta1 is
        # flat (reference demo/specs/quickstart/v1/gpu-test1.yaml:10-21).
        inner = r.get("exactly") or r
        legacy, cel = _split_selectors(inner.get("selectors"))
        out.append(DeviceRequest(
            name=r.get("name", "device"),
            device_class_name=inner.get("deviceClassName", ""),
            allocation_mode=inner.get("allocationMode", "ExactCount"),
            count=inner.get("count", 1),
            selectors=legacy,
            cel_selectors=cel,
        ))
    return out


def device_configs_from_spec(spec: Dict[str, Any]) -> List[DeviceClaimConfig]:
    out = []
    for c in spec.get("devices", {}).get("config", []):
        opaque = c.get("opaque")
        out.append(
            DeviceClaimConfig(
                requests=c.get("requests", []),
                opaque=OpaqueDeviceConfig(
                    driver=opaque.get("driver", ""),
                    parameters=opaque.get("parameters", {}),
                )
                if opaque
                else None,
            )
        )
    return out
