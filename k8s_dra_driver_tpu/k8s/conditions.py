"""Typed status conditions (metav1.Condition analog).

The reference surfaces ComputeDomain health through `status.conditions`
entries shaped like metav1.Condition: type/status/reason/message plus a
lastTransitionTime that moves ONLY when the boolean status flips — the
monotonic-transition contract `kubectl describe` and condition-age alerts
rely on. These helpers keep that contract in one place so every writer
(controller, scheduler, kubelet sync) maintains conditions identically.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import List, Optional

CONDITION_TRUE = "True"
CONDITION_FALSE = "False"
CONDITION_UNKNOWN = "Unknown"


@dataclass
class Condition:
    type: str = ""
    status: str = CONDITION_UNKNOWN   # True | False | Unknown
    reason: str = ""
    message: str = ""
    # Moves only on a status flip, never on reason/message refreshes.
    last_transition_time: float = 0.0


def get_condition(conditions: List[Condition], type_: str) -> Optional[Condition]:
    for c in conditions:
        if c.type == type_:
            return c
    return None


def condition_true(conditions: List[Condition], type_: str) -> bool:
    c = get_condition(conditions, type_)
    return c is not None and c.status == CONDITION_TRUE


def set_condition(
    conditions: List[Condition],
    type_: str,
    status: str,
    reason: str = "",
    message: str = "",
    now: Optional[float] = None,
) -> bool:
    """Upsert one condition in place. Returns True when anything changed.
    lastTransitionTime is stamped only when the status actually flips (or
    the condition is new), so a steady condition compares equal across
    reconciles and change-gated status writes stay no-ops."""
    ts = time.time() if now is None else now
    cur = get_condition(conditions, type_)
    if cur is None:
        conditions.append(Condition(
            type=type_, status=status, reason=reason, message=message,
            last_transition_time=ts,
        ))
        return True
    if cur.status == status and cur.reason == reason and cur.message == message:
        return False
    if cur.status != status:
        cur.last_transition_time = ts
    cur.status = status
    cur.reason = reason
    cur.message = message
    return True
