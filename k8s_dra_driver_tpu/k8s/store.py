"""APIServer: namespaced stores with optimistic concurrency and watches.

Semantics kept faithful to the pieces the driver depends on:

- create/update/delete return deep copies; callers never share memory with
  the store (a real API server serializes through the wire).
- update() is CAS on metadata.resourceVersion → ConflictError on mismatch.
  This is what the daemon's clique index allocation relies on
  (/root/reference/cmd/compute-domain-daemon/cdclique.go:350-372).
- delete() on an object with finalizers sets deletionTimestamp and emits
  MODIFIED; the object is only removed once an update drops the last
  finalizer — the controller's finalizer dance (computedomain.go:316-330).
- watch() streams ADDED/MODIFIED/DELETED events from the moment of
  subscription; informers do list+watch.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from k8s_dra_driver_tpu.k8s.objects import (
    AlreadyExistsError,
    ConflictError,
    K8sObject,
    NotFoundError,
    fresh_uid,
    now,
)


@dataclass(frozen=True)
class WatchEvent:
    type: str            # ADDED | MODIFIED | DELETED
    obj: K8sObject


_Key = Tuple[str, str, str]  # (kind, namespace, name)


def _match_labels(obj: K8sObject, selector: Optional[Dict[str, str]]) -> bool:
    if not selector:
        return True
    return all(obj.meta.labels.get(k) == v for k, v in selector.items())


class APIServer:
    def __init__(self) -> None:
        self._mu = threading.RLock()
        self._objects: Dict[_Key, K8sObject] = {}
        self._rv = 0
        # (queue, name-filter, namespace-filter); None filters match all —
        # the field-selector analog so a single-object watcher (e.g. the
        # daemon's own-pod PodManager) doesn't receive cluster-wide churn.
        self._watchers: Dict[
            str, List[Tuple["queue.Queue[WatchEvent]", Optional[str], Optional[str]]]
        ] = {}

    # -- internal ----------------------------------------------------------

    def _next_rv(self) -> int:
        self._rv += 1
        return self._rv

    def _emit(self, kind: str, event: WatchEvent) -> None:
        for q, name, ns in self._watchers.get(kind, []):
            if name is not None and event.obj.meta.name != name:
                continue
            if ns is not None and event.obj.meta.namespace != ns:
                continue
            q.put(event)

    @staticmethod
    def _key(obj: K8sObject) -> _Key:
        return (obj.kind, obj.meta.namespace, obj.meta.name)

    # -- CRUD --------------------------------------------------------------

    def create(self, obj: K8sObject) -> K8sObject:
        if not obj.kind or not obj.meta.name:
            raise ApiValueError("object needs kind and metadata.name")
        with self._mu:
            key = self._key(obj)
            if key in self._objects:
                raise AlreadyExistsError(f"{key} already exists")
            stored = obj.deepcopy()
            stored.meta.uid = stored.meta.uid or fresh_uid()
            stored.meta.resource_version = self._next_rv()
            stored.meta.generation = 1
            stored.meta.creation_timestamp = stored.meta.creation_timestamp or now()
            stored.meta.deletion_timestamp = None
            self._objects[key] = stored
            out = stored.deepcopy()
            self._emit(obj.kind, WatchEvent("ADDED", stored.deepcopy()))
            return out

    def get(self, kind: str, name: str, namespace: str = "") -> K8sObject:
        with self._mu:
            key = (kind, namespace, name)
            try:
                return self._objects[key].deepcopy()
            except KeyError:
                raise NotFoundError(f"{key} not found") from None

    def try_get(self, kind: str, name: str, namespace: str = "") -> Optional[K8sObject]:
        try:
            return self.get(kind, name, namespace)
        except NotFoundError:
            return None

    def kind_fingerprint(self, kind: str) -> tuple:
        """Cheap change-detection token for one kind: (count, max
        resourceVersion). O(objects) with no copying — lets read-mostly
        callers (the allocator's per-pass snapshot) reuse their previous
        deepcopied list when nothing of that kind changed. Any create
        bumps max-rv, any update bumps the object's rv, any delete drops
        the count (and a delete+create in one window bumps max-rv), so
        the token changes whenever the listed set could differ."""
        with self._mu:
            count = 0
            max_rv = 0
            for (k, _, _), obj in self._objects.items():
                if k != kind:
                    continue
                count += 1
                rv = obj.meta.resource_version or 0
                if rv > max_rv:
                    max_rv = rv
            return (count, max_rv)

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[K8sObject]:
        with self._mu:
            out = []
            for (k, ns, _), obj in sorted(self._objects.items()):
                if k != kind:
                    continue
                if namespace is not None and ns != namespace:
                    continue
                if not _match_labels(obj, label_selector):
                    continue
                out.append(obj.deepcopy())
            return out

    def update(self, obj: K8sObject) -> K8sObject:
        """CAS write. The stored object is replaced wholesale; finalizer
        removal on a deleting object completes its deletion."""
        with self._mu:
            key = self._key(obj)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFoundError(f"{key} not found")
            if obj.meta.resource_version != cur.meta.resource_version:
                raise ConflictError(
                    f"{key}: resourceVersion {obj.meta.resource_version} != "
                    f"{cur.meta.resource_version}"
                )
            stored = obj.deepcopy()
            stored.meta.uid = cur.meta.uid
            stored.meta.creation_timestamp = cur.meta.creation_timestamp
            stored.meta.deletion_timestamp = cur.meta.deletion_timestamp
            stored.meta.resource_version = self._next_rv()
            stored.meta.generation = cur.meta.generation + 1
            if stored.meta.deletion_timestamp is not None and not stored.meta.finalizers:
                del self._objects[key]
                self._emit(obj.kind, WatchEvent("DELETED", stored.deepcopy()))
                return stored.deepcopy()
            self._objects[key] = stored
            self._emit(obj.kind, WatchEvent("MODIFIED", stored.deepcopy()))
            return stored.deepcopy()

    def delete(self, kind: str, name: str, namespace: str = "") -> None:
        with self._mu:
            key = (kind, namespace, name)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFoundError(f"{key} not found")
            if cur.meta.finalizers:
                if cur.meta.deletion_timestamp is None:
                    cur.meta.deletion_timestamp = now()
                    cur.meta.resource_version = self._next_rv()
                    self._emit(kind, WatchEvent("MODIFIED", cur.deepcopy()))
                return
            del self._objects[key]
            self._emit(kind, WatchEvent("DELETED", cur.deepcopy()))

    # -- helpers -----------------------------------------------------------

    def update_with_retry(
        self, kind: str, name: str, namespace: str, mutate: Callable[[K8sObject], None],
        attempts: int = 10,
    ) -> K8sObject:
        """Get-mutate-update loop absorbing CAS conflicts."""
        last: Optional[ConflictError] = None
        for _ in range(attempts):
            obj = self.get(kind, name, namespace)
            mutate(obj)
            try:
                return self.update(obj)
            except ConflictError as e:
                last = e
        raise last  # type: ignore[misc]

    def watch(
        self, kind: str, name: Optional[str] = None, namespace: Optional[str] = None
    ) -> "queue.Queue[WatchEvent]":
        with self._mu:
            q: "queue.Queue[WatchEvent]" = queue.Queue()
            self._watchers.setdefault(kind, []).append((q, name, namespace))
            return q

    def stop_watch(self, kind: str, q: "queue.Queue[WatchEvent]") -> None:
        with self._mu:
            entries = self._watchers.get(kind, [])
            self._watchers[kind] = [e for e in entries if e[0] is not q]

    def list_and_watch(
        self, kind: str, name: Optional[str] = None, namespace: Optional[str] = None
    ) -> Tuple[List[K8sObject], "queue.Queue[WatchEvent]"]:
        """Atomic snapshot + subscription — informer bootstrap."""
        with self._mu:
            q = self.watch(kind, name, namespace)
            objs = self.list(kind, namespace=namespace)
            if name is not None:
                objs = [o for o in objs if o.meta.name == name]
            return objs, q

    # -- garbage collection -------------------------------------------------

    def collect_orphans(self, kinds: Iterable[str]) -> int:
        """One GC pass: delete objects whose controller owner is gone —
        the cluster-side behavior the reference's CleanupManager compensates
        for when owner refs can't be used (cleanup.go:35-146)."""
        doomed: List[K8sObject] = []
        with self._mu:
            uids = {o.meta.uid for o in self._objects.values()}
            for (k, _, _), obj in list(self._objects.items()):
                if k not in kinds:
                    continue
                for ref in obj.meta.owner_references:
                    if ref.controller and ref.uid not in uids:
                        doomed.append(obj)
                        break
        for obj in doomed:
            try:
                self.delete(obj.kind, obj.meta.name, obj.meta.namespace)
            except NotFoundError:
                pass
        return len(doomed)


class ApiValueError(ValueError):
    pass
