"""APIServer: namespaced stores with optimistic concurrency and watches.

Semantics kept faithful to the pieces the driver depends on:

- create/update/delete return deep copies; callers never share memory with
  the store (a real API server serializes through the wire).
- update() is CAS on metadata.resourceVersion → ConflictError on mismatch.
  This is what the daemon's clique index allocation relies on
  (/root/reference/cmd/compute-domain-daemon/cdclique.go:350-372).
- delete() on an object with finalizers sets deletionTimestamp and emits
  MODIFIED; the object is only removed once an update drops the last
  finalizer — the controller's finalizer dance (computedomain.go:316-330).
- watch() streams ADDED/MODIFIED/DELETED events from the moment of
  subscription; informers do list+watch.

Listing is index-backed: objects are bucketed per kind and per
(kind, namespace) on every write, so ``list(kind)`` touches only objects of
that kind (and ``list(kind, namespace=ns)`` only that namespace's) instead
of scanning and re-sorting the whole store — etcd's range-read over a key
prefix rather than a full keyspace scan. ``kind_fingerprint`` is an O(1)
counter lookup maintained on the same writes. ``stats`` counts what each
list actually touched (and what a pre-index full scan would have), so the
scheduler bench can report the delta.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from k8s_dra_driver_tpu.k8s.objects import (
    AlreadyExistsError,
    ConflictError,
    K8sObject,
    NotFoundError,
    fresh_uid,
    now,
)


@dataclass(frozen=True)
class WatchEvent:
    type: str            # ADDED | MODIFIED | DELETED
    obj: K8sObject


# Per-watcher event-queue bound. A stalled watcher (a consumer that stopped
# draining) must not grow memory without limit: when its queue is full the
# OLDEST event is dropped to admit the new one — the newest state always
# arrives, and informer-style consumers relist on resync anyway. Drops are
# counted (StoreStats.watch_events_dropped / tpu_dra_watch_dropped_total).
WATCH_QUEUE_MAXSIZE = 1024


@dataclass
class StoreStats:
    """Read-path accounting (plain ints, no locking beyond the store's):
    ``objects_scanned`` is what the per-kind/namespace indexes actually
    iterated; ``objects_scanned_naive`` is what the pre-index
    whole-store sort-and-filter would have touched for the same calls —
    the pair the scheduler bench reports as the index win."""

    list_calls: int = 0
    objects_scanned: int = 0
    objects_scanned_naive: int = 0
    objects_returned: int = 0
    watch_events_dropped: int = 0

    def snapshot(self) -> Dict[str, int]:
        return {
            "list_calls": self.list_calls,
            "objects_scanned": self.objects_scanned,
            "objects_scanned_naive": self.objects_scanned_naive,
            "objects_returned": self.objects_returned,
            "watch_events_dropped": self.watch_events_dropped,
        }


_Key = Tuple[str, str, str]  # (kind, namespace, name)


def _match_labels(obj: K8sObject, selector: Optional[Dict[str, str]]) -> bool:
    if not selector:
        return True
    return all(obj.meta.labels.get(k) == v for k, v in selector.items())


class APIServer:
    def __init__(self) -> None:
        self._mu = threading.RLock()
        self._objects: Dict[_Key, K8sObject] = {}  # tpulint: guarded-by=_mu
        # Secondary indexes, maintained on every write: kind -> {key -> obj}
        # and (kind, namespace) -> {key -> obj}. Values are the SAME stored
        # objects (no copies); list() deepcopies on the way out as before.
        self._by_kind: Dict[str, Dict[_Key, K8sObject]] = {}  # tpulint: guarded-by=_mu
        self._by_kind_ns: Dict[Tuple[str, str], Dict[_Key, K8sObject]] = {}  # tpulint: guarded-by=_mu
        # kind -> (live count, last resourceVersion stamped on this kind).
        # O(1) to read and to maintain; see kind_fingerprint().
        self._fp: Dict[str, Tuple[int, int]] = {}  # tpulint: guarded-by=_mu
        self._rv = 0
        self.stats = StoreStats()
        self._metrics = None  # set by attach_metrics()
        # (queue, name-filter, namespace-filter); None filters match all —
        # the field-selector analog so a single-object watcher (e.g. the
        # daemon's own-pod PodManager) doesn't receive cluster-wide churn.
        self._watchers: Dict[  # tpulint: guarded-by=_mu
            str, List[Tuple["queue.Queue[WatchEvent]", Optional[str], Optional[str]]]
        ] = {}

    # -- internal ----------------------------------------------------------

    def _next_rv(self) -> int:
        self._rv += 1
        return self._rv

    def _emit(self, kind: str, event: WatchEvent) -> None:
        for q, name, ns in self._watchers.get(kind, []):
            if name is not None and event.obj.meta.name != name:
                continue
            if ns is not None and event.obj.meta.namespace != ns:
                continue
            try:
                q.put_nowait(event)
                continue
            except queue.Full:
                pass
            # Stalled watcher: evict the oldest queued event so the queue
            # stays bounded and the newest state still arrives. Count
            # exactly the events actually lost — an eviction, plus the new
            # event itself if a racing producer refilled the freed slot.
            lost = 0
            try:
                q.get_nowait()
                lost += 1
            except queue.Empty:
                pass  # consumer drained meanwhile: nothing was dropped
            try:
                q.put_nowait(event)
            except queue.Full:  # pragma: no cover — racing producer refilled
                lost += 1
            if lost:
                self.stats.watch_events_dropped += lost
                if self._metrics is not None:
                    self._metrics["watch_dropped"].inc(kind, by=float(lost))

    @staticmethod
    def _key(obj: K8sObject) -> _Key:
        return (obj.kind, obj.meta.namespace, obj.meta.name)

    def _index_add(self, key: _Key, obj: K8sObject) -> None:
        # tpulint: holds=_mu (write-path internal; every caller locks)
        self._objects[key] = obj
        self._by_kind.setdefault(key[0], {})[key] = obj
        self._by_kind_ns.setdefault((key[0], key[1]), {})[key] = obj

    def _index_drop(self, key: _Key) -> None:
        # tpulint: holds=_mu (write-path internal; every caller locks)
        del self._objects[key]
        self._by_kind[key[0]].pop(key, None)
        self._by_kind_ns[(key[0], key[1])].pop(key, None)

    def _fp_mutate(self, kind: str, delta: int, rv: Optional[int] = None) -> None:
        # tpulint: holds=_mu (write-path internal; every caller locks)
        """Maintain the fingerprint counters on one mutation. ``rv`` is the
        resourceVersion just stamped (None for plain removals, which consume
        no rv). Token uniqueness: the rv component is monotone and strictly
        increases on every stamp; between two tokens with the same rv only
        removals happened, so the count strictly decreases — no (count, rv)
        pair can ever repeat within one kind's history."""
        count, last = self._fp.get(kind, (0, 0))
        self._fp[kind] = (count + delta, last if rv is None else rv)
        if self._metrics is not None and delta:
            self._metrics["objects"].set(kind, value=float(count + delta))

    # -- CRUD --------------------------------------------------------------

    def create(self, obj: K8sObject) -> K8sObject:
        if not obj.kind or not obj.meta.name:
            raise ApiValueError("object needs kind and metadata.name")
        with self._mu:
            key = self._key(obj)
            if key in self._objects:
                raise AlreadyExistsError(f"{key} already exists")
            stored = obj.deepcopy()
            stored.meta.uid = stored.meta.uid or fresh_uid()
            stored.meta.resource_version = self._next_rv()
            stored.meta.generation = 1
            stored.meta.creation_timestamp = stored.meta.creation_timestamp or now()
            stored.meta.deletion_timestamp = None
            self._index_add(key, stored)
            self._fp_mutate(obj.kind, +1, stored.meta.resource_version)
            out = stored.deepcopy()
            self._emit(obj.kind, WatchEvent("ADDED", stored.deepcopy()))
            return out

    def get(self, kind: str, name: str, namespace: str = "") -> K8sObject:
        with self._mu:
            key = (kind, namespace, name)
            try:
                return self._objects[key].deepcopy()
            except KeyError:
                raise NotFoundError(f"{key} not found") from None

    def try_get(self, kind: str, name: str, namespace: str = "") -> Optional[K8sObject]:
        try:
            return self.get(kind, name, namespace)
        except NotFoundError:
            return None

    def kind_fingerprint(self, kind: str) -> tuple:
        """Cheap change-detection token for one kind: (live count, last
        resourceVersion stamped on the kind). O(1) — maintained by the
        write paths instead of scanned — so read-mostly callers (the
        allocator's per-pass snapshot, the sim's quiescence detection) can
        poll it every pass for free. Any create/update bumps the rv
        component, any removal drops the count, so the token changes
        whenever the listed set could differ and never repeats."""
        with self._mu:
            return self._fp.get(kind, (0, 0))

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[K8sObject]:
        with self._mu:
            if namespace is None:
                bucket = self._by_kind.get(kind, {})
            else:
                bucket = self._by_kind_ns.get((kind, namespace), {})
            self.stats.list_calls += 1
            self.stats.objects_scanned += len(bucket)
            self.stats.objects_scanned_naive += len(self._objects)
            out = []
            for key in sorted(bucket):
                obj = bucket[key]
                if not _match_labels(obj, label_selector):
                    continue
                out.append(obj.deepcopy())
            self.stats.objects_returned += len(out)
            if self._metrics is not None:
                self._metrics["list_total"].inc()
                self._metrics["scanned_total"].inc(by=float(len(bucket)))
                self._metrics["returned_total"].inc(by=float(len(out)))
            return out

    def update(self, obj: K8sObject) -> K8sObject:
        """CAS write. The stored object is replaced wholesale; finalizer
        removal on a deleting object completes its deletion."""
        with self._mu:
            key = self._key(obj)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFoundError(f"{key} not found")
            if obj.meta.resource_version != cur.meta.resource_version:
                raise ConflictError(
                    f"{key}: resourceVersion {obj.meta.resource_version} != "
                    f"{cur.meta.resource_version}"
                )
            stored = obj.deepcopy()
            stored.meta.uid = cur.meta.uid
            stored.meta.creation_timestamp = cur.meta.creation_timestamp
            stored.meta.deletion_timestamp = cur.meta.deletion_timestamp
            stored.meta.resource_version = self._next_rv()
            stored.meta.generation = cur.meta.generation + 1
            if stored.meta.deletion_timestamp is not None and not stored.meta.finalizers:
                self._index_drop(key)
                self._fp_mutate(obj.kind, -1, stored.meta.resource_version)
                self._emit(obj.kind, WatchEvent("DELETED", stored.deepcopy()))
                return stored.deepcopy()
            self._index_add(key, stored)
            self._fp_mutate(obj.kind, 0, stored.meta.resource_version)
            self._emit(obj.kind, WatchEvent("MODIFIED", stored.deepcopy()))
            return stored.deepcopy()

    def delete(self, kind: str, name: str, namespace: str = "") -> None:
        with self._mu:
            key = (kind, namespace, name)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFoundError(f"{key} not found")
            if cur.meta.finalizers:
                if cur.meta.deletion_timestamp is None:
                    cur.meta.deletion_timestamp = now()
                    cur.meta.resource_version = self._next_rv()
                    self._fp_mutate(kind, 0, cur.meta.resource_version)
                    self._emit(kind, WatchEvent("MODIFIED", cur.deepcopy()))
                return
            self._index_drop(key)
            self._fp_mutate(kind, -1)
            self._emit(kind, WatchEvent("DELETED", cur.deepcopy()))

    # -- helpers -----------------------------------------------------------

    def attach_metrics(self, registry) -> None:
        """Expose the store's read/size accounting on a tpu_dra_* registry
        (the sim wires its cluster-shared registry here). Idempotent per
        registry; re-attaching to a different registry re-registers."""
        from k8s_dra_driver_tpu.pkg.metrics import Counter, Gauge

        with self._mu:
            self._metrics = {
                "list_total": registry.register(Counter(
                    "tpu_dra_store_list_requests_total",
                    "list() calls served by the API store.")),
                "scanned_total": registry.register(Counter(
                    "tpu_dra_store_list_objects_scanned_total",
                    "Objects the per-kind/namespace indexes iterated "
                    "across all list() calls.")),
                "returned_total": registry.register(Counter(
                    "tpu_dra_store_list_objects_returned_total",
                    "Objects deepcopied out of list() calls.")),
                "objects": registry.register(Gauge(
                    "tpu_dra_store_objects",
                    "Objects currently stored, by kind.",
                    label_names=("kind",))),
                "watch_dropped": registry.register(Counter(
                    "tpu_dra_watch_dropped_total",
                    "Watch events dropped (oldest-first) because a "
                    "watcher's bounded queue was full.",
                    label_names=("kind",))),
            }
            for kind, (count, _) in self._fp.items():
                self._metrics["objects"].set(kind, value=float(count))

    def update_with_retry(
        self, kind: str, name: str, namespace: str, mutate: Callable[[K8sObject], None],
        attempts: int = 10,
    ) -> K8sObject:
        """Get-mutate-update loop absorbing CAS conflicts."""
        last: Optional[ConflictError] = None
        for _ in range(attempts):
            obj = self.get(kind, name, namespace)
            mutate(obj)
            try:
                return self.update(obj)
            except ConflictError as e:
                last = e
        raise last  # type: ignore[misc]

    def watch(
        self, kind: str, name: Optional[str] = None, namespace: Optional[str] = None,
        maxsize: int = WATCH_QUEUE_MAXSIZE,
    ) -> "queue.Queue[WatchEvent]":
        with self._mu:
            q: "queue.Queue[WatchEvent]" = queue.Queue(maxsize=maxsize)
            self._watchers.setdefault(kind, []).append((q, name, namespace))
            return q

    def stop_watch(self, kind: str, q: "queue.Queue[WatchEvent]") -> None:
        with self._mu:
            entries = self._watchers.get(kind, [])
            self._watchers[kind] = [e for e in entries if e[0] is not q]

    def list_and_watch(
        self, kind: str, name: Optional[str] = None, namespace: Optional[str] = None,
        maxsize: int = WATCH_QUEUE_MAXSIZE,
    ) -> Tuple[List[K8sObject], "queue.Queue[WatchEvent]"]:
        """Atomic snapshot + subscription — informer bootstrap."""
        with self._mu:
            q = self.watch(kind, name, namespace, maxsize=maxsize)
            objs = self.list(kind, namespace=namespace)
            if name is not None:
                objs = [o for o in objs if o.meta.name == name]
            return objs, q

    # -- garbage collection -------------------------------------------------

    def collect_orphans(self, kinds: Iterable[str]) -> int:
        """One GC pass: delete objects whose controller owner is gone —
        the cluster-side behavior the reference's CleanupManager compensates
        for when owner refs can't be used (cleanup.go:35-146)."""
        doomed: List[K8sObject] = []
        with self._mu:
            uids = {o.meta.uid for o in self._objects.values()}
            for kind in kinds:
                for obj in list(self._by_kind.get(kind, {}).values()):
                    for ref in obj.meta.owner_references:
                        if ref.controller and ref.uid not in uids:
                            doomed.append(obj)
                            break
        for obj in doomed:
            try:
                self.delete(obj.kind, obj.meta.name, obj.meta.namespace)
            except NotFoundError:
                pass
        return len(doomed)


class ApiValueError(ValueError):
    pass
