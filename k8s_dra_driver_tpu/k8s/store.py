"""APIServer: namespaced stores with optimistic concurrency and watches.

Semantics kept faithful to the pieces the driver depends on:

- Stored objects are **immutable published snapshots**: every write
  freezes the object graph at publish time (k8s.objects.freeze), so
  get()/list()/watch fan-out hand out *references* — zero copies on the
  read path. Mutating a handed-out snapshot raises FrozenSnapshotError;
  the isolation a real API server gets from serializing through the wire
  is enforced by the seal instead of bought with a deepcopy per read.
  ``copy=True`` on get/list is the explicit opt-out for callers that
  want a private mutable copy.
- update() is CAS on metadata.resourceVersion → ConflictError on mismatch.
  This is what the daemon's clique index allocation relies on
  (/root/reference/cmd/compute-domain-daemon/cdclique.go:350-372).
- delete() on an object with finalizers sets deletionTimestamp and emits
  MODIFIED; the object is only removed once an update drops the last
  finalizer — the controller's finalizer dance (computedomain.go:316-330).
- watch() streams ADDED/MODIFIED/DELETED events from the moment of
  subscription; informers do list+watch.

Listing is index-backed: objects are bucketed per kind and per
(kind, namespace) on every write, so ``list(kind)`` touches only objects of
that kind (and ``list(kind, namespace=ns)`` only that namespace's) instead
of scanning and re-sorting the whole store — etcd's range-read over a key
prefix rather than a full keyspace scan. ``kind_fingerprint`` is an O(1)
counter lookup maintained on the same writes.

Scale-out layout (the 8192-node control-plane work):

- **Sharded locking.** The store is hash-partitioned into per-kind shard
  buckets (``_Shard``), each holding its own ``_objects``/``_by_kind``/
  ``_by_kind_ns``/``_fp`` slice under its own lock. A kind lives entirely
  in one shard (crc32(kind) % shards), so writers to ResourceClaims stop
  serializing behind Pod status churn while every single-kind operation
  keeps exactly one lock acquisition. ``shards=1`` degrades to the old
  single-global-lock behavior and is kept as the bench baseline flag.
  resourceVersion allocation is a lock-free atomic counter
  (``itertools.count``; ``__next__`` is a single C call under the GIL),
  shared by every shard so rv stays globally monotone.
- **Off-lock batched watch fan-out.** Writers never deliver watch events
  while holding a shard lock: the write path enqueues the event (plus its
  WAL record, when persistence is attached) onto a per-store dispatch
  ring inside the shard lock and delivers after releasing it. One thread
  at a time drains the ring (the first enqueuer becomes the dispatcher —
  single-threaded callers still observe synchronous delivery), coalescing
  bursts into per-watcher batches: the watcher registry is consulted once
  per batch per kind, and each event carries ONE shared immutable
  deepcopy handed to every subscriber. Per-kind ordering is preserved
  (same-kind writes serialize on the shard lock, and ring order is
  delivery order); the bounded-queue oldest-drop accounting stays exact
  because only the active dispatcher ever touches the queues' put side.
  Subscription watermarks (the ring sequence at watch() time) keep
  ``list_and_watch`` atomic: events enqueued before the snapshot are
  already in the listing and are skipped for that subscriber.

Multi-shard reads (orphan GC, persistence snapshots) go through ONE
canonical ordered-acquire helper (``_locked_all``) — pinned by the
tpulint ``shard-lock`` rule so no other code path can ever hold two shard
locks and deadlock against it.

Zero-copy write path (the 16k/32k-node work):

- **One copy per write.** create/update deepcopy the caller's object
  once (the defensive copy-in — callers keep mutable ownership of what
  they passed), stamp it, freeze it, and that single frozen snapshot IS
  the stored object, the returned object, the watch ``shared`` copy and
  the WAL record's source — the pre-freeze path's three copies per
  write collapse to one. ``update_with_retry`` is the copy-on-write
  seam: the mutator receives a thawed working copy of the current
  snapshot and commit freezes it back (``_owned`` skips even the
  copy-in — the working copy is already private).
- **Structural sharing across revisions.** Before freezing, commit
  compares each top-level field (and the metadata's label/annotation
  containers) against the prior revision and adopts the prior's frozen
  sub-object when equal — a status-only update shares spec/metadata
  sub-objects with the previous revision by identity, so the freeze
  walk short-circuits and the per-snapshot wire-encoding cache
  (k8s.serialize.wire_json) is the only serialization the WAL and
  compaction ever pay per revision.
- **copy_reads=True** is the copy-always A/B baseline for bench_scale:
  reads deepcopy on the way out and every watch event is staged as a
  fresh copy — the pre-zero-copy cost model, flag-gated.
"""

from __future__ import annotations

import itertools
import queue
import threading
import zlib
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Tuple

import copy as _copy

from k8s_dra_driver_tpu.k8s.objects import (
    AlreadyExistsError,
    ApiError,
    ConflictError,
    K8sObject,
    NotFoundError,
    freeze,
    fresh_uid,
    now,
    thaw,
)


class ReadOnlyStoreError(ApiError):
    """Raised by the mutating verbs of a store serving as a read replica
    (federation/replication.py): clients must route writes to the leader.
    ``apply_replicated`` — the replication stream's install path — is the
    only sanctioned mutation until ``read_only`` is cleared (failover)."""


@dataclass(frozen=True)
class WatchEvent:
    type: str            # ADDED | MODIFIED | DELETED
    obj: K8sObject


# Per-watcher event-queue bound. A stalled watcher (a consumer that stopped
# draining) must not grow memory without limit: when its queue is full the
# OLDEST event is dropped to admit the new one — the newest state always
# arrives, and informer-style consumers relist on resync anyway. Drops are
# counted (StoreStats.watch_events_dropped / tpu_dra_watch_dropped_total).
WATCH_QUEUE_MAXSIZE = 1024

# Default shard count: per-kind hash partitioning over this many locks.
# The driver's kinds (Pod, ResourceClaim, ResourceSlice, Node, Event,
# ComputeDomain, DaemonSet, Lease, ...) spread across them so concurrent
# writers of different kinds never contend while kinds <= shards.
DEFAULT_STORE_SHARDS = 16

# Max events one dispatcher drain takes from the ring per iteration: the
# fan-out amortization unit (one watcher-registry consult per kind per
# batch) and the bound on how long one unlucky writer plays dispatcher
# before re-checking for an empty ring.
WATCH_DISPATCH_BATCH = 256


@dataclass
class StoreStats:
    """Read-path accounting (plain ints). ``watch_events_dropped`` is
    EXACT under any concurrency: only the single active dispatcher writes
    it. The list-path counters are written under the listed kind's shard
    lock — exact for single-threaded use and for concurrent lists of
    kinds sharing a shard; concurrent lists across shards may lose
    increments (they feed trend lines, not invariants).
    ``objects_scanned`` is what the
    per-kind/namespace indexes actually iterated; ``objects_scanned_naive``
    is what the pre-index whole-store sort-and-filter would have touched
    for the same calls — the pair the scheduler bench reports as the
    index win."""

    list_calls: int = 0
    objects_scanned: int = 0
    objects_scanned_naive: int = 0
    objects_returned: int = 0
    watch_events_dropped: int = 0
    # Zero-copy accounting: ``copies_avoided`` counts read-path handouts
    # served as references (get/list objects + watch events staged without
    # a shared copy); ``read_copies`` counts deepcopies actually performed
    # on the read path (``copy=True`` opt-outs, or every handout in the
    # flag-gated copy-always baseline) — the bench's ZERO-read-copy
    # settle gate reads it; ``write_copies`` counts the write path's
    # defensive copy-ins (one per create/update, plus each
    # update_with_retry working copy).
    copies_avoided: int = 0
    read_copies: int = 0
    write_copies: int = 0

    def snapshot(self) -> Dict[str, int]:
        return {
            "list_calls": self.list_calls,
            "objects_scanned": self.objects_scanned,
            "objects_scanned_naive": self.objects_scanned_naive,
            "objects_returned": self.objects_returned,
            "watch_events_dropped": self.watch_events_dropped,
            "copies_avoided": self.copies_avoided,
            "read_copies": self.read_copies,
            "write_copies": self.write_copies,
        }


_Key = Tuple[str, str, str]  # (kind, namespace, name)


def _match_labels(obj: K8sObject, selector: Optional[Dict[str, str]]) -> bool:
    if not selector:
        return True
    return all(obj.meta.labels.get(k) == v for k, v in selector.items())


class _Shard:
    """One lock domain of the partitioned store. Every kind maps to
    exactly one shard; all four index structures for that kind live here
    and mutate only under ``mu`` (enforced by tpulint shard-lock)."""

    __slots__ = ("mu", "idx", "objects", "by_kind", "by_kind_ns", "fp")

    def __init__(self, idx: int = 0) -> None:
        self.mu = threading.RLock()
        self.idx = idx
        self.objects: Dict[_Key, K8sObject] = {}  # tpulint: guarded-by=mu
        # Secondary indexes, maintained on every write: kind -> {key -> obj}
        # and (kind, namespace) -> {key -> obj}. Values are the SAME stored
        # objects (no copies); list() deepcopies on the way out as before.
        self.by_kind: Dict[str, Dict[_Key, K8sObject]] = {}  # tpulint: guarded-by=mu
        self.by_kind_ns: Dict[Tuple[str, str], Dict[_Key, K8sObject]] = {}  # tpulint: guarded-by=mu
        # kind -> (live count, last resourceVersion stamped on this kind).
        # O(1) to read and to maintain; see kind_fingerprint().
        self.fp: Dict[str, Tuple[int, int]] = {}  # tpulint: guarded-by=mu


class APIServer:
    def __init__(self, shards: int = DEFAULT_STORE_SHARDS,
                 batch_fanout: bool = True,
                 copy_reads: bool = False) -> None:
        """``shards=1`` is the single-lock baseline (every kind behind one
        lock — the pre-scale-out behavior, kept for the bench_scale A/B);
        ``batch_fanout=False`` keeps delivery off-lock but dispatches one
        event at a time (the non-batched fallback path);
        ``copy_reads=True`` is the copy-always baseline — reads deepcopy
        on the way out and watch events are staged as fresh copies, the
        pre-zero-copy cost model kept for the bench_scale A/B."""
        if shards < 1:
            raise ApiValueError(f"shards must be >= 1, got {shards}")
        self._copy_reads = copy_reads
        self._shards: List[_Shard] = [_Shard(i) for i in range(shards)]
        # Sticky kind -> shard assignments (see _shard): reads are
        # lock-free dict lookups; assignment serializes on its own lock.
        self._shard_assign_mu = threading.Lock()
        self._shard_map: Dict[str, _Shard] = {}  # tpulint: guarded-by=_shard_assign_mu
        # Lock-free global resourceVersion: itertools.count.__next__ is a
        # single C-level call, atomic under the GIL — no shard ever
        # serializes on rv allocation.
        self._rv_counter = itertools.count(1)
        self.stats = StoreStats()
        self._metrics = None  # set by attach_metrics()
        # -- watch plane (off-lock dispatch) --------------------------------
        # (queue, name-filter, namespace-filter, min_seq); None filters
        # match all — the field-selector analog so a single-object watcher
        # (e.g. the daemon's own-pod PodManager) doesn't receive
        # cluster-wide churn. min_seq: ring sequence at subscription; ring
        # entries at or below it predate the subscription (and, for
        # list_and_watch, are already reflected in the returned listing).
        self._watch_mu = threading.Lock()
        self._watchers: Dict[  # tpulint: guarded-by=_watch_mu
            str, List[Tuple["queue.Queue[WatchEvent]", Optional[str],
                            Optional[str], int]]
        ] = {}
        # Dispatch ring: (seq, kind, WatchEvent, wal_record|None), appended
        # inside the writing shard's lock (per-kind order = write order),
        # drained outside every shard lock by one dispatcher at a time.
        self._ring_mu = threading.Lock()
        self._ring: List[tuple] = []  # tpulint: guarded-by=_ring_mu
        self._ring_seq = 0  # tpulint: guarded-by=_ring_mu
        self._dispatching = False  # tpulint: guarded-by=_ring_mu
        self._batch_fanout = batch_fanout
        self._wal = None  # set by attach_wal()
        # Read-replica mode (federation): the mutating verbs refuse with
        # ReadOnlyStoreError while the replication stream installs state
        # through apply_replicated. Cleared by failover promotion.
        self.read_only = False

    # -- internal ----------------------------------------------------------

    def _shard(self, kind: str) -> _Shard:
        """Kind -> shard. Hash-partitioned (crc32 picks the preferred
        slot) with linear probing to the first shard no OTHER kind owns
        yet, so distinct kinds get distinct locks until the shard count
        is exhausted — 8 hot kinds over 16 shards never share (plain
        crc32%16 would collide half of them). The assignment is sticky
        for the store's lifetime; the hot-path read is one GIL-atomic
        dict lookup."""
        s = self._shard_map.get(kind)
        if s is not None:
            return s
        with self._shard_assign_mu:
            s = self._shard_map.get(kind)
            if s is None:
                n = len(self._shards)
                start = zlib.crc32(kind.encode()) % n
                taken = {shard.idx for shard in self._shard_map.values()}
                for off in range(n):
                    idx = (start + off) % n
                    if idx not in taken:
                        break
                else:
                    idx = start
                s = self._shards[idx]
                self._shard_map[kind] = s
        return s

    def _locked_all(self):
        """The canonical ordered multi-shard acquire (shard-lock rule): the
        ONLY way any code path may hold more than one shard lock. Acquires
        in shard-index order, releases in reverse — a consistent
        whole-store view for orphan GC and persistence snapshots."""
        return _AllShardsLocked(self._shards)

    def _next_rv(self) -> int:
        return next(self._rv_counter)

    def _check_writable(self) -> None:
        if self.read_only:
            raise ReadOnlyStoreError(
                "store is a read replica: route writes to the leader "
                "cluster (or promote this replica first)")

    def _enqueue(self, kind: str, event: WatchEvent, wal_rec=None) -> int:
        # tpulint: holds=mu (write-path internal; every caller holds the
        # writing shard's lock so ring order is per-kind write order)
        with self._ring_mu:
            self._ring_seq += 1
            self._ring.append((self._ring_seq, kind, event, wal_rec))
            return self._ring_seq

    def _dispatch(self) -> None:
        """Drain the ring and deliver, outside every shard lock. Exactly
        one thread dispatches at a time (the ``_dispatching`` flag): the
        first writer to find the ring busy just leaves its events behind
        and returns — the active dispatcher's drain loop picks them up.
        Single-threaded callers therefore always observe their own events
        delivered before the write call returns."""
        batch_max = WATCH_DISPATCH_BATCH if self._batch_fanout else 1
        with self._ring_mu:
            if self._dispatching or not self._ring:
                return
            self._dispatching = True
        while True:
            with self._ring_mu:
                batch = self._ring[:batch_max]
                del self._ring[:len(batch)]
                if not batch:
                    # Retire the dispatcher role ATOMICALLY with the
                    # empty check: a writer that enqueued after this
                    # check will find _dispatching already False and
                    # drain its own event — done in two steps, its
                    # event would strand in the ring until an
                    # unrelated later write (lost-wakeup race).
                    self._dispatching = False
                    return
            try:
                self._deliver(batch)
                if self._wal is not None:
                    recs = [(seq, *rec) for seq, _, _, rec in batch
                            if rec is not None]
                    if recs:
                        self._wal.append(recs)
                    # Durable-mode records are flushed on the write path,
                    # but compaction still runs here — off every lock.
                    self._wal.maybe_compact(self)
            except BaseException:
                # Delivery or WAL append blew up (disk full, broken
                # metric): put the batch BACK at the front — order
                # preserved — and retire the role so a later write (or
                # flush_watchers) retries. Semantics are at-least-once:
                # a failure after partial effects re-delivers rather
                # than silently losing events or acknowledged WAL
                # records (records are idempotent per-key upserts, so a
                # duplicate append is harmless on replay).
                with self._ring_mu:
                    self._ring[:0] = batch
                    self._dispatching = False
                raise

    def flush_watchers(self) -> None:
        """Run the dispatch loop if events are pending — any thread may
        call this to become the dispatcher (the sim kicks it at the top of
        every event drain so no event can sit in the ring across a step
        while the thread that wrote it is descheduled)."""
        self._dispatch()

    def watch_backlog(self) -> int:
        """Events staged in the ring plus events delivered to subscriber
        queues but not yet consumed. Nonzero means some subscriber's
        cached view (an informer, a pass queue) still lags the store —
        the sim's quiescence detection must treat that as pending work,
        because a consumer thread that merely hasn't been scheduled yet
        can flip cluster state the moment it runs. (Zero-copy fan-out
        made writes fast enough to finish whole settle loops before the
        OS schedules a single informer thread; 'no API writes for two
        steps' alone no longer implies nothing more can happen.)"""
        with self._ring_mu:
            total = len(self._ring)
        with self._watch_mu:
            for watchers in self._watchers.values():
                for q, _, _, _ in watchers:
                    total += q.qsize()
        return total

    def _deliver(self, batch: List[tuple]) -> None:
        """Fan one ring batch out to the watchers: group by kind so the
        registry is consulted once per kind per batch (not per event),
        then put each matching event with the bounded-queue oldest-drop
        accounting. Only the active dispatcher runs this, so the exact
        drop counts can't race. Each kind's delivery loop runs UNDER
        ``_watch_mu`` (puts are non-blocking, so the hold is bounded):
        ``stop_watch`` serializes against in-flight delivery, which is
        what guarantees a closed subscription never receives another
        event — and never has phantom drops counted against it. The
        pre-fix shape (copy the list, put outside the lock) delivered
        into queues whose watchers had already unsubscribed mid-batch."""
        by_kind: Dict[str, List[tuple]] = {}
        for entry in batch:
            by_kind.setdefault(entry[1], []).append(entry)
        metrics = self._metrics
        for kind, entries in by_kind.items():
            with self._watch_mu:
                watchers = self._watchers.get(kind, ())
                if not watchers:
                    continue
                self._deliver_kind_locked(kind, entries, watchers, metrics)

    def _deliver_kind_locked(self, kind: str, entries: List[tuple],
                             watchers, metrics) -> None:
        # tpulint: holds=_watch_mu (delivery vs stop_watch serialization)
        for q, name, ns, min_seq in watchers:
            lost = 0
            for seq, _, event, _ in entries:
                if seq <= min_seq:
                    continue  # predates this subscription's snapshot
                if name is not None and event.obj.meta.name != name:
                    continue
                if ns is not None and event.obj.meta.namespace != ns:
                    continue
                try:
                    q.put_nowait(event)
                    continue
                except queue.Full:
                    pass
                # Stalled watcher: evict the oldest queued event so the
                # queue stays bounded and the newest state still
                # arrives. Count exactly the events actually lost — an
                # eviction, plus the new event itself if the freed slot
                # vanished again (defensive; no other producer exists).
                try:
                    q.get_nowait()
                    lost += 1
                except queue.Empty:
                    pass  # consumer drained meanwhile: nothing dropped
                try:
                    q.put_nowait(event)
                except queue.Full:  # pragma: no cover — no racing producer
                    lost += 1
            if lost:
                self.stats.watch_events_dropped += lost
                if metrics is not None:
                    metrics["watch_dropped"].inc(kind, by=float(lost))
        if metrics is not None:
            metrics["watch_batches"].inc(kind)
            metrics["watch_batch_events"].inc(kind, by=float(len(entries)))

    @staticmethod
    def _key(obj: K8sObject) -> _Key:
        return (obj.kind, obj.meta.namespace, obj.meta.name)

    @staticmethod
    def _index_add(shard: _Shard, key: _Key, obj: K8sObject) -> None:
        # tpulint: holds=mu (write-path internal; every caller locks)
        shard.objects[key] = obj
        shard.by_kind.setdefault(key[0], {})[key] = obj
        shard.by_kind_ns.setdefault((key[0], key[1]), {})[key] = obj

    @staticmethod
    def _index_drop(shard: _Shard, key: _Key) -> None:
        # tpulint: holds=mu (write-path internal; every caller locks)
        del shard.objects[key]
        shard.by_kind[key[0]].pop(key, None)
        shard.by_kind_ns[(key[0], key[1])].pop(key, None)

    def _fp_mutate(self, shard: _Shard, kind: str, delta: int,
                   rv: Optional[int] = None) -> Tuple[int, int]:
        # tpulint: holds=mu (write-path internal; every caller locks)
        """Maintain the fingerprint counters on one mutation. ``rv`` is the
        resourceVersion just stamped (None for plain removals, which consume
        no rv). Token uniqueness: the rv component is monotone and strictly
        increases on every stamp; between two tokens with the same rv only
        removals happened, so the count strictly decreases — no (count, rv)
        pair can ever repeat within one kind's history. Returns the new
        token (the WAL records it so replay restores identical tokens)."""
        count, last = shard.fp.get(kind, (0, 0))
        token = (count + delta, last if rv is None else rv)
        shard.fp[kind] = token
        if self._metrics is not None:
            if delta:
                self._metrics["objects"].set(kind, value=float(token[0]))
            self._metrics["shard_writes"].inc(str(shard.idx))
        return token

    def _write_event(self, shard: _Shard, kind: str, etype: str,
                     shared: K8sObject, op: str, key: _Key,
                     fp: Tuple[int, int]) -> None:
        # tpulint: holds=mu (write-path internal; every caller holds the
        # writing shard's lock)
        """Stage one write's watch event (and WAL record) from inside the
        shard lock. ``shared`` is the frozen stored snapshot itself —
        every watcher (and the WAL serializer, via the snapshot's cached
        wire encoding) receives the same reference; nothing is copied.
        In the copy-always baseline (``copy_reads=True``) the event is
        instead staged as one fresh mutable deepcopy, the pre-zero-copy
        cost model. Group-commit WAL records ride the ring and are
        appended off-lock by the dispatcher; durable (fsync) records are
        flushed to the shard's own log file HERE, before the write
        returns — fsync releases the GIL, so shards flush in parallel
        while the single-lock baseline serializes every flush."""
        if self._copy_reads:
            self.stats.read_copies += 1
            shared = shared.deepcopy()
        else:
            self.stats.copies_avoided += 1
            if self._metrics is not None:
                self._metrics["copies_avoided"].inc("watch")
        wal = self._wal
        durable = wal is not None and wal.fsync
        rec = None if (wal is None or durable) else (op, key, shared, fp)
        seq = self._enqueue(kind, WatchEvent(etype, shared), rec)
        if durable:
            wal.write_sync(shard.idx, (seq, op, key, shared, fp))

    # -- CRUD --------------------------------------------------------------

    def create(self, obj: K8sObject) -> K8sObject:
        self._check_writable()
        if not obj.kind or not obj.meta.name:
            raise ApiValueError("object needs kind and metadata.name")
        shard = self._shard(obj.kind)
        with shard.mu:
            key = self._key(obj)
            if key in shard.objects:
                raise AlreadyExistsError(f"{key} already exists")
            # The write path's ONE copy: the defensive copy-in (the
            # caller keeps mutable ownership of what it passed). The
            # stamped, frozen snapshot is then stored, returned, AND
            # staged for every watcher + the WAL — nothing else copies.
            stored = obj.deepcopy()
            self.stats.write_copies += 1
            stored.meta.uid = stored.meta.uid or fresh_uid()
            stored.meta.resource_version = self._next_rv()
            stored.meta.generation = 1
            stored.meta.creation_timestamp = stored.meta.creation_timestamp or now()
            stored.meta.deletion_timestamp = None
            freeze(stored)
            self._index_add(shard, key, stored)
            fp = self._fp_mutate(shard, obj.kind, +1, stored.meta.resource_version)
            self._write_event(shard, obj.kind, "ADDED", stored, "PUT", key, fp)
        self._dispatch()
        return stored

    def get(self, kind: str, name: str, namespace: str = "",
            copy: bool = False) -> K8sObject:
        """Read one object. Returns the frozen published snapshot itself
        (zero-copy); ``copy=True`` is the explicit opt-out returning a
        thawed private copy for callers that mutate."""
        shard = self._shard(kind)
        with shard.mu:
            key = (kind, namespace, name)
            try:
                obj = shard.objects[key]
            except KeyError:
                raise NotFoundError(f"{key} not found") from None
            if copy or self._copy_reads:
                self.stats.read_copies += 1
                return obj.deepcopy()
            self.stats.copies_avoided += 1
            if self._metrics is not None:
                self._metrics["copies_avoided"].inc("get")
            return obj

    def try_get(self, kind: str, name: str, namespace: str = "",
                copy: bool = False) -> Optional[K8sObject]:
        try:
            return self.get(kind, name, namespace, copy=copy)
        except NotFoundError:
            return None

    def kind_fingerprint(self, kind: str) -> tuple:
        """Cheap change-detection token for one kind: (live count, last
        resourceVersion stamped on the kind). O(1) — maintained by the
        write paths instead of scanned — so read-mostly callers (the
        allocator's per-pass snapshot, the sim's quiescence detection) can
        poll it every pass for free. Any create/update bumps the rv
        component, any removal drops the count, so the token changes
        whenever the listed set could differ and never repeats."""
        shard = self._shard(kind)
        with shard.mu:
            return shard.fp.get(kind, (0, 0))

    def _size_estimate(self) -> int:
        """Whole-store object count for the *hypothetical* naive-scan
        stat: per-shard dict lens read without the other shards' locks
        (len() is a single C call; the figure feeds a what-if counter,
        not an invariant)."""
        return sum(len(s.objects) for s in self._shards)

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        copy: bool = False,
    ) -> List[K8sObject]:
        """List a kind (namespace/label filtered). The returned list is
        fresh but its elements are the frozen published snapshots
        themselves (zero-copy); ``copy=True`` deepcopies each element
        out for callers that mutate."""
        do_copy = copy or self._copy_reads
        shard = self._shard(kind)
        with shard.mu:
            if namespace is None:
                bucket = shard.by_kind.get(kind, {})
            else:
                bucket = shard.by_kind_ns.get((kind, namespace), {})
            self.stats.list_calls += 1
            self.stats.objects_scanned += len(bucket)
            self.stats.objects_scanned_naive += self._size_estimate()
            out = []
            for key in sorted(bucket):
                obj = bucket[key]
                if not _match_labels(obj, label_selector):
                    continue
                out.append(obj.deepcopy() if do_copy else obj)
            self.stats.objects_returned += len(out)
            if do_copy:
                self.stats.read_copies += len(out)
            else:
                self.stats.copies_avoided += len(out)
            if self._metrics is not None:
                self._metrics["list_total"].inc()
                self._metrics["scanned_total"].inc(by=float(len(bucket)))
                self._metrics["returned_total"].inc(by=float(len(out)))
                if not do_copy and out:
                    self._metrics["copies_avoided"].inc(
                        "list", by=float(len(out)))
            return out

    @staticmethod
    def _share_unchanged(stored: K8sObject, prior: K8sObject) -> None:
        # tpulint: holds=mu (write-path internal; every caller locks)
        """Structural sharing across revisions: adopt the PRIOR frozen
        revision's sub-objects into the not-yet-frozen ``stored`` wherever
        the field compares equal — a status-only update then shares its
        spec (and label/annotation containers) with the previous revision
        by identity. The freeze walk short-circuits on the shared frozen
        subtrees, and the duplicate trees from the copy-in are released
        immediately instead of living once per revision — at 16k nodes
        the store holds one spec per object, not one per status write."""
        if type(stored) is not type(prior):
            return
        nd, pd = stored.__dict__, prior.__dict__
        for name, pval in pd.items():
            if name.startswith("_") or name in ("kind", "meta"):
                continue
            nval = nd.get(name)
            if nval is not None and nval is not pval and nval == pval:
                nd[name] = pval
        # metadata itself always differs (fresh resourceVersion), but its
        # containers usually don't:
        nm, pm = stored.meta.__dict__, prior.meta.__dict__
        for name in ("labels", "annotations", "finalizers",
                     "owner_references"):
            nval, pval = nm.get(name), pm.get(name)
            if nval is not None and nval is not pval and nval == pval:
                nm[name] = pval

    def update(self, obj: K8sObject, _owned: bool = False) -> K8sObject:
        """CAS write. The stored object is replaced wholesale; finalizer
        removal on a deleting object completes its deletion. ``_owned``
        (internal, the update_with_retry copy-on-write commit) marks
        ``obj`` as a private working copy the store may freeze in place
        instead of copying in."""
        self._check_writable()
        shard = self._shard(obj.kind)
        with shard.mu:
            key = self._key(obj)
            cur = shard.objects.get(key)
            if cur is None:
                raise NotFoundError(f"{key} not found")
            if obj.meta.resource_version != cur.meta.resource_version:
                raise ConflictError(
                    f"{key}: resourceVersion {obj.meta.resource_version} != "
                    f"{cur.meta.resource_version}"
                )
            if _owned:
                stored = obj
            else:
                stored = obj.deepcopy()  # the ONE defensive copy-in
                self.stats.write_copies += 1
            stored.meta.uid = cur.meta.uid
            stored.meta.creation_timestamp = cur.meta.creation_timestamp
            stored.meta.deletion_timestamp = cur.meta.deletion_timestamp
            stored.meta.resource_version = self._next_rv()
            stored.meta.generation = cur.meta.generation + 1
            self._share_unchanged(stored, cur)
            freeze(stored)
            if stored.meta.deletion_timestamp is not None and not stored.meta.finalizers:
                self._index_drop(shard, key)
                fp = self._fp_mutate(shard, obj.kind, -1,
                                     stored.meta.resource_version)
                self._write_event(shard, obj.kind, "DELETED", stored,
                                  "DEL", key, fp)
            else:
                self._index_add(shard, key, stored)
                fp = self._fp_mutate(shard, obj.kind, 0,
                                     stored.meta.resource_version)
                self._write_event(shard, obj.kind, "MODIFIED", stored,
                                  "PUT", key, fp)
        self._dispatch()
        return stored

    def delete(self, kind: str, name: str, namespace: str = "") -> None:
        self._check_writable()
        shard = self._shard(kind)
        with shard.mu:
            key = (kind, namespace, name)
            cur = shard.objects.get(key)
            if cur is None:
                raise NotFoundError(f"{key} not found")
            if cur.meta.finalizers:
                if cur.meta.deletion_timestamp is None:
                    # Copy-on-write, not copy: shallow-copy the frozen
                    # snapshot (copy.copy drops the seal and shares every
                    # frozen sub-object), replace only the metadata, and
                    # publish the re-frozen revision.
                    stored = _copy.copy(cur)
                    stored.meta = thaw(cur.meta)
                    stored.meta.deletion_timestamp = now()
                    stored.meta.resource_version = self._next_rv()
                    freeze(stored)
                    self._index_add(shard, key, stored)
                    fp = self._fp_mutate(shard, kind, 0,
                                         stored.meta.resource_version)
                    self._write_event(shard, kind, "MODIFIED", stored,
                                      "PUT", key, fp)
                else:
                    return
            else:
                self._index_drop(shard, key)
                fp = self._fp_mutate(shard, kind, -1)
                self._write_event(shard, kind, "DELETED", cur,
                                  "DEL", key, fp)
        self._dispatch()

    # -- helpers -----------------------------------------------------------

    def attach_metrics(self, registry) -> None:
        """Expose the store's read/size accounting on a tpu_dra_* registry
        (the sim wires its cluster-shared registry here). Idempotent per
        registry; re-attaching to a different registry re-registers."""
        from k8s_dra_driver_tpu.pkg.metrics import Counter, Gauge

        metrics = {
            "list_total": registry.register(Counter(
                "tpu_dra_store_list_requests_total",
                "list() calls served by the API store.")),
            "scanned_total": registry.register(Counter(
                "tpu_dra_store_list_objects_scanned_total",
                "Objects the per-kind/namespace indexes iterated "
                "across all list() calls.")),
            "returned_total": registry.register(Counter(
                "tpu_dra_store_list_objects_returned_total",
                "Objects returned from list() calls (reference handouts "
                "on the zero-copy path).")),
            "copies_avoided": registry.register(Counter(
                "tpu_dra_store_copies_avoided_total",
                "Read-path deep copies avoided by handing out frozen "
                "snapshot references, by path (get / list / watch).",
                label_names=("path",))),
            "objects": registry.register(Gauge(
                "tpu_dra_store_objects",
                "Objects currently stored, by kind.",
                label_names=("kind",))),
            "watch_dropped": registry.register(Counter(
                "tpu_dra_watch_dropped_total",
                "Watch events dropped (oldest-first) because a "
                "watcher's bounded queue was full.",
                label_names=("kind",))),
            "shards": registry.register(Gauge(
                "tpu_dra_store_shards",
                "Lock shards the store is hash-partitioned into "
                "(1 = the single-lock baseline).")),
            "shard_writes": registry.register(Counter(
                "tpu_dra_store_shard_writes_total",
                "Write-path mutations (create/update/delete) per lock "
                "shard — a skewed distribution means hot kinds hash "
                "together.",
                label_names=("shard",))),
            "watch_batches": registry.register(Counter(
                "tpu_dra_store_watch_fanout_batches_total",
                "Off-lock watch fan-out batches delivered, by kind (one "
                "watcher-registry consult per batch).",
                label_names=("kind",))),
            "watch_batch_events": registry.register(Counter(
                "tpu_dra_store_watch_fanout_events_total",
                "Watch events carried by the off-lock fan-out batches, "
                "by kind (events / batches = burst coalescing factor).",
                label_names=("kind",))),
        }
        metrics["shards"].set(value=float(len(self._shards)))
        with self._locked_all():
            self._metrics = metrics
            for shard in self._shards:
                for kind, (count, _) in shard.fp.items():
                    metrics["objects"].set(kind, value=float(count))
        if self._wal is not None:
            self._wal.attach_metrics(registry)

    def attach_wal(self, wal) -> None:
        """Attach a persistence log (k8s.persist.StoreWAL): every write
        from now on rides the dispatch ring as a WAL record and is
        appended off-lock by the dispatcher; the WAL compacts itself into
        snapshots via ``_locked_all`` when due."""
        self._wal = wal

    def update_with_retry(
        self, kind: str, name: str, namespace: str, mutate: Callable[[K8sObject], None],
        attempts: int = 10,
    ) -> K8sObject:
        """Get-mutate-update loop absorbing CAS conflicts — the store's
        copy-on-write seam: the mutator receives a thawed private working
        copy of the current published snapshot, and the commit freezes it
        back in place (``_owned``), structurally sharing every sub-object
        the mutation left untouched with the prior revision."""
        last: Optional[ConflictError] = None
        for _ in range(attempts):
            work = self.get(kind, name, namespace)
            if work.frozen:  # copy_reads mode already handed out a copy
                work = work.thaw()
            self.stats.write_copies += 1
            mutate(work)
            try:
                return self.update(work, _owned=True)
            except ConflictError as e:
                last = e
        raise last  # type: ignore[misc]

    def watch(
        self, kind: str, name: Optional[str] = None, namespace: Optional[str] = None,
        maxsize: int = WATCH_QUEUE_MAXSIZE,
    ) -> "queue.Queue[WatchEvent]":
        q: "queue.Queue[WatchEvent]" = queue.Queue(maxsize=maxsize)
        with self._watch_mu:
            with self._ring_mu:
                min_seq = self._ring_seq
            self._watchers.setdefault(kind, []).append((q, name, namespace,
                                                        min_seq))
        return q

    def stop_watch(self, kind: str, q: "queue.Queue[WatchEvent]") -> None:
        with self._watch_mu:
            entries = self._watchers.get(kind, [])
            self._watchers[kind] = [e for e in entries if e[0] is not q]

    def list_and_watch(
        self, kind: str, name: Optional[str] = None, namespace: Optional[str] = None,
        maxsize: int = WATCH_QUEUE_MAXSIZE,
    ) -> Tuple[List[K8sObject], "queue.Queue[WatchEvent]"]:
        """Atomic snapshot + subscription — informer bootstrap. Holding the
        kind's shard lock across [subscribe, list] means no same-kind write
        is in flight: everything at or below the subscription watermark is
        in the listing, everything above it reaches the queue. The
        bootstrap listing is a reference handout like any other read —
        the pre-freeze path deepcopied every object once per subscriber,
        which at 16k nodes made each new informer a full-store copy."""
        shard = self._shard(kind)
        with shard.mu:
            q = self.watch(kind, name, namespace, maxsize=maxsize)
            objs = self.list(kind, namespace=namespace)
            if name is not None:
                objs = [o for o in objs if o.meta.name == name]
            return objs, q

    # -- garbage collection -------------------------------------------------

    def collect_orphans(self, kinds: Iterable[str]) -> int:
        """One GC pass: delete objects whose controller owner is gone —
        the cluster-side behavior the reference's CleanupManager compensates
        for when owner refs can't be used (cleanup.go:35-146). The doomed
        scan needs a cross-kind uid view, so it runs under the canonical
        ordered all-shard lock."""
        doomed: List[K8sObject] = []
        with self._locked_all():
            uids = set()
            for shard in self._shards:
                uids.update(o.meta.uid for o in shard.objects.values())
            for kind in kinds:
                shard = self._shard(kind)
                for obj in list(shard.by_kind.get(kind, {}).values()):
                    for ref in obj.meta.owner_references:
                        if ref.controller and ref.uid not in uids:
                            doomed.append(obj)
                            break
        for obj in doomed:
            try:
                self.delete(obj.kind, obj.meta.name, obj.meta.namespace)
            except NotFoundError:
                pass
        return len(doomed)

    # -- persistence support -------------------------------------------------

    def dump_state(self) -> dict:
        """Consistent whole-store dump for the persistence snapshot: every
        stored object (the frozen snapshots themselves — immutable, so
        safe to serialize after the locks drop, and each carries its
        cached wire encoding so compaction re-serializes nothing), the
        per-kind fingerprint tokens, and the ring watermark separating
        already-snapshotted writes from WAL records still in flight.
        Taken under the ordered all-shard lock so no write is ever
        half-visible."""
        with self._locked_all():
            objects = []
            fps: Dict[str, Tuple[int, int]] = {}
            for shard in self._shards:
                objects.extend(shard.objects.values())
                fps.update(shard.fp)
            with self._ring_mu:
                watermark = self._ring_seq
            return {"objects": objects, "fps": fps, "watermark": watermark,
                    "rv": max([fp[1] for fp in fps.values()], default=0)}

    def load_state(self, objects: Iterable[K8sObject],
                   fps: Dict[str, Tuple[int, int]], rv: int) -> None:
        """Install restored state wholesale (persistence replay). Only
        valid on a fresh store: indexes are rebuilt, fingerprint tokens
        restored verbatim (the token-match acceptance check), and the rv
        counter resumes past the highest restored version. Emits no watch
        events — there are no subscribers before a restore."""
        with self._locked_all():
            for shard in self._shards:
                if shard.objects:
                    raise ApiValueError("load_state on a non-empty store")
            for obj in objects:
                shard = self._shard(obj.kind)
                self._index_add(shard, self._key(obj), freeze(obj.deepcopy()))
            for kind, token in fps.items():
                self._shard(kind).fp[kind] = (int(token[0]), int(token[1]))
            self._rv_counter = itertools.count(rv + 1)

    # -- replication support -------------------------------------------------

    def apply_replicated(self, op: str, obj: Optional[K8sObject], key,
                         fp: Optional[Tuple[int, int]] = None) -> None:
        """Install ONE replicated WAL record (federation/replication.py).

        Unlike create/update/delete this preserves the LEADER's stamps
        verbatim — resourceVersion, uid, generation, timestamps arrive on
        ``obj`` (decoded from the record's spliced wire encoding) and the
        per-kind fingerprint token is installed as carried (``fp``; None
        leaves the current token, the snapshot diff-apply path installs
        tokens wholesale afterwards). Watch events are emitted through the
        normal off-lock fan-out, so informers, telemetry rollups and
        tpu-kubectl watch a replica exactly as they watch a leader; a WAL
        attached to THIS store re-logs the record (durable replica) via
        the snapshot's cached wire encoding. Permitted while ``read_only``
        — it is the replication stream's sanctioned mutation path. ``obj``
        may be None only for DEL (a delete replayed against a key the
        snapshot never contained)."""
        kind = str(key[0])
        k: _Key = (kind, str(key[1]), str(key[2]))
        shard = self._shard(kind)
        with shard.mu:
            if op == "PUT":
                if obj is None:
                    raise ApiValueError(f"replicated PUT for {k} carries "
                                        f"no object body")
                etype = "MODIFIED" if k in shard.objects else "ADDED"
                stored = obj if obj.frozen else freeze(obj)
                self._index_add(shard, k, stored)
            else:
                cur = shard.objects.get(k)
                stored = obj if obj is not None else cur
                if cur is not None:
                    self._index_drop(shard, k)
                etype = "DELETED"
            if fp is not None:
                token = (int(fp[0]), int(fp[1]))
                shard.fp[kind] = token
            else:
                token = shard.fp.get(kind, (0, 0))
            if self._metrics is not None:
                self._metrics["objects"].set(kind, value=float(token[0]))
                self._metrics["shard_writes"].inc(str(shard.idx))
            if stored is not None:
                if not stored.frozen:
                    freeze(stored)
                self._write_event(shard, kind, etype, stored, op, k, token)
        self._dispatch()

    def install_fingerprints(self, fps: Dict[str, Tuple[int, int]]) -> None:
        """Install per-kind fingerprint tokens verbatim (the replication
        snapshot handoff: objects were diff-applied first, then the
        tokens land wholesale so the replica's change-detection state is
        token-identical to the leader's snapshot)."""
        for kind, token in fps.items():
            shard = self._shard(kind)
            with shard.mu:
                shard.fp[kind] = (int(token[0]), int(token[1]))
                if self._metrics is not None:
                    self._metrics["objects"].set(kind,
                                                 value=float(token[0]))

    def resume_rv(self, rv: Optional[int] = None) -> None:
        """Restart the resourceVersion counter past ``rv`` (default: the
        highest rv any fingerprint token carries). Failover promotion
        calls this so a promoted replica's first write stamps a version
        above everything it replicated."""
        if rv is None:
            with self._locked_all():
                rv = max((fp[1] for s in self._shards
                          for fp in s.fp.values()), default=0)
        self._rv_counter = itertools.count(int(rv) + 1)


class _AllShardsLocked:
    """Context manager behind APIServer._locked_all(): acquires every
    shard lock in index order, releases in reverse. Kept as its own type
    (not @contextmanager) so the shard-lock checker can whitelist it as
    the one sanctioned multi-shard acquire."""

    __slots__ = ("_shards",)

    def __init__(self, shards: List[_Shard]) -> None:
        self._shards = shards

    def __enter__(self) -> None:  # tpulint: ordered-acquire
        for shard in self._shards:
            shard.mu.acquire()

    def __exit__(self, *exc) -> None:
        for shard in reversed(self._shards):
            shard.mu.release()


class ApiValueError(ValueError):
    pass
