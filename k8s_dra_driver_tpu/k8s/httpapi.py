"""HTTP transport for the APIServer: server + remote client.

The reference's five binaries are separate processes sharing one Kubernetes
API server over HTTP; this module gives the TPU build the same shape for
development and the multi-process test tier. `serve_api()` exposes an
in-process APIServer over REST + streaming watches; `RemoteAPIServer`
implements the same interface as `k8s.APIServer` over that wire, so every
component (plugins, controller, daemon, webhook, informers) runs unmodified
in its own process with `--api-backend http`.

Run standalone:  python -m k8s_dra_driver_tpu.k8s.httpapi --port 8001

Routes:
    POST   /objects                     create (body: wire object)
    PUT    /objects                     update (CAS; 409 on conflict)
    GET    /objects/{kind}?name=&ns=    get one (404) or list (ns optional,
                                        labels=<json> selector)
    DELETE /objects/{kind}?name=&ns=    delete (finalizer-aware)
    GET    /watch/{kind}                JSON-lines event stream
    GET    /healthz
    GET    /history/series              flight-recorder series names
    GET    /history/query?series=&resolution=&window=|lo=&hi=
    GET    /history/decisions?kind=&ns=&name=&limit= (or ?trace_id=a,b)
    GET    /replication/status          leader replication head + streams
    GET    /replication/snapshot        bootstrap/resync snapshot document
    GET    /replication/wal?stream=&from=  chunked WAL record stream
    GET    /replica/watermark           follower staleness stamp
    GET    /metrics                     Prometheus text exposition
    GET    /debug/traces?trace_id=&name=  Chrome trace export
    GET    /federation/metrics          fleet-merged exposition
                                        (cluster label per sample)

The /history routes are served only when the hosted APIServer carries a
``history`` attribute (the sim wires its HistoryStore there); they 404
otherwise so clients can tell "no recorder" from "empty history". The
/replication routes use the same seam on ``api.replication`` (a
``federation.ReplicationSource`` — only a persistent leader store has
one), and /replica/watermark on ``api.replica`` (a follower's
``federation.ReplicaStore``), so one server binary serves leader,
follower, or plain in-memory stores and clients probe capability by
route. Followers are read-only: mutating verbs answer 403 ``ReadOnly``.
The same seam gates /metrics on ``api.metrics_registry`` and
/federation/metrics on ``api.federation_peers`` (name -> base-url map);
replica answers additionally carry the machine-readable staleness
header pair ``X-Replication-Watermark`` / ``X-Replication-Lag``.
"""

from __future__ import annotations

import json
import logging
import queue
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

from k8s_dra_driver_tpu.k8s.objects import (
    AlreadyExistsError,
    ApiError,
    ConflictError,
    K8sObject,
    NotFoundError,
)
from k8s_dra_driver_tpu.k8s.serialize import from_wire, to_wire
from k8s_dra_driver_tpu.k8s.store import APIServer, ReadOnlyStoreError, WatchEvent

log = logging.getLogger(__name__)

_ERROR_STATUS = {
    NotFoundError: 404,
    AlreadyExistsError: 409,
    ConflictError: 409,
    ReadOnlyStoreError: 403,
}
_ERROR_CODE = {
    NotFoundError: "NotFound",
    AlreadyExistsError: "AlreadyExists",
    ConflictError: "Conflict",
    ReadOnlyStoreError: "ReadOnly",
}
_CODE_ERROR = {v: k for k, v in _ERROR_CODE.items()}

WATCH_HEARTBEAT_S = 5.0


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    api: APIServer  # set by serve_api subclassing
    stopping: threading.Event  # server shutdown: terminate watch streams

    def log_message(self, *args: object) -> None:  # quiet
        pass

    # -- helpers -----------------------------------------------------------

    def _send_json(self, status: int, doc: dict) -> None:
        body = json.dumps(doc).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self._staleness_headers()
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, status: int, body: bytes,
                   content_type: str = "text/plain; version=0.0.4") -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self._staleness_headers()
        self.end_headers()
        self.wfile.write(body)

    def _staleness_headers(self) -> None:
        """Machine-readable staleness on EVERY replica answer: the
        applied replication watermark and the record lag behind the
        leader head, as an X-header pair — so scripted consumers get
        what the kubectl stderr stamp tells humans. Absent (not zero)
        on non-replica servers."""
        replica = getattr(self.api, "replica", None)
        if replica is not None:
            self.send_header("X-Replication-Watermark",
                             str(replica.watermark()))
            self.send_header("X-Replication-Lag",
                             str(replica.lag_records()))

    def _send_error_obj(self, e: Exception) -> None:
        status = _ERROR_STATUS.get(type(e), 500)
        code = _ERROR_CODE.get(type(e), "Internal")
        self._send_json(status, {"error": code, "message": str(e)})

    def _body(self) -> dict:
        length = int(self.headers.get("Content-Length", "0"))
        return json.loads(self.rfile.read(length) or b"{}")

    def _route(self) -> Tuple[str, List[str], Dict[str, List[str]]]:
        parsed = urllib.parse.urlparse(self.path)
        parts = [p for p in parsed.path.split("/") if p]
        return parsed.path, parts, urllib.parse.parse_qs(parsed.query)

    # -- verbs -------------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802
        _, parts, q = self._route()
        try:
            if parts == ["healthz"]:
                self._send_json(200, {"ok": True})
            elif len(parts) == 2 and parts[0] == "objects":
                kind = parts[1]
                name = q.get("name", [None])[0]
                if name is not None:
                    ns = q.get("ns", [""])[0]
                    self._send_json(200, to_wire(self.api.get(kind, name, ns)))
                else:
                    ns = q.get("ns", [None])[0]
                    labels = json.loads(q["labels"][0]) if "labels" in q else None
                    objs = self.api.list(kind, namespace=ns, label_selector=labels)
                    self._send_json(200, {"items": [to_wire(o) for o in objs]})
            elif len(parts) == 2 and parts[0] == "watch":
                self._stream_watch(
                    parts[1],
                    name=q.get("name", [None])[0],
                    namespace=q.get("ns", [None])[0],
                )
            elif len(parts) == 2 and parts[0] == "history":
                self._history_route(parts[1], q)
            elif len(parts) == 2 and parts[0] == "replication":
                self._replication_route(parts[1], q)
            elif parts == ["replica", "watermark"]:
                self._replica_route()
            elif parts == ["metrics"]:
                self._metrics_route()
            elif parts == ["debug", "traces"]:
                self._traces_route(q)
            elif parts == ["federation", "metrics"]:
                self._federation_metrics_route()
            else:
                self._send_json(404, {"error": "NoRoute", "message": self.path})
        except ApiError as e:
            self._send_error_obj(e)
        except (ValueError, KeyError) as e:
            # Malformed labels= JSON / invalid body must not tear down the
            # connection without a JSON error document.
            self._send_json(400, {"error": "BadRequest", "message": str(e)})
        except (BrokenPipeError, ConnectionResetError):
            pass

    def do_POST(self) -> None:  # noqa: N802
        _, parts, _ = self._route()
        try:
            if parts == ["objects"]:
                obj = from_wire(self._body())
                self._send_json(201, to_wire(self.api.create(obj)))
            else:
                self._send_json(404, {"error": "NoRoute", "message": self.path})
        except ApiError as e:
            self._send_error_obj(e)
        except (ValueError, KeyError) as e:
            # Malformed labels= JSON / invalid body must not tear down the
            # connection without a JSON error document.
            self._send_json(400, {"error": "BadRequest", "message": str(e)})

    def do_PUT(self) -> None:  # noqa: N802
        _, parts, _ = self._route()
        try:
            if parts == ["objects"]:
                obj = from_wire(self._body())
                self._send_json(200, to_wire(self.api.update(obj)))
            else:
                self._send_json(404, {"error": "NoRoute", "message": self.path})
        except ApiError as e:
            self._send_error_obj(e)
        except (ValueError, KeyError) as e:
            # Malformed labels= JSON / invalid body must not tear down the
            # connection without a JSON error document.
            self._send_json(400, {"error": "BadRequest", "message": str(e)})

    def do_DELETE(self) -> None:  # noqa: N802
        _, parts, q = self._route()
        try:
            if len(parts) == 2 and parts[0] == "objects":
                name = q.get("name", [""])[0]
                ns = q.get("ns", [""])[0]
                self.api.delete(parts[1], name, ns)
                self._send_json(200, {"ok": True})
            else:
                self._send_json(404, {"error": "NoRoute", "message": self.path})
        except ApiError as e:
            self._send_error_obj(e)
        except (ValueError, KeyError) as e:
            # Malformed labels= JSON / invalid body must not tear down the
            # connection without a JSON error document.
            self._send_json(400, {"error": "BadRequest", "message": str(e)})

    # -- flight recorder -----------------------------------------------------

    def _history_route(self, what: str, q: Dict[str, List[str]]) -> None:
        """Query surface for the pkg/history.py HistoryStore the sim
        attaches to its APIServer. float()/query() raising ValueError is
        handled by do_GET's 400 path — malformed window/resolution never
        tears the connection down."""
        hist = getattr(self.api, "history", None)
        if hist is None:
            self._send_json(404, {"error": "NoRoute",
                                  "message": "no history store attached"})
        elif what == "series":
            self._send_json(200, {"series": hist.series_names()})
        elif what == "query":
            series = q.get("series", [""])[0]
            resolution = q.get("resolution", ["raw"])[0]
            window = None
            if "lo" in q and "hi" in q:
                window = (float(q["lo"][0]), float(q["hi"][0]))
            elif "window" in q:
                window = float(q["window"][0])
            pts = hist.query(series, window=window, resolution=resolution)
            self._send_json(200, {"series": series,
                                  "resolution": resolution, "points": pts})
        elif what == "decisions":
            trace_ids = q.get("trace_id", [""])[0]
            if trace_ids:
                # Trace-stitching read: every retained decision stamped
                # with one of the (comma-separated) trace ids, whatever
                # object it was recorded against.
                recs = hist.decisions_by_trace(
                    trace_ids.split(","),
                    limit=int(q.get("limit", ["0"])[0]))
            else:
                recs = hist.decisions_for(
                    q.get("kind", [""])[0], q.get("ns", [""])[0],
                    q.get("name", [""])[0],
                    limit=int(q.get("limit", ["0"])[0]))
            self._send_json(200, {"items": [r.to_doc() for r in recs]})
        else:
            self._send_json(404, {"error": "NoRoute", "message": self.path})

    # -- replication ---------------------------------------------------------

    def _replication_route(self, what: str, q: Dict[str, List[str]]) -> None:
        """Leader half of WAL-streamed replication (federation/). Gated
        on ``api.replication`` the same way /history gates on
        ``api.history`` — a store without an attached ReplicationSource
        404s, so followers can tell "not a replicable leader" apart from
        transport failures."""
        repl = getattr(self.api, "replication", None)
        if repl is None:
            self._send_json(404, {"error": "NoRoute",
                                  "message": "no replication source attached"})
        elif what == "status":
            self._send_json(200, repl.status())
        elif what == "snapshot":
            self._send_json(200, repl.snapshot())
        elif what == "wal":
            stream = int(q.get("stream", ["-1"])[0])
            from_seq = int(q.get("from", ["0"])[0])
            self._stream_wal(repl, stream, from_seq)
        else:
            self._send_json(404, {"error": "NoRoute", "message": self.path})

    def _stream_wal(self, repl, stream: int, from_seq: int) -> None:
        """Chunked JSON-lines tail of one WAL stream: raw record lines
        forwarded verbatim (the on-disk bytes already splice the cached
        wire encodings — nothing is re-serialized here), with the
        source's HEARTBEAT/SNAPSHOT control lines interleaved. Ends when
        the server stops or the client goes away (heartbeat writes
        surface dead sockets, same as watch streams)."""
        self.send_response(200)
        self.send_header("Content-Type", "application/jsonl")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        for line in repl.tail(stream, from_seq, stop=self.stopping):
            data = (line + "\n").encode()
            self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
            self.wfile.flush()

    def _replica_route(self) -> None:
        """Follower staleness stamp: the applied replication watermark
        (and lag bookkeeping) of the ReplicaStore hosting this store, or
        404 when this server is not a replica."""
        replica = getattr(self.api, "replica", None)
        if replica is None:
            self._send_json(404, {"error": "NoRoute",
                                  "message": "not a replica store"})
        else:
            self._send_json(200, replica.status())

    # -- observability -------------------------------------------------------

    def _metrics_route(self) -> None:
        """Prometheus text exposition for the registry hanging off the
        hosted store (``api.metrics_registry`` — the same capability
        seam as history/replication: absent registry 404s)."""
        registry = getattr(self.api, "metrics_registry", None)
        if registry is None:
            self._send_json(404, {"error": "NoRoute",
                                  "message": "no metrics registry attached"})
        else:
            self._send_text(200, registry.expose().encode())

    def _traces_route(self, q: Dict[str, List[str]]) -> None:
        """Chrome trace-event export of the process-default span ring,
        mirroring MetricsServer's /debug/traces so a cluster served only
        through this API still answers ``sim trace --cluster``. Accepts
        the same trace_id=/name= narrowing."""
        from k8s_dra_driver_tpu.pkg import tracing

        tracer = tracing.get_tracer()
        spans = tracer.spans(trace_id=q.get("trace_id", [None])[0],
                             name=q.get("name", [None])[0])
        self._send_text(200, tracer.export_chrome_json(spans),
                        content_type="application/json")

    def _federation_metrics_route(self) -> None:
        """The global query plane's aggregation route: scrape every
        federated peer's /metrics and re-emit the union with a
        ``cluster`` label injected into each sample, so one Prometheus
        target covers the fleet. Gated on ``api.federation_peers``
        (a name -> base-url map the fleet harness attaches); unreachable
        peers are skipped — a partitioned region must not blank the
        whole fleet's scrape."""
        from k8s_dra_driver_tpu.federation.query import merge_metrics_texts

        peers = getattr(self.api, "federation_peers", None)
        if not peers:
            self._send_json(404, {"error": "NoRoute",
                                  "message": "no federation peers attached"})
            return
        texts: Dict[str, str] = {}
        unreachable: List[str] = []
        for name in sorted(peers):
            try:
                with urllib.request.urlopen(
                        peers[name].rstrip("/") + "/metrics",
                        timeout=5.0) as resp:
                    texts[name] = resp.read().decode()
            except (OSError, urllib.error.URLError):
                unreachable.append(name)
        body = merge_metrics_texts(texts)
        for name in unreachable:
            body += f"# cluster {name}: unreachable\n"
        self._send_text(200, body.encode())

    # -- watch streaming ----------------------------------------------------

    def _stream_watch(self, kind: str, name: Optional[str] = None,
                      namespace: Optional[str] = None) -> None:
        """JSON-lines chunked stream; heartbeats detect dead clients so the
        server-side queue is unregistered (a real API server closes idle
        watches the same way). name/ns are the field-selector analog."""
        # Deep bound: remote informers rebuild their caches from this
        # stream and only relist on reconnect — a drop here would diverge
        # them silently, so allow a far larger burst than the store default
        # (a stalled client is eventually reaped by the heartbeat below).
        from k8s_dra_driver_tpu.k8s.informer import INFORMER_WATCH_QUEUE_MAXSIZE

        wq = self.api.watch(kind, name=name, namespace=namespace,
                            maxsize=INFORMER_WATCH_QUEUE_MAXSIZE)
        try:
            self.send_response(200)
            self.send_header("Content-Type", "application/jsonl")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def write_line(doc: dict) -> None:
                line = (json.dumps(doc) + "\n").encode()
                self.wfile.write(f"{len(line):x}\r\n".encode() + line + b"\r\n")
                self.wfile.flush()

            # The queue is registered: tell the client its watch is live so
            # it can order a subsequent list after the subscription.
            write_line({"type": "SYNC"})
            last_beat = time.monotonic()
            while not self.stopping.is_set():
                try:
                    ev = wq.get(timeout=0.5)
                except queue.Empty:
                    if time.monotonic() - last_beat >= WATCH_HEARTBEAT_S:
                        write_line({"type": "HEARTBEAT"})
                        last_beat = time.monotonic()
                    continue
                write_line({"type": ev.type, "object": to_wire(ev.obj)})
            # Server stopping: end the stream so clients see the outage and
            # reconnect (a real apiserver severs watches on restart too).
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            self.api.stop_watch(kind, wq)


class HTTPAPIServer:
    """Hosts an APIServer over HTTP on a background thread."""

    def __init__(self, api: Optional[APIServer] = None, host: str = "127.0.0.1",
                 port: int = 0):
        self.api = api or APIServer()

        class Handler(_Handler):
            pass

        Handler.api = self.api
        Handler.stopping = self._stopping = threading.Event()
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "HTTPAPIServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="http-apiserver", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stopping.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)


def serve_api(api: Optional[APIServer] = None, host: str = "127.0.0.1",
              port: int = 0) -> HTTPAPIServer:
    return HTTPAPIServer(api, host, port).start()


# -- client -----------------------------------------------------------------


class _RemoteHistory:
    """Client half of the /history routes: the HistoryStore query
    surface (series_names / query / decisions_for) over the wire, so
    ``tpu-kubectl explain`` and ``top --history`` run unmodified against
    a remote sim."""

    def __init__(self, client: "RemoteAPIServer"):
        self._client = client

    def series_names(self) -> List[str]:
        doc = self._client._request("GET", "/history/series")
        return list(doc.get("series", []))

    def query(self, series: str, window=None,
              resolution: str = "raw") -> List[dict]:
        params = {"series": series, "resolution": resolution}
        if isinstance(window, tuple):
            params["lo"], params["hi"] = window
        elif window is not None:
            params["window"] = window
        doc = self._client._request(
            "GET", "/history/query" + self._client._q(**params))
        return doc.get("points", [])

    def decisions_for(self, kind: str, namespace: str, name: str,
                      window=None, limit: int = 0) -> list:
        from k8s_dra_driver_tpu.pkg.history import DecisionRecord

        doc = self._client._request(
            "GET", "/history/decisions" + self._client._q(
                kind=kind, ns=namespace, name=name,
                limit=limit if limit else None))
        recs = [DecisionRecord.from_doc(d) for d in doc.get("items", [])]
        if window is not None:
            lo, hi = window
            recs = [r for r in recs if lo <= r.time <= hi]
        return recs

    def decisions_by_trace(self, trace_ids, limit: int = 0) -> list:
        from k8s_dra_driver_tpu.pkg.history import DecisionRecord

        want = sorted({t for t in trace_ids if t})
        if not want:
            return []
        doc = self._client._request(
            "GET", "/history/decisions" + self._client._q(
                trace_id=",".join(want),
                limit=limit if limit else None))
        recs = [DecisionRecord.from_doc(d) for d in doc.get("items", [])]
        return recs


class RemoteAPIServer:
    """Client-side APIServer over the HTTP wire — drop-in for k8s.APIServer
    (create/get/try_get/list/update/delete/update_with_retry/watch/
    stop_watch/list_and_watch)."""

    def __init__(self, base_url: str, timeout: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self._watch_stops: Dict[int, threading.Event] = {}
        self._watch_known: Dict[int, Dict[Tuple[str, str], K8sObject]] = {}
        # Machine-readable staleness from the last response's
        # X-Replication-Watermark / X-Replication-Lag header pair:
        # {"watermark": int, "lag_records": int}, or None when the
        # server is not a replica. Consumers (kubectl -o json) read
        # this instead of paying the /replica/watermark round-trip.
        self.last_staleness: Optional[Dict[str, int]] = None

    # -- plumbing ----------------------------------------------------------

    def _note_staleness(self, headers) -> None:
        wm = headers.get("X-Replication-Watermark")
        lag = headers.get("X-Replication-Lag")
        if wm is None:
            self.last_staleness = None
            return
        try:
            self.last_staleness = {"watermark": int(wm),
                                   "lag_records": int(lag or 0)}
        except ValueError:
            self.last_staleness = None

    def _request(self, method: str, path: str, body: Optional[dict] = None) -> dict:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.base_url + path, data=data, method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                self._note_staleness(resp.headers)
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            doc = {}
            try:
                doc = json.loads(e.read() or b"{}")
            except json.JSONDecodeError:
                pass
            err_cls = _CODE_ERROR.get(doc.get("error", ""), ApiError)
            raise err_cls(doc.get("message", str(e))) from None

    def _request_text(self, path: str) -> str:
        try:
            with urllib.request.urlopen(self.base_url + path,
                                        timeout=self.timeout) as resp:
                self._note_staleness(resp.headers)
                return resp.read().decode()
        except urllib.error.HTTPError as e:
            doc = {}
            try:
                doc = json.loads(e.read() or b"{}")
            except json.JSONDecodeError:
                pass
            err_cls = _CODE_ERROR.get(doc.get("error", ""), ApiError)
            raise err_cls(doc.get("message", str(e))) from None

    @staticmethod
    def _q(**params) -> str:
        q = {k: v for k, v in params.items() if v is not None}
        return ("?" + urllib.parse.urlencode(q)) if q else ""

    # -- interface ----------------------------------------------------------

    @property
    def history(self) -> Optional[_RemoteHistory]:
        """Remote view of the server-side flight recorder, or None when
        the server has no HistoryStore attached (one probe round-trip —
        kubectl resolves this once per command, not per row)."""
        try:
            self._request("GET", "/history/series")
        except ApiError:
            return None
        return _RemoteHistory(self)

    def replica_status(self) -> Optional[dict]:
        """The server's follower staleness stamp (applied replication
        watermark, lag, promotion state), or None when it is not a read
        replica — kubectl probes this once per command to stamp follower
        answers."""
        try:
            return self._request("GET", "/replica/watermark")
        except ApiError:
            return None

    def metrics_text(self) -> Optional[str]:
        """The server's Prometheus text exposition, or None when no
        metrics registry is attached (the `top --all-clusters` scrape)."""
        try:
            return self._request_text("/metrics")
        except ApiError:
            return None

    def federation_metrics_text(self) -> Optional[str]:
        """The fleet-merged exposition from /federation/metrics, or
        None when this server has no federation peers attached."""
        try:
            return self._request_text("/federation/metrics")
        except ApiError:
            return None

    def debug_traces(self, trace_id: Optional[str] = None,
                     name: Optional[str] = None) -> Optional[dict]:
        """The server's Chrome trace export (/debug/traces), or None
        when the route is absent — `sim trace --cluster` routing."""
        try:
            return json.loads(self._request_text(
                "/debug/traces" + self._q(trace_id=trace_id, name=name)))
        except (ApiError, json.JSONDecodeError):
            return None

    def create(self, obj: K8sObject) -> K8sObject:
        return from_wire(self._request("POST", "/objects", to_wire(obj)))

    def get(self, kind: str, name: str, namespace: str = "",
            copy: bool = False) -> K8sObject:
        # ``copy`` is signature parity with the in-process store's
        # zero-copy reads: wire deserialization already yields a private
        # mutable object, so there is nothing further to copy.
        return from_wire(
            self._request("GET", f"/objects/{kind}" + self._q(name=name, ns=namespace))
        )

    def try_get(self, kind: str, name: str, namespace: str = "",
                copy: bool = False) -> Optional[K8sObject]:
        try:
            return self.get(kind, name, namespace)
        except NotFoundError:
            return None

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        copy: bool = False,
    ) -> List[K8sObject]:
        labels = json.dumps(label_selector) if label_selector else None
        doc = self._request(
            "GET", f"/objects/{kind}" + self._q(ns=namespace, labels=labels)
        )
        return [from_wire(d) for d in doc["items"]]

    def update(self, obj: K8sObject) -> K8sObject:
        return from_wire(self._request("PUT", "/objects", to_wire(obj)))

    def delete(self, kind: str, name: str, namespace: str = "") -> None:
        self._request("DELETE", f"/objects/{kind}" + self._q(name=name, ns=namespace))

    def update_with_retry(
        self, kind: str, name: str, namespace: str,
        mutate: Callable[[K8sObject], None], attempts: int = 10,
    ) -> K8sObject:
        last: Optional[ConflictError] = None
        for _ in range(attempts):
            obj = self.get(kind, name, namespace)
            mutate(obj)
            try:
                return self.update(obj)
            except ConflictError as e:
                last = e
        raise last  # type: ignore[misc]

    # -- watch ---------------------------------------------------------------

    def watch(
        self, kind: str, name: Optional[str] = None, namespace: Optional[str] = None,
        maxsize: int = 0,
    ) -> "queue.Queue[WatchEvent]":
        # ``maxsize`` keeps the APIServer.watch signature so informers and
        # the sim need no backend-specific branching; the meaningful bound
        # lives server-side (_stream_watch) — this client queue is drained
        # by the reader thread, and capping it here would make the
        # reconnect replay_list() deadlock against a slow consumer.
        q: "queue.Queue[WatchEvent]" = queue.Queue()
        stop = threading.Event()
        synced = threading.Event()
        self._watch_stops[id(q)] = stop
        query = self._q(name=name, ns=namespace)

        # Objects this watch has delivered and not yet seen deleted, keyed by
        # (namespace, name) — lets a resync synthesize DELETED events for
        # objects that vanished while the stream was down. Exposed per-queue
        # so list_and_watch can seed it with its snapshot (objects a consumer
        # learned from the list, not the stream, must also be diffed).
        known: Dict[Tuple[str, str], K8sObject] = {}
        self._watch_known[id(q)] = known

        def emit(ev_type: str, obj: K8sObject) -> None:
            key = (obj.namespace or "", obj.meta.name)
            if ev_type == "DELETED":
                known.pop(key, None)
            else:
                known[key] = obj
            q.put(WatchEvent(ev_type, obj))

        def replay_list() -> None:
            live = {}
            for obj in self.list(kind, namespace=namespace):
                if name is None or obj.meta.name == name:
                    live[(obj.namespace or "", obj.meta.name)] = obj
            # Anything we knew about that the snapshot no longer contains was
            # deleted during the outage.
            for key, obj in list(known.items()):
                if key not in live:
                    emit("DELETED", obj)
            for obj in live.values():
                emit("ADDED", obj)

        def stream_once(resync: bool) -> None:
            req = urllib.request.Request(self.base_url + f"/watch/{kind}" + query)
            with urllib.request.urlopen(req, timeout=None) as resp:
                for raw in resp:
                    if stop.is_set():
                        return
                    doc = json.loads(raw)
                    kind_ = doc.get("type")
                    if kind_ == "SYNC":
                        if resync:
                            # Subscription is live again: replay the current
                            # state (ADDED + synthesized DELETED) so informer
                            # caches converge on everything missed during the
                            # outage. Listing after SYNC means no gap between
                            # snapshot and stream; informers absorb replays.
                            replay_list()
                        synced.set()
                        continue
                    if kind_ == "HEARTBEAT":
                        continue
                    emit(doc["type"], from_wire(doc["object"]))

        def reader() -> None:
            # Reconnect on unexpected stream end (apiserver restart, network
            # blip) rather than leaving informers — incl. the PodManager
            # readiness mirror — on a stale cache forever.
            first = True
            try:
                while not stop.is_set():
                    try:
                        stream_once(resync=not first)
                        if not stop.is_set():
                            log.warning("watch stream for %s ended; reconnecting", kind)
                    except (OSError, json.JSONDecodeError, ApiError):
                        # ApiError covers replay_list()'s HTTP list failing
                        # (e.g. 500 while the server restarts) — the thread
                        # must survive to retry, not die silently.
                        if stop.is_set():
                            return
                        log.warning("watch stream for %s errored; reconnecting", kind)
                    first = False
                    synced.set()  # never leave the caller blocked
                    stop.wait(timeout=1.0)
            finally:
                synced.set()

        threading.Thread(target=reader, name=f"watch-{kind}", daemon=True).start()
        # Block until the server registered the subscription: events emitted
        # after watch() returns are then guaranteed to be delivered, which
        # list_and_watch's snapshot ordering relies on.
        synced.wait(timeout=self.timeout)
        return q

    def stop_watch(self, kind: str, q: "queue.Queue[WatchEvent]") -> None:
        self._watch_known.pop(id(q), None)
        stop = self._watch_stops.pop(id(q), None)
        if stop:
            stop.set()

    def list_and_watch(
        self, kind: str, name: Optional[str] = None, namespace: Optional[str] = None,
        maxsize: int = 0,
    ) -> Tuple[List[K8sObject], "queue.Queue[WatchEvent]"]:
        """Watch-then-list: events racing the list may duplicate objects the
        snapshot already contains; informer caches absorb replays (the
        real-world list+watch has the same at-least-once property)."""
        q = self.watch(kind, name=name, namespace=namespace, maxsize=maxsize)
        objs = self.list(kind, namespace=namespace)
        if name is not None:
            objs = [o for o in objs if o.meta.name == name]
        # Seed the watch's known-object map with the snapshot: a consumer's
        # cache built from this list must see synthesized DELETED events for
        # these objects too if they vanish during a stream outage.
        known = self._watch_known.get(id(q))
        if known is not None:
            for obj in objs:
                known.setdefault((obj.namespace or "", obj.meta.name), obj)
        return objs, q


class RemoteReplicationSource:
    """Client half of the /replication routes: the same
    status()/snapshot()/tail() trio as ``federation.ReplicationSource``,
    so a ``ReplicaStore`` follows a leader over the wire with no code
    differences from the in-process case.

    ``tail`` reads the chunked JSON-lines stream and yields the raw
    lines (record lines verbatim, control lines included) — the caller
    parses, exactly as with the local source. The read timeout is well
    above the leader's heartbeat cadence, so a partitioned or dead
    leader surfaces as an exception within ``timeout`` seconds and the
    follower's supervisor reconnects; a set ``stop`` event ends the
    stream within one heartbeat."""

    def __init__(self, base_url: str, timeout: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def _request(self, path: str) -> dict:
        with urllib.request.urlopen(self.base_url + path,
                                    timeout=self.timeout) as resp:
            return json.loads(resp.read() or b"{}")

    def status(self) -> dict:
        return self._request("/replication/status")

    def snapshot(self) -> dict:
        return self._request("/replication/snapshot")

    def tail(self, stream: int, from_seq: int,
             stop: Optional[threading.Event] = None):
        url = (self.base_url
               + f"/replication/wal?stream={stream}&from={from_seq}")
        resp = urllib.request.urlopen(url, timeout=self.timeout)
        try:
            # http.client undoes the chunked framing; readline gives back
            # the JSON lines the server wrote. Heartbeats arrive every
            # TAIL_HEARTBEAT_S, so this loop re-checks ``stop`` at least
            # that often and a silent wire trips the socket timeout.
            while stop is None or not stop.is_set():
                raw = resp.readline()
                if not raw:
                    return  # leader closed the stream (shutdown)
                line = raw.decode().strip()
                if line:
                    yield line
        finally:
            resp.close()


def main(argv=None) -> int:
    import argparse
    import signal

    parser = argparse.ArgumentParser("tpu-dra-apiserver",
                                     description="standalone sim API server over HTTP")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8001)
    args = parser.parse_args(argv)
    srv = serve_api(host=args.host, port=args.port)
    print(f"serving on {srv.url}", flush=True)
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *a: stop.set())
    stop.wait()
    srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
