"""Mini-CEL: the DRA device-selector subset of CEL, evaluated in-process.

Real clusters evaluate DeviceClass/request `selectors[].cel.expression`
with cel-go inside the scheduler (the reference's chart relies on this,
e.g. `device.driver == 'gpu.nvidia.com' && device.attributes[...]...`).
The sim's allocator uses this evaluator so the *shipped chart's actual
expressions* — not a parallel match-attribute encoding — decide matching.

Expressions compile once (lru-cached) to a closure evaluated per device,
so the allocator's device loop pays no repeated parsing.

Supported subset (everything our chart and the reference's use, plus the
obvious neighbors):

    device.driver, device.attributes["key"],
    device.attributes["domain"].name   (-> flat "domain/name" lookup),
    device.capacity["key"], device.capacity["domain"].name
    quantity("16Gi") and the k8s CEL quantity methods
      .isGreaterThan(q) .isLessThan(q) .isEqualTo(q) .compareTo(q)
    literals: 'str' "str" ints (incl. negative) true false
    operators: == != < <= > >= && || !  and parentheses

Operator precedence follows cel-go: unary `!` binds tighter than the
comparison operators (`!a == b` is `(!a) == b`), comparisons bind tighter
than `&&`, which binds tighter than `||`.

Missing attributes make *every* comparison false — including `!=`. Real
cel-go errors on a missing-key access and DRA treats an erroring selector
as non-matching, so "absent attribute → device does not match" is the
faithful net behavior (a `!= -> true` convenience would match devices in
sim that a real scheduler would reject). The same rule applies to
unlike-typed comparisons that can't be numerically coerced (cel-go
type-errors; we return non-match) and to quantity methods over
unparseable operands.
"""

from __future__ import annotations

import functools
import re
from fractions import Fraction
from typing import Any, Callable, List, Optional


class CelError(ValueError):
    pass


_TOKEN = re.compile(r"""
    \s*(
        '(?:[^'\\]|\\.)*' | "(?:[^"\\]|\\.)*"   # strings
      | -?\d+                                    # ints (incl. negative)
      | [A-Za-z_][A-Za-z0-9_]*                   # identifiers
      | == | != | <= | >= | && | \|\|            # two-char ops
      | [()\[\].!<>]                             # single-char ops
    )""", re.VERBOSE)


def _tokenize(expr: str) -> List[str]:
    out, pos = [], 0
    while pos < len(expr):
        m = _TOKEN.match(expr, pos)
        if not m:
            if expr[pos:].strip() == "":
                break
            raise CelError(f"bad token at {expr[pos:pos + 12]!r}")
        out.append(m.group(1))
        pos = m.end()
    return out


class _Missing:
    """Sentinel for absent attributes / type errors: never matches.

    Falsy so that `&&` / `||` short-circuits agree with cel-go's net
    effect (an erroring operand can only make the selector non-matching,
    never matching)."""

    def __bool__(self) -> bool:
        return False

    def __repr__(self) -> str:  # pragma: no cover — debug aid
        return "<missing>"


MISSING = _Missing()

_Fn = Callable[[Any], Any]  # compiled node: device -> value


def _is_int(tok: str) -> bool:
    return tok.lstrip("-").isdigit() and tok != "-"


# Kubernetes resource.Quantity suffixes (binary + decimal + milli), the
# subset CEL's quantity("...") accepts that selectors realistically use.
_QTY_SUFFIX = {
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50,
    "Ei": 2**60, "k": 10**3, "M": 10**6, "G": 10**9, "T": 10**12,
    "P": 10**15, "E": 10**18, "": 1,
}
_QTY_RE = re.compile(r"^\s*([+-]?\d+(?:\.\d+)?)([KMGTPE]i|[kMGTPE]|m|)\s*$")


def parse_quantity(s) -> Fraction:
    """Parse a k8s quantity ("16Gi", "500m", "2", 17179869184) to an
    exact Fraction. Raises ValueError on anything unparseable."""
    if isinstance(s, bool):
        raise ValueError(f"not a quantity: {s!r}")
    if isinstance(s, (int, Fraction)):
        return Fraction(s)
    m = _QTY_RE.match(str(s))
    if not m:
        raise ValueError(f"not a quantity: {s!r}")
    num, suffix = m.groups()
    if suffix == "m":
        return Fraction(num) / 1000
    return Fraction(num) * _QTY_SUFFIX[suffix]


_QTY_METHODS = {"isGreaterThan", "isLessThan", "isEqualTo", "compareTo"}


def _qty_method(name: str, a, b):
    """Apply a k8s CEL quantity method; MISSING on type error so a bad
    operand makes the device non-matching, mirroring cel-go's error."""
    try:
        qa, qb = parse_quantity(a), parse_quantity(b)
    except ValueError:
        return MISSING
    if name == "isGreaterThan":
        return qa > qb
    if name == "isLessThan":
        return qa < qb
    if name == "isEqualTo":
        return qa == qb
    return -1 if qa < qb else (1 if qa > qb else 0)  # compareTo


class _Compiler:
    """Recursive-descent compile to closures; runs once per expression."""

    def __init__(self, tokens: List[str]):
        self.toks = tokens
        self.i = 0

    def peek(self) -> Optional[str]:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def take(self, want: Optional[str] = None) -> str:
        tok = self.peek()
        if tok is None or (want is not None and tok != want):
            raise CelError(f"expected {want or 'token'}, got {tok!r}")
        self.i += 1
        return tok

    def expr(self) -> _Fn:
        fn = self.and_()
        while self.peek() == "||":
            self.take()
            rhs = self.and_()
            fn = (lambda lhs, rhs: lambda d: bool(lhs(d)) or bool(rhs(d)))(fn, rhs)
        return fn

    def and_(self) -> _Fn:
        fn = self.cmp()
        while self.peek() == "&&":
            self.take()
            rhs = self.cmp()
            fn = (lambda lhs, rhs: lambda d: bool(lhs(d)) and bool(rhs(d)))(fn, rhs)
        return fn

    def unary(self) -> _Fn:
        # cel-go binds `!` tighter than comparisons: `!a == b` is
        # `(!a) == b`. Errors (MISSING) propagate through negation.
        if self.peek() == "!":
            self.take()
            inner = self.unary()

            def negate(d, inner=inner):
                v = inner(d)
                if isinstance(v, _Missing):
                    return MISSING
                return not bool(v)

            return negate
        return self.term()

    _CMPS = {"==", "!=", "<", "<=", ">", ">="}

    def cmp(self) -> _Fn:
        lhs = self.unary()
        op = self.peek()
        if op not in self._CMPS:
            return lhs
        self.take()
        rhs = self.unary()

        def compare(d, lhs=lhs, rhs=rhs, op=op):
            a, b = lhs(d), rhs(d)
            if isinstance(a, _Missing) or isinstance(b, _Missing):
                # cel-go errors here and DRA counts the device as
                # non-matching — so every operator, != included, is false.
                return False
            # CEL compares like-typed values; coerce int-vs-str-of-int
            # since attribute wire values may arrive as strings. Unlike
            # types that won't coerce to int are a cel-go type error
            # (DRA: non-match) — never fall back to lexicographic compare,
            # which would match devices real cel-go rejects (e.g.
            # "16Gi" < "2" is lexicographically true). Deliberately int()
            # not parse_quantity(): cel-go has no int-vs-quantity overload
            # either, so `capacity < 2` against "16Gi" must not match —
            # quantity math belongs to the quantity methods.
            if isinstance(a, Fraction) != isinstance(b, Fraction):
                # quantity vs anything-but-quantity: cel-go has no such
                # overload (int-vs-quantity included) — non-match, never
                # a truncating coercion.
                return False
            # bool before the int branch: Python's bool IS an int, so
            # without this check `true == 1` would compare True == 1 and
            # match. cel-go has no bool-vs-int overload (no_such_overload
            # error; DRA: non-match) — and bool attribute values must not
            # be "coerced" through int("true") either.
            if isinstance(a, bool) != isinstance(b, bool):
                return False
            if isinstance(a, int) != isinstance(b, int):
                try:
                    a, b = int(a), int(b)
                except (TypeError, ValueError):
                    return False  # no_such_overload → DRA non-match
            if op == "==":
                return a == b
            if op == "!=":
                return a != b
            if op == "<":
                return a < b
            if op == "<=":
                return a <= b
            if op == ">":
                return a > b
            return a >= b

        return compare

    def term(self) -> _Fn:
        tok = self.peek()
        if tok == "(":
            self.take()
            fn = self.expr()
            self.take(")")
            return fn
        if tok is None:
            raise CelError("unexpected end of expression")
        if tok[0] in "'\"":
            self.take()
            v = tok[1:-1]
            return lambda d: v
        if _is_int(tok):
            self.take()
            v = int(tok)
            return lambda d: v
        if tok == "true":
            self.take()
            return lambda d: True
        if tok == "false":
            self.take()
            return lambda d: False
        if tok == "quantity":
            self.take()
            self.take("(")
            arg = self.take()
            if arg[0] not in "'\"":
                raise CelError(f"quantity() wants a string literal, got {arg!r}")
            self.take(")")
            try:
                q = parse_quantity(arg[1:-1])
            except ValueError as e:
                raise CelError(str(e)) from e
            return self.postfix(lambda d, q=q: q)
        if tok == "device":
            return self.device_path()
        raise CelError(f"unsupported term {tok!r}")

    def device_path(self) -> _Fn:
        self.take("device")
        self.take(".")
        field = self.take()
        if field == "driver":
            return self.postfix(lambda d: getattr(d, "driver", MISSING))
        if field not in ("attributes", "capacity"):
            raise CelError(f"unsupported device field {field!r}")
        self.take("[")
        key_tok = self.take()
        if key_tok[0] not in "'\"":
            raise CelError(f"map key must be a string literal, got {key_tok!r}")
        key = key_tok[1:-1]
        self.take("]")
        name = None
        if (self.peek() == "." and self.i + 1 < len(self.toks)
                and self.toks[self.i + 1] not in _QTY_METHODS):
            # Qualified form: attributes["domain"].name -> "domain/name",
            # with a fallback to the bare name for flat attribute maps
            # (capacity gets the identical treatment: cel-go exposes
            # device.capacity['<domain>'].<name> with quantity values).
            self.take()
            name = self.take()

        def lookup(d, field=field, key=key, name=name):
            mapping = getattr(d, field, None) or {}
            if name is None:
                return mapping.get(key, MISSING)
            return mapping.get(f"{key}/{name}", mapping.get(name, MISSING))

        return self.postfix(lookup)

    def postfix(self, base: _Fn) -> _Fn:
        """Chained quantity method calls: .isGreaterThan(q) etc., applied
        to whatever value `base` yields (the k8s CEL quantity library the
        reference's bats specs rely on, e.g.
        device.capacity['nvidia.com'].memory.isGreaterThan(quantity("10Gi")))."""
        fn = base
        while (self.peek() == "." and self.i + 1 < len(self.toks)
               and self.toks[self.i + 1] in _QTY_METHODS):
            self.take()
            method = self.take()
            self.take("(")
            arg = self.expr()
            self.take(")")

            def call(d, fn=fn, method=method, arg=arg):
                v, a = fn(d), arg(d)
                if isinstance(v, _Missing) or isinstance(a, _Missing):
                    return MISSING
                return _qty_method(method, v, a)

            fn = call
        return fn


@functools.lru_cache(maxsize=1024)
def compile_expression(expression: str) -> _Fn:
    """Compile one selector expression to a device -> bool-ish closure."""
    c = _Compiler(_tokenize(expression))
    fn = c.expr()
    if c.peek() is not None:
        raise CelError(f"trailing tokens at {c.peek()!r}")
    return fn


def evaluate(expression: str, device) -> bool:
    """Evaluate one selector expression against a device-like object
    (needs .driver and .attributes / .capacity mappings)."""
    return bool(compile_expression(expression)(device))


def matches(expressions, device) -> bool:
    """All-of over a selector list (DRA ANDs multiple selectors)."""
    return all(evaluate(e, device) for e in expressions)
