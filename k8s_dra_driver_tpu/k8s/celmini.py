"""Mini-CEL: the DRA device-selector subset of CEL, evaluated in-process.

Real clusters evaluate DeviceClass/request `selectors[].cel.expression`
with cel-go inside the scheduler (the reference's chart relies on this,
e.g. `device.driver == 'gpu.nvidia.com' && device.attributes[...]...`).
The sim's allocator uses this evaluator so the *shipped chart's actual
expressions* — not a parallel match-attribute encoding — decide matching.

Expressions compile once (lru-cached) to a closure evaluated per device,
so the allocator's device loop pays no repeated parsing.

Supported subset (everything our chart and the reference's use, plus the
obvious neighbors):

    device.driver, device.attributes["key"],
    device.attributes["domain"].name   (-> flat "domain/name" lookup),
    device.capacity["key"]
    literals: 'str' "str" ints (incl. negative) true false
    operators: == != < <= > >= && || !  and parentheses

Missing attributes make *every* comparison false — including `!=`. Real
cel-go errors on a missing-key access and DRA treats an erroring selector
as non-matching, so "absent attribute → device does not match" is the
faithful net behavior (a `!= -> true` convenience would match devices in
sim that a real scheduler would reject).
"""

from __future__ import annotations

import functools
import re
from typing import Any, Callable, List, Optional


class CelError(ValueError):
    pass


_TOKEN = re.compile(r"""
    \s*(
        '(?:[^'\\]|\\.)*' | "(?:[^"\\]|\\.)*"   # strings
      | -?\d+                                    # ints (incl. negative)
      | [A-Za-z_][A-Za-z0-9_]*                   # identifiers
      | == | != | <= | >= | && | \|\|            # two-char ops
      | [()\[\].!<>]                             # single-char ops
    )""", re.VERBOSE)


def _tokenize(expr: str) -> List[str]:
    out, pos = [], 0
    while pos < len(expr):
        m = _TOKEN.match(expr, pos)
        if not m:
            if expr[pos:].strip() == "":
                break
            raise CelError(f"bad token at {expr[pos:pos + 12]!r}")
        out.append(m.group(1))
        pos = m.end()
    return out


class _Missing:
    """Sentinel for absent attributes: comparisons never match."""

    def __repr__(self) -> str:  # pragma: no cover — debug aid
        return "<missing>"


MISSING = _Missing()

_Fn = Callable[[Any], Any]  # compiled node: device -> value


def _is_int(tok: str) -> bool:
    return tok.lstrip("-").isdigit() and tok != "-"


class _Compiler:
    """Recursive-descent compile to closures; runs once per expression."""

    def __init__(self, tokens: List[str]):
        self.toks = tokens
        self.i = 0

    def peek(self) -> Optional[str]:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def take(self, want: Optional[str] = None) -> str:
        tok = self.peek()
        if tok is None or (want is not None and tok != want):
            raise CelError(f"expected {want or 'token'}, got {tok!r}")
        self.i += 1
        return tok

    def expr(self) -> _Fn:
        fn = self.and_()
        while self.peek() == "||":
            self.take()
            rhs = self.and_()
            fn = (lambda lhs, rhs: lambda d: bool(lhs(d)) or bool(rhs(d)))(fn, rhs)
        return fn

    def and_(self) -> _Fn:
        fn = self.unary()
        while self.peek() == "&&":
            self.take()
            rhs = self.unary()
            fn = (lambda lhs, rhs: lambda d: bool(lhs(d)) and bool(rhs(d)))(fn, rhs)
        return fn

    def unary(self) -> _Fn:
        if self.peek() == "!":
            self.take()
            inner = self.unary()
            return lambda d: not bool(inner(d))
        return self.cmp()

    _CMPS = {"==", "!=", "<", "<=", ">", ">="}

    def cmp(self) -> _Fn:
        lhs = self.term()
        op = self.peek()
        if op not in self._CMPS:
            return lhs
        self.take()
        rhs = self.term()

        def compare(d, lhs=lhs, rhs=rhs, op=op):
            a, b = lhs(d), rhs(d)
            if isinstance(a, _Missing) or isinstance(b, _Missing):
                # cel-go errors here and DRA counts the device as
                # non-matching — so every operator, != included, is false.
                return False
            # CEL compares like-typed values; coerce int-vs-str-of-int
            # since attribute wire values may arrive as strings.
            if isinstance(a, int) != isinstance(b, int):
                try:
                    a, b = int(a), int(b)
                except (TypeError, ValueError):
                    a, b = str(a), str(b)
            if op == "==":
                return a == b
            if op == "!=":
                return a != b
            if op == "<":
                return a < b
            if op == "<=":
                return a <= b
            if op == ">":
                return a > b
            return a >= b

        return compare

    def term(self) -> _Fn:
        tok = self.peek()
        if tok == "(":
            self.take()
            fn = self.expr()
            self.take(")")
            return fn
        if tok is None:
            raise CelError("unexpected end of expression")
        if tok[0] in "'\"":
            self.take()
            v = tok[1:-1]
            return lambda d: v
        if _is_int(tok):
            self.take()
            v = int(tok)
            return lambda d: v
        if tok == "true":
            self.take()
            return lambda d: True
        if tok == "false":
            self.take()
            return lambda d: False
        if tok == "device":
            return self.device_path()
        raise CelError(f"unsupported term {tok!r}")

    def device_path(self) -> _Fn:
        self.take("device")
        self.take(".")
        field = self.take()
        if field == "driver":
            return lambda d: getattr(d, "driver", MISSING)
        if field not in ("attributes", "capacity"):
            raise CelError(f"unsupported device field {field!r}")
        self.take("[")
        key_tok = self.take()
        if key_tok[0] not in "'\"":
            raise CelError(f"map key must be a string literal, got {key_tok!r}")
        key = key_tok[1:-1]
        self.take("]")
        name = None
        if self.peek() == ".":
            # Qualified form: attributes["domain"].name -> "domain/name",
            # with a fallback to the bare name for flat attribute maps.
            self.take()
            name = self.take()

        def lookup(d, field=field, key=key, name=name):
            mapping = getattr(d, field, None) or {}
            if name is None:
                return mapping.get(key, MISSING)
            return mapping.get(f"{key}/{name}", mapping.get(name, MISSING))

        return lookup


@functools.lru_cache(maxsize=1024)
def compile_expression(expression: str) -> _Fn:
    """Compile one selector expression to a device -> bool-ish closure."""
    c = _Compiler(_tokenize(expression))
    fn = c.expr()
    if c.peek() is not None:
        raise CelError(f"trailing tokens at {c.peek()!r}")
    return fn


def evaluate(expression: str, device) -> bool:
    """Evaluate one selector expression against a device-like object
    (needs .driver and .attributes / .capacity mappings)."""
    return bool(compile_expression(expression)(device))


def matches(expressions, device) -> bool:
    """All-of over a selector list (DRA ANDs multiple selectors)."""
    return all(evaluate(e, device) for e in expressions)
