"""Core + DRA object kinds (the subset the driver exercises).

Models the resource.k8s.io v1beta1 DRA surface the reference programs
against — ResourceSlice/ResourceClaim/DeviceClass with KEP-4815 counter
sets — plus the core kinds (Pod, Node, DaemonSet) the ComputeDomain stack
manipulates. Field names follow the k8s API in snake_case.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from k8s_dra_driver_tpu.k8s.conditions import Condition
from k8s_dra_driver_tpu.k8s.objects import K8sObject, ObjectMeta

# Kind names --------------------------------------------------------------

EVENT = "Event"
POD = "Pod"
NODE = "Node"
DAEMON_SET = "DaemonSet"
DEPLOYMENT = "Deployment"
RESOURCE_CLAIM = "ResourceClaim"
RESOURCE_CLAIM_TEMPLATE = "ResourceClaimTemplate"
RESOURCE_SLICE = "ResourceSlice"
DEVICE_CLASS = "DeviceClass"
COMPUTE_DOMAIN = "ComputeDomain"
COMPUTE_DOMAIN_CLIQUE = "ComputeDomainClique"
VALIDATING_WEBHOOK_CONFIGURATION = "ValidatingWebhookConfiguration"


# -- DRA building blocks ---------------------------------------------------

@dataclass
class OpaqueDeviceConfig:
    """Per-driver opaque config blob attached to a request."""

    driver: str = ""
    parameters: Dict[str, Any] = field(default_factory=dict)


@dataclass
class DeviceClaimConfig:
    requests: List[str] = field(default_factory=list)  # empty = all requests
    opaque: Optional[OpaqueDeviceConfig] = None
    # Where this config came from: "claim" or "class" — drives precedence
    # (/root/reference/cmd/gpu-kubelet-plugin/device_state.go:1399-1463).
    source: str = "claim"  # tpulint: disable=wire-drift -- provenance tag, not wire data: the decode *context* (claim vs class doc) supplies it


@dataclass
class DeviceRequest:
    name: str = ""
    device_class_name: str = ""
    allocation_mode: str = "ExactCount"  # or "All"
    count: int = 1
    # Legacy sim-only attr=value strings; never wire-encoded.
    selectors: List[str] = field(default_factory=list)  # tpulint: disable=wire-drift -- deliberately one-way: encode raises on legacy selectors (no wire form), decode yields CEL only
    # Real DRA selectors[].cel.expression strings — tagged at manifest
    # parse time (the k8s shape {cel: {expression}}) so the allocator
    # never has to sniff which language a string is in.
    cel_selectors: List[str] = field(default_factory=list)


@dataclass
class DeviceTaint:
    key: str = ""
    value: str = ""
    effect: str = "NoSchedule"  # or NoExecute


# Well-known device-taint keys the tpu kubelet plugin publishes (and the
# allocator/controller consume). Chip-level silicon faults vs fabric-level
# link faults keep distinct keys so an operator — and the mesh compiler,
# which must route AROUND a dead link rather than drop its endpoint chips
# — can tell them apart.
UNHEALTHY_TAINT_KEY = "tpu.google.com/unhealthy"
ICI_LINK_TAINT_KEY = "tpu.google.com/ici-link-unhealthy"


@dataclass
class Counter:
    value: int = 0


@dataclass
class CounterSet:
    """KEP-4815 shared counters on a ResourceSlice."""

    name: str = ""
    counters: Dict[str, Counter] = field(default_factory=dict)


@dataclass
class DeviceCounterConsumption:
    counter_set: str = ""
    counters: Dict[str, Counter] = field(default_factory=dict)


@dataclass
class Device:
    name: str = ""
    attributes: Dict[str, Any] = field(default_factory=dict)
    capacity: Dict[str, Any] = field(default_factory=dict)
    taints: List[DeviceTaint] = field(default_factory=list)
    consumes_counters: List[DeviceCounterConsumption] = field(default_factory=list)


@dataclass
class ResourcePool:
    name: str = ""
    generation: int = 0
    resource_slice_count: int = 1


@dataclass
class DeviceRequestAllocationResult:
    request: str = ""
    driver: str = ""
    pool: str = ""
    device: str = ""


@dataclass
class AllocationResult:
    devices: List[DeviceRequestAllocationResult] = field(default_factory=list)
    node_name: str = ""


@dataclass
class ResourceClaimConsumer:
    kind: str = "Pod"
    name: str = ""
    uid: str = ""


# -- events ------------------------------------------------------------------

EVENT_TYPE_NORMAL = "Normal"
EVENT_TYPE_WARNING = "Warning"


@dataclass
class ObjectReference:
    """Pointer to the object an Event narrates (corev1.ObjectReference)."""

    kind: str = ""
    name: str = ""
    namespace: str = ""
    uid: str = ""


@dataclass
class Event(K8sObject):
    """corev1.Event, the subset `kubectl describe` renders: the involved
    object, a CamelCase reason, a human message, and client-go-style
    aggregation fields (count / firstTimestamp / lastTimestamp)."""

    kind: str = EVENT
    involved_object: ObjectReference = field(default_factory=ObjectReference)
    type: str = EVENT_TYPE_NORMAL   # Normal | Warning
    reason: str = ""
    message: str = ""
    source: str = ""                # emitting component
    count: int = 1
    first_timestamp: float = 0.0
    last_timestamp: float = 0.0
    # Trace of the latest occurrence — links a describe/explain row to the
    # /debug/traces span set that produced it (empty when none was active).
    trace_id: str = ""  # tpulint: disable=wire-drift -- sim-only provenance link, not corev1 wire data


# -- utilization telemetry ---------------------------------------------------

@dataclass
class UtilizationSummary:
    """Compact window roll-up the telemetry plane CASes onto ResourceClaim
    and ComputeDomain status (`utilizationSummary` on the wire): the p95s
    of the sampling window, quantized at write time so steady load does
    not churn resourceVersions or watch fan-out. Equality is the change
    gate's comparison, so it covers CONTENT only: ``updated_at`` (a
    timestamp) and ``window_seconds``/``samples`` (which grow every tick
    while the ring fills — comparing them would make even constant load
    write status once per sample for a whole window) are excluded."""

    window_seconds: float = field(default=0.0, compare=False)
    samples: int = field(default=0, compare=False)
    duty_cycle_p95: float = 0.0        # [0, 1]
    hbm_used_p95_bytes: int = 0
    hbm_total_bytes: int = 0
    ici_utilization_p95: float = 0.0   # [0, 1]; domains only, 0 for claims
    updated_at: float = field(default=0.0, compare=False)


@dataclass
class ObservedFootprint:
    """What a claim's lifecycle actually cost, written once by the
    critical-path profiler (`status.observedFootprint` on the wire) so
    a recommender can right-size the next instance of the workload
    straight off the object. Values are quantized at write time (same
    change-gate discipline as UtilizationSummary); ``updated_at`` is
    excluded from equality so a re-profile that lands on identical
    quantized values writes nothing."""

    # Phase name -> seconds on the claim's critical path (virtual clock),
    # quantized; keys are the lifecycle analyzer's closed phase vocabulary.
    phase_seconds: Dict[str, float] = field(default_factory=dict)
    peak_hbm_bytes: int = 0
    duty_p95: float = 0.0              # [0, 1]
    updated_at: float = field(default=0.0, compare=False)


# -- kinds ------------------------------------------------------------------

@dataclass
class ResourceClaim(K8sObject):
    kind: str = RESOURCE_CLAIM
    requests: List[DeviceRequest] = field(default_factory=list)
    config: List[DeviceClaimConfig] = field(default_factory=list)
    # Contention-plane priority tier (spec.priorityTier on the wire).
    # The effective tier is max(claim, consumer pod, namespace
    # TenantQuota floor); see docs/reference/preemption.md.
    priority_tier: int = 0
    allocation: Optional[AllocationResult] = None
    reserved_for: List[ResourceClaimConsumer] = field(default_factory=list)
    # Typed lifecycle conditions (Allocated, Prepared), mirrored from the
    # scheduler/kubelet the way claim.status.conditions carries them upstream.
    conditions: List[Condition] = field(default_factory=list)
    # Windowed utilization roll-up written by the telemetry aggregator
    # (status.utilizationSummary upstream-style); None until the claim's
    # chips produced a full first summary.
    utilization: Optional[UtilizationSummary] = None
    # Critical-path profile written once by the lifecycle analyzer when
    # the claim's consumer reaches Running (status.observedFootprint);
    # the recommender's input signal.
    observed_footprint: Optional[ObservedFootprint] = None


CLAIM_COND_ALLOCATED = "Allocated"
CLAIM_COND_PREPARED = "Prepared"


@dataclass
class WebhookClientConfig:
    """Where the apiserver dials the webhook. `url` for out-of-cluster
    endpoints (tests / kind), service ref for in-cluster; ca_bundle is
    base64 PEM the apiserver must verify the serving cert against."""

    url: str = ""
    service_name: str = ""
    service_namespace: str = ""
    service_path: str = ""
    ca_bundle: str = ""


@dataclass
class WebhookRule:
    api_groups: List[str] = field(default_factory=list)
    api_versions: List[str] = field(default_factory=list)
    operations: List[str] = field(default_factory=list)  # CREATE/UPDATE/*
    resources: List[str] = field(default_factory=list)   # plurals


@dataclass
class RegisteredWebhook:
    name: str = ""
    client_config: WebhookClientConfig = field(default_factory=WebhookClientConfig)
    rules: List[WebhookRule] = field(default_factory=list)
    failure_policy: str = "Fail"  # or Ignore
    side_effects: str = "None"
    admission_review_versions: List[str] = field(default_factory=lambda: ["v1"])


@dataclass
class ValidatingWebhookConfiguration(K8sObject):
    kind: str = VALIDATING_WEBHOOK_CONFIGURATION
    webhooks: List[RegisteredWebhook] = field(default_factory=list)


@dataclass
class ResourceClaimTemplate(K8sObject):
    kind: str = RESOURCE_CLAIM_TEMPLATE
    spec_meta_labels: Dict[str, str] = field(default_factory=dict)
    spec_meta_annotations: Dict[str, str] = field(default_factory=dict)
    requests: List[DeviceRequest] = field(default_factory=list)
    config: List[DeviceClaimConfig] = field(default_factory=list)


@dataclass
class ResourceSlice(K8sObject):
    kind: str = RESOURCE_SLICE
    driver: str = ""
    node_name: str = ""
    pool: ResourcePool = field(default_factory=ResourcePool)
    devices: List[Device] = field(default_factory=list)
    shared_counters: List[CounterSet] = field(default_factory=list)


@dataclass
class DeviceClass(K8sObject):
    kind: str = DEVICE_CLASS
    driver: str = ""  # selector: device.driver == driver
    # Attribute equality selectors, the CEL-expression stand-in.
    match_attributes: Dict[str, Any] = field(default_factory=dict)  # tpulint: disable=wire-drift -- encode compiles match-attrs INTO CEL expressions; decode returns them via cel_selectors (semantic round-trip)
    # Real DRA selector expressions (selectors[].cel.expression); when set,
    # evaluated by k8s.celmini — the same strings the chart ships.
    cel_selectors: List[str] = field(default_factory=list)
    config: List[DeviceClaimConfig] = field(default_factory=list)


@dataclass
class PodResourceClaimRef:
    name: str = ""                         # name within the pod spec
    resource_claim_name: str = ""          # direct claim reference
    resource_claim_template_name: str = "" # template to instantiate


@dataclass
class Container:
    name: str = "main"
    image: str = ""
    env: Dict[str, str] = field(default_factory=dict)
    # Downward-API env: env var name -> fieldPath (metadata.name,
    # metadata.namespace, status.podIP); the kubelet materializes these from
    # the pod at start.
    downward_env: Dict[str, str] = field(default_factory=dict)
    command: List[str] = field(default_factory=list)
    # Exec readiness probe command; the sim's probe loop honors agent state,
    # this records the manifest-level probe (reference
    # templates/compute-domain-daemon.tmpl.yaml:75-100).
    readiness_probe: List[str] = field(default_factory=list)


@dataclass
class PodCondition:
    type: str = ""
    status: str = "False"


@dataclass
class Pod(K8sObject):
    kind: str = POD
    node_name: str = ""
    containers: List[Container] = field(default_factory=list)
    resource_claims: List[PodResourceClaimRef] = field(default_factory=list)
    # Contention-plane priority tier (spec.priorityTier on the wire);
    # defaulted/raised by the namespace's TenantQuota priorityFloor.
    priority_tier: int = 0
    phase: str = "Pending"
    pod_ip: str = ""
    ready: bool = False
    conditions: List[PodCondition] = field(default_factory=list)
    # What the container runtime materialized from CDI specs (sim kubelet).
    injected_env: Dict[str, str] = field(default_factory=dict)
    injected_devices: List[str] = field(default_factory=list)


@dataclass
class NodeTaint:
    key: str = ""
    value: str = ""
    effect: str = "NoSchedule"


@dataclass
class Node(K8sObject):
    kind: str = NODE
    taints: List[NodeTaint] = field(default_factory=list)
    addresses: Dict[str, str] = field(default_factory=dict)
    allocatable: Dict[str, int] = field(default_factory=dict)


@dataclass
class PodTemplate:
    labels: Dict[str, str] = field(default_factory=dict)
    containers: List[Container] = field(default_factory=list)
    resource_claims: List[PodResourceClaimRef] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)


@dataclass
class DaemonSet(K8sObject):
    kind: str = DAEMON_SET
    selector: Dict[str, str] = field(default_factory=dict)
    node_selector: Dict[str, str] = field(default_factory=dict)
    template: PodTemplate = field(default_factory=PodTemplate)
    desired: int = 0
    ready: int = 0


@dataclass
class Deployment(K8sObject):
    kind: str = DEPLOYMENT
    replicas: int = 1
    selector: Dict[str, str] = field(default_factory=dict)
    template: PodTemplate = field(default_factory=PodTemplate)
    ready: int = 0
