"""In-memory Kubernetes API machinery.

The reference drives a real API server through generated clientsets and
tests against a generated fake (/root/reference/pkg/nvidia.com/clientset/
versioned/fake/). This package is the analog for a Python driver with no
cluster in the loop: a faithful-enough API server core — namespaced stores,
resourceVersion optimistic concurrency, finalizer-aware deletion, watches —
plus informers/listers on top. Controllers and plugins are written against
these interfaces only, so pointing them at a real API server later is an
adapter, not a rewrite.
"""

from k8s_dra_driver_tpu.k8s.objects import (  # noqa: F401
    ApiError,
    ConflictError,
    AlreadyExistsError,
    NotFoundError,
    K8sObject,
    ObjectMeta,
    OwnerReference,
)
from k8s_dra_driver_tpu.k8s.store import APIServer, WatchEvent  # noqa: F401
from k8s_dra_driver_tpu.k8s.informer import Informer  # noqa: F401
