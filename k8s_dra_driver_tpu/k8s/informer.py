"""Informer: list+watch cache with event handlers and listers.

The shape of client-go's SharedInformer the reference leans on
(/root/reference/pkg/nvidia.com/informers/...): a thread consumes the watch
stream into a local cache; handlers fire on add/update/delete; listers read
the cache without touching the API server.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Callable, Dict, List, Optional

from k8s_dra_driver_tpu.k8s.objects import K8sObject
from k8s_dra_driver_tpu.k8s.store import APIServer, WatchEvent

log = logging.getLogger(__name__)

Handler = Callable[[Optional[K8sObject], K8sObject], None]
# add: (None, new); update: (old, new); delete: (old, old)

# Informer caches are built once at start() and have no relist path: a
# dropped event means permanent, silent divergence. So informers subscribe
# with a much deeper bound than the store's 1024 default — bounded (a dead
# handler thread still cannot grow memory forever) but far beyond any
# realistic burst between handler dispatches.
INFORMER_WATCH_QUEUE_MAXSIZE = 65536


class Informer:
    def __init__(
        self,
        api: APIServer,
        kind: str,
        field_name: Optional[str] = None,
        field_namespace: Optional[str] = None,
    ):
        """field_name/field_namespace narrow the list+watch to one object —
        the reference's single-pod field selector (podmanager.go:47-53);
        the server then never streams unrelated churn to this informer."""
        self.api = api
        self.kind = kind
        self.field_name = field_name
        self.field_namespace = field_namespace
        self._cache: Dict[str, K8sObject] = {}  # tpulint: guarded-by=_mu
        self._mu = threading.RLock()
        self._on_add: List[Handler] = []
        self._on_update: List[Handler] = []
        self._on_delete: List[Handler] = []
        self._thread: Optional[threading.Thread] = None
        self._queue: Optional["queue.Queue[WatchEvent]"] = None
        self._stop = threading.Event()
        self._synced = threading.Event()

    def add_event_handler(
        self,
        on_add: Optional[Handler] = None,
        on_update: Optional[Handler] = None,
        on_delete: Optional[Handler] = None,
    ) -> None:
        if on_add:
            self._on_add.append(on_add)
        if on_update:
            self._on_update.append(on_update)
        if on_delete:
            self._on_delete.append(on_delete)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("informer already started")
        objs, self._queue = self.api.list_and_watch(
            self.kind, name=self.field_name, namespace=self.field_namespace,
            maxsize=INFORMER_WATCH_QUEUE_MAXSIZE,
        )
        with self._mu:
            for o in objs:
                self._cache[o.key] = o
        for o in objs:
            self._dispatch(self._on_add, None, o)
        self._synced.set()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"informer-{self.kind}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._queue is not None:
            self.api.stop_watch(self.kind, self._queue)
            try:
                # Wake the loop; a full (bounded) queue is fine — the 0.5s
                # get timeout observes _stop on its own.
                self._queue.put_nowait(None)  # type: ignore[arg-type]
            except queue.Full:
                pass
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def wait_for_cache_sync(self, timeout: float = 10.0) -> bool:
        return self._synced.wait(timeout)

    def _run(self) -> None:
        assert self._queue is not None
        while not self._stop.is_set():
            try:
                ev = self._queue.get(timeout=0.5)
            except queue.Empty:
                continue
            if ev is None:
                break
            self._handle(ev)

    def _handle(self, ev: WatchEvent) -> None:
        key = ev.obj.key
        with self._mu:
            old = self._cache.get(key)
            if ev.type == "DELETED":
                self._cache.pop(key, None)
            else:
                self._cache[key] = ev.obj
        if ev.type == "ADDED" and old is None:
            self._dispatch(self._on_add, None, ev.obj)
        elif ev.type == "DELETED":
            self._dispatch(self._on_delete, old or ev.obj, old or ev.obj)
        else:
            self._dispatch(self._on_update, old, ev.obj)

    @staticmethod
    def _dispatch(handlers: List[Handler], old: Optional[K8sObject], new: K8sObject) -> None:
        for h in handlers:
            try:
                h(old, new)
            except Exception:  # noqa: BLE001 — handler bugs must not kill the informer
                log.exception("informer handler failed for %s", new.key)

    # -- lister ------------------------------------------------------------
    #
    # The cache holds the store's published frozen snapshots, so listers
    # hand out references for free — same contract as APIServer.get/list.
    # copy=True is the explicit opt-out for a caller that wants a private
    # mutable copy.

    def get(self, name: str, namespace: str = "",
            copy: bool = False) -> Optional[K8sObject]:
        key = f"{namespace}/{name}" if namespace else name
        with self._mu:
            obj = self._cache.get(key)
        if obj is not None and copy:
            return obj.deepcopy()
        return obj

    def list(
        self,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        copy: bool = False,
    ) -> List[K8sObject]:
        with self._mu:
            out = []
            for obj in self._cache.values():
                if namespace is not None and obj.meta.namespace != namespace:
                    continue
                if label_selector and not all(
                    obj.meta.labels.get(k) == v for k, v in label_selector.items()
                ):
                    continue
                out.append(obj.deepcopy() if copy else obj)
            out.sort(key=lambda o: o.key)
            return out
