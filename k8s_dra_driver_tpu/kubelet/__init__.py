"""Kubelet-facing gRPC seam: the DRA plugin protocol + plugin registration.

A real kubelet discovers plugins by scanning its plugin registry directory
for unix sockets, handshakes over the `pluginregistration.Registration`
service, then drives `DRAPlugin.NodePrepareResources` /
`NodeUnprepareResources` on the advertised endpoint (the reference reaches
this seam through the vendored kubeletplugin helper,
/root/reference/cmd/gpu-kubelet-plugin/driver.go:131-149).

Modules:
    draserver.py    — serves both protocols over unix sockets
    kubeletstub.py  — a kubelet test double driving the same wire
    *_pb2.py        — protoc-generated message bindings (protos/*.proto)
"""

from k8s_dra_driver_tpu.kubelet.draserver import DRAGrpcServer  # noqa: F401
