"""gRPC DRA plugin server — the kubelet-facing seam of both node plugins.

Serves two unix-domain sockets, matching the kubelet's conventions
(reference: vendored k8s.io/dynamic-resource-allocation/kubeletplugin,
draplugin.go — KubeletPluginsDir/KubeletRegistryDir):

    <registrar_dir>/<driver>-reg.sock   pluginregistration.Registration
    <plugin_data_dir>/dra.sock          DRAPlugin (v1 AND v1beta1 service
                                        names, so any kubelet >= 1.31 can
                                        drive us)

The DRA service resolves each wire Claim{namespace,uid,name} to the full
ResourceClaim through the API server — the same resolution the reference
helper performs before invoking the driver — then delegates to the
driver's prepare/unprepare and translates results back to wire Devices.

No grpcio-tools in the image: service plumbing uses
grpc.method_handlers_generic_handler over the protoc-generated messages.
"""

from __future__ import annotations

import logging
import os
import threading
from concurrent import futures
from typing import Dict, List, Optional

import grpc

from k8s_dra_driver_tpu.k8s.core import RESOURCE_CLAIM, ResourceClaim
from k8s_dra_driver_tpu.kubelet import dra_v1_pb2, dra_v1beta1_pb2
from k8s_dra_driver_tpu.kubelet import pluginregistration_pb2 as reg_pb2

log = logging.getLogger(__name__)

DRA_SOCKET_NAME = "dra.sock"
SUPPORTED_VERSIONS = ["v1beta1", "v1"]

_V1_SERVICE = "k8s.io.kubelet.pkg.apis.dra.v1.DRAPlugin"
_V1BETA1_SERVICE = "k8s.io.kubelet.pkg.apis.dra.v1beta1.DRAPlugin"
_REG_SERVICE = "pluginregistration.Registration"


def _is_retryable(err: Exception) -> bool:
    try:
        from k8s_dra_driver_tpu.plugins.computedomain.computedomain import (
            RetryableError,
        )
        return isinstance(err, RetryableError)
    except ImportError:  # pragma: no cover
        return False


class _DRAService:
    """Version-agnostic service body; `pb` selects the message module."""

    def __init__(self, server: "DRAGrpcServer", pb):
        self.server = server
        self.pb = pb

    # -- claim resolution ---------------------------------------------------

    def _resolve_claim(self, wire_claim) -> ResourceClaim:
        rc = self.server.api.get(
            RESOURCE_CLAIM, wire_claim.name, wire_claim.namespace
        )
        if wire_claim.uid and rc.meta.uid and rc.meta.uid != wire_claim.uid:
            raise ValueError(
                f"claim {wire_claim.namespace}/{wire_claim.name}: uid mismatch "
                f"(kubelet has {wire_claim.uid}, apiserver has {rc.meta.uid})"
            )
        return rc

    def _wire_devices(self, claim: ResourceClaim, result) -> List:
        """Map a driver prepare result onto wire Device entries keyed by the
        claim's allocation (pool/device names come from the allocation; CDI
        ids from what the driver actually prepared)."""
        alloc = claim.allocation.devices if claim.allocation else []
        prepared = getattr(result, "devices", None)
        out = []
        if prepared:
            by_name = {d.name: d for d in prepared}
            for ar in alloc:
                pd = by_name.get(ar.device)
                out.append(self.pb.Device(
                    request_names=[ar.request] if ar.request else [],
                    pool_name=ar.pool,
                    device_name=ar.device,
                    cdi_device_ids=list(pd.cdi_device_ids) if pd else [],
                ))
            return out
        # Flat CDI-id list (compute-domain driver): attach to the first
        # allocated device; the runtime applies each CDI id once.
        ids = list(getattr(result, "cdi_device_ids", None) or result or [])
        for i, ar in enumerate(alloc):
            out.append(self.pb.Device(
                request_names=[ar.request] if ar.request else [],
                pool_name=ar.pool,
                device_name=ar.device,
                cdi_device_ids=ids if i == 0 else [],
            ))
        return out

    # -- rpc handlers -------------------------------------------------------

    def node_prepare_resources(self, request, context):
        resp = self.pb.NodePrepareResourcesResponse()
        claims: Dict[str, ResourceClaim] = {}
        for wc in request.claims:
            try:
                claims[wc.uid] = self._resolve_claim(wc)
            except Exception as e:  # noqa: BLE001 — per-claim error contract
                resp.claims[wc.uid].error = f"resolve claim: {e}"
        if claims:
            results = self.server.driver.prepare_resource_claims(
                list(claims.values())
            )
            for uid, result in results.items():
                if isinstance(result, Exception):
                    kind = "retryable" if _is_retryable(result) else "permanent"
                    resp.claims[uid].error = f"{kind}: {result}"
                else:
                    resp.claims[uid].devices.extend(
                        self._wire_devices(claims[uid], result)
                    )
        return resp

    def node_unprepare_resources(self, request, context):
        resp = self.pb.NodeUnprepareResourcesResponse()
        uids = [wc.uid for wc in request.claims]
        results = self.server.driver.unprepare_resource_claims(uids)
        for uid in uids:
            err = results.get(uid)
            resp.claims[uid].error = str(err) if err is not None else ""
        return resp

    def handlers(self, service_name: str) -> grpc.GenericRpcHandler:
        pb = self.pb
        return grpc.method_handlers_generic_handler(service_name, {
            "NodePrepareResources": grpc.unary_unary_rpc_method_handler(
                self.node_prepare_resources,
                request_deserializer=pb.NodePrepareResourcesRequest.FromString,
                response_serializer=pb.NodePrepareResourcesResponse.SerializeToString,
            ),
            "NodeUnprepareResources": grpc.unary_unary_rpc_method_handler(
                self.node_unprepare_resources,
                request_deserializer=pb.NodeUnprepareResourcesRequest.FromString,
                response_serializer=pb.NodeUnprepareResourcesResponse.SerializeToString,
            ),
        })


class _RegistrationService:
    def __init__(self, server: "DRAGrpcServer"):
        self.server = server

    def get_info(self, request, context):
        return reg_pb2.PluginInfo(
            type="DRAPlugin",
            name=self.server.driver_name,
            endpoint=self.server.dra_socket_path,
            supported_versions=SUPPORTED_VERSIONS,
        )

    def notify_registration_status(self, request, context):
        self.server.registered = bool(request.plugin_registered)
        if request.error:
            log.error("kubelet rejected plugin registration: %s", request.error)
        else:
            log.info("kubelet registration status: registered=%s",
                     self.server.registered)
        return reg_pb2.RegistrationStatusResponse()

    def handlers(self) -> grpc.GenericRpcHandler:
        return grpc.method_handlers_generic_handler(_REG_SERVICE, {
            "GetInfo": grpc.unary_unary_rpc_method_handler(
                self.get_info,
                request_deserializer=reg_pb2.InfoRequest.FromString,
                response_serializer=reg_pb2.PluginInfo.SerializeToString,
            ),
            "NotifyRegistrationStatus": grpc.unary_unary_rpc_method_handler(
                self.notify_registration_status,
                request_deserializer=reg_pb2.RegistrationStatus.FromString,
                response_serializer=(
                    reg_pb2.RegistrationStatusResponse.SerializeToString
                ),
            ),
        })


class DRAGrpcServer:
    """Runs the registration + DRA gRPC services for one driver."""

    def __init__(
        self,
        driver,
        api,
        plugin_data_dir: str,
        registrar_dir: str,
        driver_name: Optional[str] = None,
    ):
        self.driver = driver
        self.api = api
        self.driver_name = driver_name or driver.driver_name
        self.plugin_data_dir = plugin_data_dir
        self.registrar_dir = registrar_dir
        self.registered = False
        self._dra_server: Optional[grpc.Server] = None
        self._reg_server: Optional[grpc.Server] = None
        self._lock = threading.Lock()

    @property
    def dra_socket_path(self) -> str:
        return os.path.join(self.plugin_data_dir, DRA_SOCKET_NAME)

    @property
    def registration_socket_path(self) -> str:
        return os.path.join(self.registrar_dir, f"{self.driver_name}-reg.sock")

    def start(self) -> "DRAGrpcServer":
        os.makedirs(self.plugin_data_dir, exist_ok=True)
        os.makedirs(self.registrar_dir, exist_ok=True)
        for path in (self.dra_socket_path, self.registration_socket_path):
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass

        dra = grpc.server(futures.ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="dra-grpc"))
        dra.add_generic_rpc_handlers((
            _DRAService(self, dra_v1_pb2).handlers(_V1_SERVICE),
            _DRAService(self, dra_v1beta1_pb2).handlers(_V1BETA1_SERVICE),
        ))
        dra.add_insecure_port(f"unix://{self.dra_socket_path}")
        dra.start()
        self._dra_server = dra

        reg = grpc.server(futures.ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="reg-grpc"))
        reg.add_generic_rpc_handlers((_RegistrationService(self).handlers(),))
        reg.add_insecure_port(f"unix://{self.registration_socket_path}")
        reg.start()
        self._reg_server = reg
        log.info("DRA gRPC up: dra=%s registrar=%s",
                 self.dra_socket_path, self.registration_socket_path)
        return self

    def stop(self, grace: float = 2.0) -> None:
        with self._lock:
            for srv in (self._reg_server, self._dra_server):
                if srv is not None:
                    srv.stop(grace).wait()
            self._reg_server = self._dra_server = None
            for path in (self.dra_socket_path, self.registration_socket_path):
                try:
                    os.unlink(path)
                except FileNotFoundError:
                    pass
