"""ComputeDomain + ComputeDomainClique CRD types.

Reference shapes: ComputeDomain{Spec,Status,Node}
(/root/reference/api/nvidia.com/resource/v1beta1/computedomain.go:39-143,
numNodes semantics 63-93) and ComputeDomainClique + DaemonInfo
(computedomainclique.go:30-57). TPU re-interpretation: a ComputeDomain
assembles a multi-host ICI pod slice; cliques key on the ICI domain id
(sliceUID.partition) instead of the NVLink clusterUUID.cliqueID.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from k8s_dra_driver_tpu.k8s.conditions import Condition
from k8s_dra_driver_tpu.k8s.core import (
    COMPUTE_DOMAIN,
    COMPUTE_DOMAIN_CLIQUE,
    UtilizationSummary,
)
from k8s_dra_driver_tpu.k8s.objects import K8sObject
from k8s_dra_driver_tpu.pkg.meshgen import MeshBundle

COMPUTE_DOMAIN_FINALIZER = "resource.tpu.google.com/computedomain"

# Node label key the CD plugin sets (value = CD uid) at workload Prepare
# time; the controller's DaemonSet node-selects on it (follow-the-workload,
# /root/reference/cmd/compute-domain-kubelet-plugin/computedomain.go:372-400).
COMPUTE_DOMAIN_NODE_LABEL = "resource.tpu.google.com/computeDomain"

# Per-domain override for the MEGASCALE coordinator port the channel env
# advertises. Normally absent (the fixed well-known port is correct inside
# pod network namespaces); the controller sets it at DaemonSet render time
# when configured for dynamic allocation — loopback/sim deployments where
# every "pod" shares the host's port space and the fixed port may be taken.
COORDINATOR_PORT_ANNOTATION = "resource.tpu.google.com/coordinator-port"


CD_STATUS_READY = "Ready"
CD_STATUS_NOT_READY = "NotReady"
# Spec failed domain-bounds validation (the reference rejects domains over
# the 18-node IMEX limit, cmd/compute-domain-controller/main.go:55-60); no
# owned objects are rendered for a Rejected domain.
CD_STATUS_REJECTED = "Rejected"

# Default cap on hosts per domain, the 18-node IMEX-domain analog
# (/root/reference/cmd/compute-domain-controller/main.go:55-60). A v5e pod
# slice tops out at 64 hosts (v5e-256 = 64 hosts x 4 chips).
DEFAULT_MAX_NODES_PER_DOMAIN = 64

# Typed condition types on ComputeDomainStatus.conditions. `status.status`
# stays as the coarse summary string; the conditions carry the reasoned,
# transition-timestamped history `kubectl describe` renders.
CD_COND_VALIDATED = "Validated"   # spec passed domain-bounds validation
CD_COND_READY = "Ready"           # required member nodes registered + ready
CD_COND_DEGRADED = "Degraded"     # a member node publishes unhealthy devices

# Resize-epoch state-machine phases (ElasticComputeDomains). The record
# lives in ``ComputeDomainStatus.resize`` while an epoch is in flight and
# is CAS-persisted BEFORE each side-effecting step, so a crashed/restored
# controller resumes (or rolls back) instead of forgetting a half-resized
# domain. Absent record = no epoch in flight.
RESIZE_QUIESCING = "Quiescing"     # survivors' claims -> MigrationCheckpoint
RESIZE_PLACING = "Placing"         # new placement being computed/recorded
RESIZE_RESTARTING = "Restarting"   # awaiting recompiled bundle + re-prepare

# Why an epoch started — recorded on the resize record and on the
# DomainResizing/DomainHealed events.
RESIZE_TRIGGER_SPEC = "spec"       # operator edited spec.numNodes
RESIZE_TRIGGER_HEAL = "heal"       # member lease expired (host failure)
RESIZE_TRIGGER_GROW = "grow"       # healed domain growing back toward spec


@dataclass
class ComputeDomainChannelSpec:
    resource_claim_template_name: str = ""


@dataclass
class ComputeDomainSpec:
    # Number of hosts the domain must span before it reports Ready.
    # 0 means "size follows the workload" (the deprecated-numNodes semantics
    # the reference converged on, computedomain.go:63-93).
    num_nodes: int = 0
    # Optional requested slice shape, e.g. "4x4"; validated against what the
    # member hosts actually report.
    topology: str = ""
    channel: ComputeDomainChannelSpec = field(default_factory=ComputeDomainChannelSpec)


@dataclass
class ComputeDomainNode:
    name: str = ""
    ip_address: str = ""
    ici_domain: str = ""     # cliqueID analog
    worker_id: int = -1
    status: str = CD_STATUS_NOT_READY


@dataclass
class ComputeDomainPlacement:
    """The host-grid block the scheduler chose for this domain: a
    contiguous axis-aligned rectangle of hosts within ONE ICI domain's
    host grid (e.g. a 2x2 block at 0x0 of a v5e-16 slice). Recorded so
    the daemon's clique assembly — and any operator reading `describe` —
    sees placement that reflects real ICI adjacency, not just N nodes."""

    ici_domain: str = ""
    block_origin: str = ""   # host-grid coords, e.g. "0x0"
    block_shape: str = ""    # host-grid units, e.g. "2x2"
    nodes: List[str] = field(default_factory=list)  # row-major over block


@dataclass
class ComputeDomainResize:
    """One in-flight resize epoch: the phase pointer plus everything
    rollback needs (the prior placement and desired size, verbatim) and
    everything resume needs (the planned new placement, computed once at
    epoch start so a crash between quiesce and re-place replays the SAME
    decision instead of re-planning against drifted state)."""

    phase: str = ""                    # RESIZE_* constant
    trigger: str = ""                  # RESIZE_TRIGGER_* constant
    target_nodes: int = 0              # membership this epoch drives to
    lost_nodes: List[str] = field(default_factory=list)  # expired members
    new_placement: Optional[ComputeDomainPlacement] = None
    prior_placement: Optional[ComputeDomainPlacement] = None
    prior_desired: int = 0
    attempts: int = 0                  # bounded-retry counter (this target)
    started_at: float = 0.0            # orchestrator clock at epoch start


@dataclass
class ComputeDomainStatus:
    status: str = CD_STATUS_NOT_READY
    nodes: List[ComputeDomainNode] = field(default_factory=list)
    conditions: List[Condition] = field(default_factory=list)
    placement: Optional[ComputeDomainPlacement] = None
    # Elastic membership (ElasticComputeDomains): ``epoch`` counts
    # completed resize transitions (0 = never resized), ``desired_nodes``
    # is the CURRENT epoch's membership target — equal to spec.numNodes
    # normally, smaller after a host-failure heal until the host returns
    # (0 = follow spec). ``resize`` is the in-flight epoch record.
    epoch: int = 0
    desired_nodes: int = 0
    resize: Optional[ComputeDomainResize] = None
    # The compiled Placement→JAX mesh bundle (pkg/meshgen): topology-
    # aligned device order + axes + partition rules, (re-)emitted by the
    # controller on placement or link-health change and injected into
    # claiming containers as TPU_DRA_MESH_BUNDLE by the CDI handler.
    mesh_bundle: Optional[MeshBundle] = None
    # Windowed utilization roll-up over the domain's member hosts, written
    # by the telemetry aggregator (quantized + change-gated like the
    # claim-level summary); carries the domain ICI utilization p95.
    utilization: Optional[UtilizationSummary] = None


@dataclass
class ComputeDomain(K8sObject):
    kind: str = COMPUTE_DOMAIN
    spec: ComputeDomainSpec = field(default_factory=ComputeDomainSpec)
    status: ComputeDomainStatus = field(default_factory=ComputeDomainStatus)


@dataclass
class ComputeDomainDaemonInfo:
    node_name: str = ""
    ip_address: str = ""
    dns_name: str = ""
    # Stable per-domain index, CAS-allocated on the clique
    # (/root/reference/cmd/compute-domain-daemon/cdclique.go:350-372);
    # becomes TPU_WORKER_ID for the workload.
    index: int = -1
    ready: bool = False


@dataclass
class ComputeDomainClique(K8sObject):
    """Membership record for one ICI domain within one ComputeDomain.
    Named ``<cd-uid>.<ici-domain-hash>``."""

    kind: str = COMPUTE_DOMAIN_CLIQUE
    domain_uid: str = ""
    ici_domain: str = ""
    nodes: List[ComputeDomainDaemonInfo] = field(default_factory=list)
    # node name -> the worker index it held when it was deregistered
    # (lease expiry / heal-shrink). A re-joining node reclaims its former
    # slot when still free, so resize-epoch rollback — and any workload
    # keyed on TPU_WORKER_ID — sees the SAME worker identity across an
    # agent restart instead of a freshly CAS-allocated one.
    released: Dict[str, int] = field(default_factory=dict)

    def node_info(self, node_name: str) -> Optional[ComputeDomainDaemonInfo]:
        for n in self.nodes:
            if n.node_name == node_name:
                return n
        return None

    def used_indices(self) -> Dict[int, str]:
        return {n.index: n.node_name for n in self.nodes if n.index >= 0}
