"""Opaque per-claim device configs with Normalize/Validate + strict decoding.

Mirrors the reference's config taxonomy and its two-phase hygiene
(/root/reference/api/nvidia.com/resource/v1beta1/api.go:41-58): the webhook
strict-decodes at admission so bad configs fail fast; the kubelet plugin
re-decodes strictly at Prepare. Config classes:

- TpuConfig       (GpuConfig analog, gpuconfig.go:29-83): sharing policy.
- SubsliceConfig  (MigDeviceConfig analog, migconfig.go:28-70).
- VfioTpuConfig   (VfioDeviceConfig analog, vfiodeviceconfig.go:29-85).
- ComputeDomainChannelConfig / ComputeDomainDaemonConfig
  (computedomainconfig.go:28-86).
- Sharing: TimeSlicingConfig (Default/Short/Medium/Long) and
  MpsLikePremappedConfig — the TPU analog of MPS pinned-memory limits is a
  premapped-HBM budget per chip (sharing.go:28-260).
"""

from __future__ import annotations

from dataclasses import dataclass, field, fields as dc_fields
from typing import Any, Dict, Optional, Type

API_GROUP = "resource.tpu.google.com"
API_VERSION = f"{API_GROUP}/v1beta1"

TPU_DRIVER_NAME = "tpu.google.com"
COMPUTE_DOMAIN_DRIVER_NAME = "compute-domain.tpu.google.com"


class DecodeError(ValueError):
    pass


class ValidationError(ValueError):
    pass


TIME_SLICE_INTERVALS = ("Default", "Short", "Medium", "Long")


@dataclass
class TimeSlicingConfig:
    interval: str = "Default"

    def normalize(self) -> None:
        if not self.interval:
            self.interval = "Default"

    def validate(self) -> None:
        if self.interval not in TIME_SLICE_INTERVALS:
            raise ValidationError(
                f"unknown time-slice interval {self.interval!r}; "
                f"want one of {TIME_SLICE_INTERVALS}"
            )


# Admission-level absurdity bound for premapped budgets: far above any real
# chip's HBM (v5p is 95 GiB), so typo'd units (bytes-vs-KiB etc.) die at the
# webhook while the exact per-chip capacity check happens at Prepare, where
# the chip's hbm_bytes is known (the two-phase split of the reference's MPS
# pinned-memory validation, validate.go:25-106).
MAX_PREMAPPED_HBM_BYTES = 1 << 40  # 1 TiB


@dataclass
class MpsLikePremappedConfig:
    """Multi-process chip sharing via premapped HBM budgets.

    default_premapped_hbm_bytes applies to every sharing process; per-chip
    overrides key by chip index (the per-device pinned-memory-limit shape of
    the reference's MPS config, sharing.go:175-260).
    """

    default_premapped_hbm_bytes: int = 0
    per_chip_premapped_hbm_bytes: Dict[int, int] = field(default_factory=dict)

    def normalize(self) -> None:
        self.per_chip_premapped_hbm_bytes = {
            int(k): int(v) for k, v in self.per_chip_premapped_hbm_bytes.items()
        }

    def validate(self) -> None:
        if self.default_premapped_hbm_bytes < 0:
            raise ValidationError("default_premapped_hbm_bytes must be >= 0")
        if (self.default_premapped_hbm_bytes == 0
                and not self.per_chip_premapped_hbm_bytes):
            raise ValidationError(
                "premapped sharing needs a budget: set "
                "default_premapped_hbm_bytes > 0 or per-chip overrides"
            )
        if self.default_premapped_hbm_bytes > MAX_PREMAPPED_HBM_BYTES:
            raise ValidationError(
                f"default_premapped_hbm_bytes="
                f"{self.default_premapped_hbm_bytes} exceeds the "
                f"{MAX_PREMAPPED_HBM_BYTES} sanity bound (check units)"
            )
        for idx, v in self.per_chip_premapped_hbm_bytes.items():
            if idx < 0:
                raise ValidationError(
                    f"per_chip_premapped_hbm_bytes key {idx} must be >= 0"
                )
            if v <= 0:
                raise ValidationError(
                    f"per_chip_premapped_hbm_bytes[{idx}]={v} must be > 0"
                )
            if v > MAX_PREMAPPED_HBM_BYTES:
                raise ValidationError(
                    f"per_chip_premapped_hbm_bytes[{idx}]={v} exceeds the "
                    f"{MAX_PREMAPPED_HBM_BYTES} sanity bound (check units)"
                )


SHARING_STRATEGIES = ("TimeSlicing", "Premapped")


@dataclass
class SharingConfig:
    strategy: str = "TimeSlicing"
    time_slicing: Optional[TimeSlicingConfig] = None
    premapped: Optional[MpsLikePremappedConfig] = None

    def normalize(self) -> None:
        if self.strategy == "TimeSlicing" and self.time_slicing is None:
            self.time_slicing = TimeSlicingConfig()
        if self.time_slicing:
            self.time_slicing.normalize()
        if self.premapped:
            self.premapped.normalize()

    def validate(self) -> None:
        if self.strategy not in SHARING_STRATEGIES:
            raise ValidationError(
                f"unknown sharing strategy {self.strategy!r}; want one of {SHARING_STRATEGIES}"
            )
        if self.strategy == "TimeSlicing":
            if self.premapped is not None:
                raise ValidationError("premapped config set but strategy is TimeSlicing")
            assert self.time_slicing is not None
            self.time_slicing.validate()
        else:
            if self.time_slicing is not None and self.time_slicing.interval != "Default":
                raise ValidationError("time_slicing config set but strategy is Premapped")
            if self.premapped is None:
                raise ValidationError("strategy Premapped requires a premapped config")
            self.premapped.validate()


@dataclass
class DeviceConfig:
    """Base: every opaque config carries kind + normalize/validate."""

    def normalize(self) -> None:  # pragma: no cover — overridden
        pass

    def validate(self) -> None:  # pragma: no cover — overridden
        pass

    @property
    def kind(self) -> str:
        return type(self).__name__


@dataclass
class TpuConfig(DeviceConfig):
    sharing: Optional[SharingConfig] = None

    def normalize(self) -> None:
        if self.sharing:
            self.sharing.normalize()

    def validate(self) -> None:
        if self.sharing:
            self.sharing.validate()


@dataclass
class SubsliceConfig(DeviceConfig):
    """Config for dynamically-carved ICI subslices (DynamicSubslice gate)."""

    profile: str = ""        # e.g. "1x2"; empty = as allocated
    sharing: Optional[SharingConfig] = None

    def normalize(self) -> None:
        if self.sharing:
            self.sharing.normalize()

    def validate(self) -> None:
        if self.profile:
            from k8s_dra_driver_tpu.tpulib.types import parse_topology

            try:
                parse_topology(self.profile)
            except ValueError as e:
                raise ValidationError(str(e)) from None
        if self.sharing:
            self.sharing.validate()


IOMMU_MODES = ("auto", "legacy", "iommufd")


@dataclass
class VfioTpuConfig(DeviceConfig):
    """Passthrough config (PassthroughSupport gate).

    ``iommu_mode`` selects the IOMMU backend the workload sees (the
    reference's IOMMUBackendPolicy, api/.../iommu.go:22-76): ``legacy``
    pins the group-fd backend, ``iommufd`` requires /dev/iommu on the
    node, ``auto`` prefers iommufd when present (≈ PreferIommuFD).
    ``enable_api_device`` additionally injects the IOMMU API device into
    the container — /dev/iommu (iommufd) or /dev/vfio/vfio (legacy), the
    vfio-cdi.go:52-81 common edit."""

    iommu_mode: str = "auto"
    enable_api_device: bool = False

    def normalize(self) -> None:
        if not self.iommu_mode:
            self.iommu_mode = "auto"
        self.iommu_mode = self.iommu_mode.lower()

    def validate(self) -> None:
        if self.iommu_mode not in IOMMU_MODES:
            raise ValidationError(
                f"unknown iommu_mode {self.iommu_mode!r}; want one of {IOMMU_MODES}"
            )
        if not isinstance(self.enable_api_device, bool):
            raise ValidationError("enable_api_device must be a boolean")


@dataclass
class ComputeDomainChannelConfig(DeviceConfig):
    domain_id: str = ""  # uid of the ComputeDomain this channel belongs to
    # Which slice channel this claim binds (checkpointed; at most one claim
    # may hold a channel id per node — the assertImexChannelNotAllocated
    # analog, reference device_state.go:878-906).
    channel_id: int = 0
    # "All" CDI-injects every channel char device up to the plugin's
    # max-channel-count (the reference's AllocationMode: All,
    # device_state.go:690-733); "Single" injects only channel_id.
    allocation_mode: str = "All"

    def validate(self) -> None:
        if not self.domain_id:
            raise ValidationError("domain_id is required")
        if isinstance(self.channel_id, bool) or not isinstance(self.channel_id, int):
            raise ValidationError(
                f"channel_id must be an integer, got {self.channel_id!r}"
            )
        if self.channel_id < 0:
            raise ValidationError("channel_id must be >= 0")
        if self.allocation_mode not in ("All", "Single"):
            raise ValidationError(
                f"allocation_mode must be All or Single, got {self.allocation_mode!r}"
            )


def channel_domain_uid(claim) -> str:
    """The ComputeDomain uid a claim's channel config references, or ""
    for claims carrying no channel. THE rule identifying a pod as a
    domain worker — shared by the sim scheduler's host-grid steering and
    the rebalancer's demand detection so they can never drift."""
    for cc in claim.config:
        if (cc.opaque is not None
                and cc.opaque.driver == COMPUTE_DOMAIN_DRIVER_NAME
                and cc.opaque.parameters.get("kind")
                == "ComputeDomainChannelConfig"):
            return cc.opaque.parameters.get("domain_id", "")
    return ""


@dataclass
class ComputeDomainDaemonConfig(DeviceConfig):
    domain_id: str = ""

    def validate(self) -> None:
        if not self.domain_id:
            raise ValidationError("domain_id is required")


_KINDS: Dict[str, Type[DeviceConfig]] = {
    "TpuConfig": TpuConfig,
    "SubsliceConfig": SubsliceConfig,
    "VfioTpuConfig": VfioTpuConfig,
    "ComputeDomainChannelConfig": ComputeDomainChannelConfig,
    "ComputeDomainDaemonConfig": ComputeDomainDaemonConfig,
}

_NESTED: Dict[str, Type] = {
    "sharing": SharingConfig,
    "time_slicing": TimeSlicingConfig,
    "premapped": MpsLikePremappedConfig,
}


def _build(cls: Type, data: Dict[str, Any], strict: bool, path: str):
    known = {f.name: f for f in dc_fields(cls)}
    kwargs: Dict[str, Any] = {}
    for k, v in data.items():
        if k not in known:
            if strict:
                raise DecodeError(f"unknown field {path + k!r} for {cls.__name__}")
            continue
        if k in _NESTED and isinstance(v, dict):
            kwargs[k] = _build(_NESTED[k], v, strict, f"{path}{k}.")
        else:
            kwargs[k] = v
    try:
        return cls(**kwargs)
    except TypeError as e:
        raise DecodeError(f"bad config for {cls.__name__}: {e}") from None


def decode_config(parameters: Dict[str, Any], strict: bool) -> DeviceConfig:
    """Decode an opaque ``parameters`` blob into a typed config.

    Expects ``apiVersion`` = resource.tpu.google.com/v1beta1 and a known
    ``kind``; remaining keys are the config body.
    """
    if not isinstance(parameters, dict):
        raise DecodeError(f"opaque parameters must be an object, got {type(parameters)}")
    api_version = parameters.get("apiVersion", "")
    if api_version != API_VERSION:
        raise DecodeError(
            f"unsupported apiVersion {api_version!r}; want {API_VERSION}"
        )
    kind = parameters.get("kind", "")
    cls = _KINDS.get(kind)
    if cls is None:
        raise DecodeError(f"unknown config kind {kind!r}; known: {sorted(_KINDS)}")
    body = {k: v for k, v in parameters.items() if k not in ("apiVersion", "kind")}
    cfg = _build(cls, body, strict, path="")
    cfg.normalize()
    return cfg


def strict_decode(parameters: Dict[str, Any]) -> DeviceConfig:
    return decode_config(parameters, strict=True)


def nonstrict_decode(parameters: Dict[str, Any]) -> DeviceConfig:
    return decode_config(parameters, strict=False)
