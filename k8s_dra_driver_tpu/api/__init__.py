"""resource.tpu.google.com/v1beta1 — CRDs and opaque device configs.

The TPU-native counterpart of /root/reference/api/nvidia.com/resource/
v1beta1: ComputeDomain + ComputeDomainClique CRD types, and the opaque
per-claim config taxonomy (TpuConfig, SubsliceConfig, VfioTpuConfig,
ComputeDomain{Channel,Daemon}Config, sharing) with Normalize/Validate and
strict/nonstrict decoding.
"""

from k8s_dra_driver_tpu.api.configs import (  # noqa: F401
    API_GROUP,
    API_VERSION,
    ComputeDomainChannelConfig,
    ComputeDomainDaemonConfig,
    DecodeError,
    DeviceConfig,
    MpsLikePremappedConfig,
    SharingConfig,
    SubsliceConfig,
    TimeSlicingConfig,
    TpuConfig,
    ValidationError,
    VfioTpuConfig,
    decode_config,
    nonstrict_decode,
    strict_decode,
)
from k8s_dra_driver_tpu.api.tenantquota import (  # noqa: F401
    TENANT_QUOTA,
    TenantQuota,
    TenantQuotaSpec,
    TenantQuotaStatus,
)
from k8s_dra_driver_tpu.api.computedomain import (  # noqa: F401
    COMPUTE_DOMAIN_FINALIZER,
    ComputeDomain,
    ComputeDomainClique,
    ComputeDomainDaemonInfo,
    ComputeDomainNode,
    ComputeDomainSpec,
    ComputeDomainStatus,
)
