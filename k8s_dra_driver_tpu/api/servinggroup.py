"""ServingGroup CRD: the serving workload class the autoscaler scales.

The "millions of users" story needs a workload that looks like production
inference, not batch training: N identical replicas, each one pod plus
one subslice ResourceClaim, fronted by a QPS stream and judged by a
latency SLO. A ServingGroup declares exactly that — the replica template,
the current per-replica subslice *tier* (``spec.profile``, chosen from
the ordered ``spec.tiers``), the traffic model the sim engine drives,
the latency/duty objectives, and the scaling policy knobs (cooldowns,
stabilization window, tier thresholds) the autoscaler honors.

The split of responsibilities mirrors a real HPA stack:

- the **traffic engine** (autoscaler/traffic.py) senses: QPS from the
  trace, per-replica utilization and latency from the queueing model,
  written back as quantized change-gated ``status.traffic`` and observed
  into the SLO evaluator;
- the **controller** (autoscaler/controller.py) actuates: stamps replica
  pods+claims to ``spec.replicas``, garbage-collects scale-downs, and
  moves ``spec.replicas``/``spec.profile`` under policy.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from k8s_dra_driver_tpu.k8s.conditions import Condition
from k8s_dra_driver_tpu.k8s.objects import K8sObject

SERVING_GROUP = "ServingGroup"

# Labels stamped on every replica pod AND its claim: the group label is
# how the traffic engine / autoscaler watch-feed their caches (no store
# scans), the tier label is how a rolling re-tier tells old-tier replicas
# from their replacements.
SERVING_GROUP_LABEL = "serving.tpu.google.com/group"
SERVING_TIER_LABEL = "serving.tpu.google.com/tier"
# Replica slot index annotation (indices are reused lowest-free so names
# stay stable across scale cycles).
SERVING_REPLICA_ANNOTATION = "serving.tpu.google.com/replica-index"

# The empty tier: one whole chip via the plain TPU device class (the
# smallest unit the allocator hands out without DynamicSubslice).
TIER_SINGLE_CHIP = ""


def tier_chips(profile: str) -> int:
    """Chips per replica at a tier: the subslice profile's area, or 1 for
    the single-chip tier."""
    if not profile:
        return 1
    dims = [int(d) for d in profile.lower().split("x")]
    out = 1
    for d in dims:
        out *= d
    return out


@dataclass
class ServingSLO:
    """Declared objectives. ``latency_p95_ms`` is the page bound the
    traffic engine normalizes against (observed/bound > 1 is a bad
    sample); ``duty_bound`` rides the existing claim-duty SLO."""

    latency_p95_ms: float = 50.0
    duty_bound: float = 0.95


@dataclass
class ServingTraffic:
    """The sim traffic model. ``trace`` is a tpulib.loadtrace spec
    (diurnal/bursty/playback); generator kinds scale to ``peak_qps``,
    playback samples are raw QPS. ``qps_per_chip`` is the replica's
    service capacity per chip at duty 1.0; ``base_latency_ms`` the
    unloaded service time the M/M/1-style latency curve grows from."""

    trace: str = ""
    peak_qps: float = 100.0
    qps_per_chip: float = 10.0
    base_latency_ms: float = 10.0


@dataclass
class ServingScalingPolicy:
    """Autoscaler knobs (docs/reference/autoscaling.md). All times are
    VIRTUAL seconds — the telemetry clock, never wall time."""

    min_replicas: int = 1
    max_replicas: int = 64
    # Size replicas so predicted per-replica utilization sits here.
    target_duty: float = 0.6
    # Scale-up reacts fast (bounded only by its own cooldown); scale-down
    # additionally waits out the stabilization window: the effective
    # desired count is the MAX over the window, so a bursty trace never
    # flaps (classic HPA stabilization semantics).
    scale_up_cooldown_s: float = 15.0
    scale_down_cooldown_s: float = 60.0
    stabilization_window_s: float = 120.0
    # Vertical policy: down-tier when the group's observed duty p95 stays
    # under this for the stabilization window (and no latency alert).
    down_tier_duty: float = 0.25
    tier_cooldown_s: float = 300.0


@dataclass
class ServingReplicaTemplate:
    """Per-replica pod shape (one container; the claim is generated)."""

    image: str = "serving"
    env: Dict[str, str] = field(default_factory=dict)


@dataclass
class ServingGroupSpec:
    replicas: int = 1
    # Current per-replica subslice tier ("1x1", "1x2", "2x2", ... or ""
    # for a single whole chip). The autoscaler moves this within `tiers`.
    profile: str = TIER_SINGLE_CHIP
    # Ordered smallest-first tiers vertical scaling may choose from;
    # empty disables vertical scaling.
    tiers: List[str] = field(default_factory=list)
    template: ServingReplicaTemplate = field(
        default_factory=ServingReplicaTemplate)
    slo: ServingSLO = field(default_factory=ServingSLO)
    traffic: ServingTraffic = field(default_factory=ServingTraffic)
    policy: ServingScalingPolicy = field(default_factory=ServingScalingPolicy)


@dataclass
class ServingTrafficStatus:
    """The traffic engine's last quantized sample, change-gated like
    UtilizationSummary so steady load never churns resourceVersions
    (``updated_at`` is display metadata outside the equality gate)."""

    qps: float = 0.0
    latency_ms: float = 0.0
    # observed latency / declared bound; > 1.0 is an SLO violation.
    latency_ratio: float = 0.0
    # offered per-replica utilization (rho) clamped to [0, 1].
    utilization: float = 0.0
    ready_replicas: int = 0
    updated_at: float = field(default=0.0, compare=False)


@dataclass
class ServingGroupStatus:
    desired_replicas: int = 0
    ready_replicas: int = 0
    # Tier actually stamped on current replicas (trails spec.profile
    # while a rolling re-tier is in flight).
    profile: str = TIER_SINGLE_CHIP
    # Virtual timestamps of the last scaling actions (cooldown anchors).
    last_scale_up: float = 0.0
    last_scale_down: float = 0.0
    last_retier: float = 0.0
    traffic: Optional[ServingTrafficStatus] = None
    conditions: List[Condition] = field(default_factory=list)


@dataclass
class ServingGroup(K8sObject):
    kind: str = SERVING_GROUP
    spec: ServingGroupSpec = field(default_factory=ServingGroupSpec)
    status: ServingGroupStatus = field(default_factory=ServingGroupStatus)


def replica_capacity_qps(spec: ServingGroupSpec) -> float:
    """QPS one replica serves at duty 1.0."""
    return max(1e-9, spec.traffic.qps_per_chip * tier_chips(spec.profile))
