"""TenantQuota CRD: the per-namespace contract of the contention plane.

Everything below the contention plane treats tenants as cooperating:
gang admission and the rebalancer make room by *moving* claims, but one
namespace's claim storm can still starve another indefinitely. A
TenantQuota names the namespace's share of the fleet explicitly:

- ``spec.weight`` — the namespace's weighted-fair-queuing share. The
  scheduler's dirty-batch admission orders pending work by virtual-time
  fair queuing over these weights (``scheduling/wfq.py``), so a tenant
  with weight 2 admits twice the chip-work per unit of contention as a
  tenant with weight 1, regardless of how many claims each submits.
- ``spec.chipQuota`` — hard cap on chips the namespace may hold
  allocated at once (0 = unlimited). Over-quota claims park
  unschedulable with a per-tenant reason (``QuotaExceeded``) and
  re-admit when usage drops or the quota is raised.
- ``spec.priorityFloor`` — the namespace's default priority tier: every
  pod/claim in the namespace is treated as AT LEAST this tier (a
  workload may declare a higher ``priorityTier`` on its own pod/claim;
  it can never demote below the floor). Tiers drive checkpoint-aware
  preemption: a higher-tier claim that parks unschedulable may evict
  strictly-lower-tier victims (``scheduling/preemption.py``).

One TenantQuota per namespace (the object's own namespace is the
tenant); when several exist the first by name wins, matching how
ResourceQuota scopes resolve. Status is written change-gated by the
scheduler's contention manager once per pass.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from k8s_dra_driver_tpu.k8s.objects import K8sObject

TENANT_QUOTA = "TenantQuota"

# Tier vocabulary: plain non-negative ints compare naturally ("never
# evict equal-or-higher tiers" is `victim_tier < preemptor_tier`).
# These names are conventions for manifests/docs, not an enum — any
# int >= 0 is a valid tier.
TIER_BEST_EFFORT = 0
TIER_STANDARD = 50
TIER_HIGH = 100


@dataclass
class TenantQuotaSpec:
    # Weighted-fair-queuing share; clamped to a small positive epsilon
    # by the queue so a zero/negative weight cannot divide by zero.
    weight: float = 1.0
    # Max chips the namespace may hold allocated at once; 0 = unlimited.
    chip_quota: int = 0
    # Minimum (and default) priority tier for the namespace's workloads.
    priority_floor: int = 0


@dataclass
class TenantQuotaStatus:
    """Scheduler-written observability: what the contention manager
    currently accounts to this tenant. Quantized + change-gated (steady
    state writes nothing); ``updated_at`` is display metadata outside
    the equality gate, like UtilizationSummary's."""

    chips_used: int = 0
    pods_pending: int = 0
    # WFQ virtual finish time (rounded) — how far ahead of the global
    # virtual clock this tenant's admitted work has pushed it. A tenant
    # far ahead of its peers waits; one behind is owed service.
    virtual_time: float = 0.0
    updated_at: float = field(default=0.0, compare=False)


@dataclass
class TenantQuota(K8sObject):
    kind: str = TENANT_QUOTA
    spec: TenantQuotaSpec = field(default_factory=TenantQuotaSpec)
    status: TenantQuotaStatus = field(default_factory=TenantQuotaStatus)
