"""k8s_dra_driver_tpu — a TPU-native Kubernetes Dynamic Resource Allocation driver.

A from-scratch framework with the capabilities of the NVIDIA DRA GPU driver
(see SURVEY.md): a ``tpu-kubelet-plugin`` that enumerates ``tpu.google.com``
devices (whole chips and ICI subslices as KEP-4815 partitionable devices) and
publishes them as ResourceSlices, prepares claims by CDI-injecting
``/dev/accel*`` plus libtpu topology environment into containers, and a
ComputeDomain stack — controller, per-domain slice agent, kubelet plugin —
that assembles multi-host ICI pod slices follow-the-workload style.

Layer map (mirrors SURVEY.md §1, TPU-native):

    L5  controller/            ComputeDomain reconciler (+ webhook/)
    L4  api/, k8s/             CRD + config types, API machinery
    L3  plugins/tpu/,          DRA kubelet plugins
        plugins/computedomain/
    L2  daemon/                per-domain slice agent (ICI bootstrap/health)
    L1  pkg/                   featuregates, flock, workqueue, metrics, bootid
    L0  tpulib/ + native/      C++ enumeration shim + mock backend

The JAX side (models/, ops/, parallel/) is the workload half: the proof-of-
function training step and allreduce benchmark that run on a prepared slice,
analogous to the reference's nvbandwidth test jobs.
"""

__version__ = "0.1.0"
