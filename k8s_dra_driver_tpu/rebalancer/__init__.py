"""Live repack: the online defragmentation rebalancer.

PR 5's topology-aware admission *slows* fragmentation but never reverses
it: once small v5e-1/2/4 claims scatter across hosts, large-profile and
multi-host ComputeDomain placements stay destroyed until churn happens to
free a block. This subsystem is the reversal — the Flex-MIG /
"Managing Multi-Instance GPUs for High Throughput and Energy Savings"
insight that the real wins come from repartitioning *online*, not just at
admission (PAPERS.md).

Two modules:

- ``planner``: pure planning over the allocator's bitmask placement view —
  score per-node fragmentation, pick the *minimal* set of migration units
  (a consumer pod plus every claim it holds) whose eviction restores a
  target profile or host-grid block, and the energy-mode consolidation
  order.
- ``controller``: the control loop — watches the fragmentation signal
  behind ``tpu_dra_node_frag_largest_free_profile`` plus unschedulable
  demand, executes migrations (cordon -> checkpoint-aware unprepare ->
  re-place via the placement tables -> re-prepare -> uncordon) under a
  migration budget, with rollback to the source placement on any
  mid-migration failure, per-step tracing spans, and
  RebalancePlanned/ClaimMigrated/MigrationFailed events.

Gated by the ``LiveRepack`` feature gate (or an explicit config passed to
the sim); see docs/reference/rebalancing.md.
"""

from k8s_dra_driver_tpu.rebalancer.controller import (  # noqa: F401
    CORDON_ANNOTATION,
    DRAIN_READY_ANNOTATION,
    MODE_DEFRAG,
    MODE_ENERGY,
    RebalanceController,
    RebalancerConfig,
)
from k8s_dra_driver_tpu.rebalancer.planner import (  # noqa: F401
    MigrationUnit,
    NodeView,
    RepackPlan,
    WHOLE_HOST,
    build_node_views,
    plan_consolidation,
    plan_domain_block,
    plan_profile,
)
