"""RebalanceController: the live-repack control loop.

Watches the fragmentation signal behind the
``tpu_dra_node_frag_largest_free_profile`` gauge (read as bitmasks via
``Allocator.placement_overview``) plus unschedulable demand (pending pods
whose large-profile or multi-host ComputeDomain claims no node can place),
plans the minimal migration set with ``rebalancer.planner``, and executes
each migration as a rollback-safe pipeline:

    cordon claim(s) -> checkpoint-aware unprepare on the source
    (DeviceState.migrate_out: the MigrationCheckpoint handshake)
    -> re-place via the PR 5 bitmask placement tables
    -> re-prepare on the target -> rebind the pod -> uncordon

Any mid-migration failure rolls back to the source placement: the target
side is unprepared, the allocation is restored, and the source re-prepare
clears the MigrationCheckpoint entry — the claim ends exactly where it
started and the partition ledger holds exactly its original partitions.

Migrations are budgeted (a per-pass cap plus a token bucket refilled over
time), every step runs under a tracing span, and the controller narrates
through RebalancePlanned/ClaimMigrated/MigrationFailed events.

**Energy mode** inverts the goal: instead of freeing one large placement
it consolidates movable claims onto the fewest hosts (tightest-fit-first
re-placement restricted to equal-or-busier hosts, so the occupied-host
count strictly falls), publishes the ``tpu_dra_reclaimable_hosts`` gauge,
and marks fully-idle hosts drain-ready via a Node annotation that
``describe`` renders.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from k8s_dra_driver_tpu.api.configs import (
    TPU_DRIVER_NAME,
    channel_domain_uid,
)
from k8s_dra_driver_tpu.k8s.core import (
    COMPUTE_DOMAIN,
    NODE,
    ObjectReference,
    POD,
    RESOURCE_CLAIM,
)
from k8s_dra_driver_tpu.k8s.objects import NotFoundError
from k8s_dra_driver_tpu.pkg import placement as placement_lib
from k8s_dra_driver_tpu.pkg import tracing
from k8s_dra_driver_tpu.pkg.backoff import Backoff, BackoffMetrics
from k8s_dra_driver_tpu.pkg.events import (
    EventRecorder,
    REASON_CLAIM_MIGRATED,
    REASON_MIGRATION_FAILED,
    REASON_REBALANCE_PLANNED,
)
from k8s_dra_driver_tpu.pkg.metrics import Counter, Gauge, Registry
from k8s_dra_driver_tpu.rebalancer.planner import (
    NodeView,
    RepackPlan,
    WHOLE_HOST,
    build_node_views,
    plan_consolidation,
    plan_domain_block,
    plan_profile,
    reclaimable_hosts,
)

log = logging.getLogger(__name__)

MODE_DEFRAG = "defrag"
MODE_ENERGY = "energy"

# Claim annotation marking an in-flight migration: the planner skips
# cordoned claims, so two controllers (or two passes) never double-migrate.
CORDON_ANNOTATION = "rebalancer.tpu.google.com/cordoned"


def try_cordon(api, claim, owner: str = "true") -> bool:
    """Atomically acquire the migration cordon on one claim.

    The CAS closure only claims an object that is un-cordoned OR already
    cordoned by the SAME ``owner``, so of N distinct actors racing on
    one claim exactly one wins — the seam that keeps the rebalancer's
    consolidation pass and the serving autoscaler's scale-down drain
    (both of which move/retire claims) from ever double-handling one
    replica. Same-owner re-acquisition is deliberate: an actor that
    crashed between its cordon and its follow-through must be able to
    resume its own half-done work instead of reading its stale mark as
    someone else's in-flight migration forever. ``owner`` therefore
    names an actor ROLE, and mutual exclusion WITHIN a role is the
    role's own deployment contract (one leader-elected rebalancer, one
    autoscaler per cluster — the same single-instance assumption both
    controllers already rest on). Returns False when the claim is
    cordoned by a different owner or gone."""
    def mutate(obj, owner=owner):
        cur = obj.meta.annotations.get(CORDON_ANNOTATION)
        if cur == owner:
            # Same-owner re-acquisition: already ours, nothing to write.
            raise _CordonNoWrite(won=True)
        if cur is not None:
            # A losing attempt must not write: the no-op update would
            # still bump resourceVersion and fan out a MODIFIED event —
            # per-tick churn on every contended claim while a drain
            # retries against an in-flight migration.
            raise _CordonNoWrite(won=False)
        obj.meta.annotations[CORDON_ANNOTATION] = owner

    try:
        api.update_with_retry(RESOURCE_CLAIM, claim.meta.name,
                              claim.meta.namespace, mutate)
    except _CordonNoWrite as verdict:
        return verdict.won
    except NotFoundError:
        return False
    return True


class _CordonNoWrite(Exception):
    """Raised from the cordon CAS closures to abort WITHOUT writing;
    carries the acquisition verdict."""

    def __init__(self, won: bool):
        super().__init__()
        self.won = won


def release_cordon(api, claim) -> None:
    """Drop the migration cordon (no-op — and no write — when the claim
    is gone or not cordoned)."""
    def mutate(obj):
        if CORDON_ANNOTATION not in obj.meta.annotations:
            raise _CordonNoWrite(won=False)
        obj.meta.annotations.pop(CORDON_ANNOTATION, None)
    try:
        api.update_with_retry(RESOURCE_CLAIM, claim.meta.name,
                              claim.meta.namespace, mutate)
    except (_CordonNoWrite, NotFoundError):
        pass
# Node annotation the energy mode sets on fully-idle hosts — the
# drain-ready marker `describe node` renders.
DRAIN_READY_ANNOTATION = "rebalancer.tpu.google.com/drain-ready"


@dataclass
class RebalancerConfig:
    """Policy knobs (docs/reference/rebalancing.md)."""

    mode: str = MODE_DEFRAG                 # defrag | energy
    # Profiles to keep placeable even without pending demand ("whole-host"
    # or a subslice shape like "2x2") — the proactive watch targets.
    watch_profiles: Tuple[str, ...] = ()
    # Hard cap on migration units moved in one pass.
    max_migrations_per_pass: int = 4
    # Token bucket across passes: capacity + refill rate. A churn storm
    # cannot turn the rebalancer into its own churn storm.
    migration_burst: int = 16
    migration_refill_per_s: float = 1.0
    # Per-unit retry pacing after a failed/rolled-back migration
    # (pkg.backoff: capped exponential, deterministic jitter, reset on
    # success). The first retry is immediate — only a unit that keeps
    # failing backs off, so a persistent fault can't make the controller
    # re-roll the same migration at full pass rate forever.
    retry_backoff_base_s: float = 2.0
    retry_backoff_cap_s: float = 60.0


class RebalancerMetrics:
    def __init__(self, registry: Registry):
        self.passes_total = registry.register(Counter(
            "tpu_dra_rebalance_passes_total",
            "Completed rebalancer passes, by mode.",
            ("mode",)))
        self.migrations_total = registry.register(Counter(
            "tpu_dra_rebalance_migrations_total",
            "Claim-unit migrations attempted, by outcome "
            "(migrated / failed — failed includes rolled-back).",
            ("outcome",)))
        self.deferred_total = registry.register(Counter(
            "tpu_dra_rebalance_deferred_total",
            "Planned migrations deferred by the per-pass cap or the "
            "token-bucket budget."))
        self.plan_units = registry.register(Gauge(
            "tpu_dra_rebalance_last_pass_migrations",
            "Migration units moved by the last rebalancer pass "
            "(0 when nothing needed repacking)."))
        self.reclaimable_hosts = registry.register(Gauge(
            "tpu_dra_reclaimable_hosts",
            "Hosts with zero allocated chips — drainable right now "
            "(energy mode keeps this maximal by consolidating claims)."))


class RebalanceController:
    """``plugin_resolver(node_name)`` returns the node's TpuDriver (the
    object exposing prepare_resource_claims / migrate_claim_out /
    migrate_claim_end), or None for unknown nodes — the seam that lets the
    sim hand over its in-process plugins and a future remote-plugin
    transport slot in unchanged."""

    def __init__(
        self,
        api,
        allocator,
        plugin_resolver: Callable[[str], object],
        config: Optional[RebalancerConfig] = None,
        metrics_registry: Optional[Registry] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.api = api
        self.allocator = allocator
        self.resolve_plugin = plugin_resolver
        self.config = config or RebalancerConfig()
        registry = metrics_registry or Registry()
        self.metrics = RebalancerMetrics(registry)
        self.recorder = EventRecorder(api, "rebalancer",
                                      metrics_registry=registry)
        self.clock = clock
        # Optional flight recorder (pkg/history.py HistoryStore):
        # migrations and rollbacks record per-victim-pod decisions.
        self.history = None
        self._tokens = float(self.config.migration_burst)
        self._tokens_at = clock()
        # Consolidated retry pacing (pkg.backoff) keyed by migration unit:
        # a unit whose migration failed skips passes until its delay
        # elapsed; success forgets the history.
        self.retry_backoff = Backoff(
            base=self.config.retry_backoff_base_s,
            cap=self.config.retry_backoff_cap_s,
            jitter=0.2, clock=clock,
            metrics=BackoffMetrics(registry), source="rebalancer")
        # Last pass's per-node largest-free reading — the cheap "did the
        # fragmentation signal move" gate.
        self._last_frag: Optional[tuple] = None

    # -- budget ---------------------------------------------------------------

    def _take_token(self) -> bool:
        now = self.clock()
        self._tokens = min(
            float(self.config.migration_burst),
            self._tokens + max(0.0, now - self._tokens_at)
            * self.config.migration_refill_per_s)
        self._tokens_at = now
        if self._tokens < 1.0:
            return False
        self._tokens -= 1.0
        return True

    # -- snapshot -------------------------------------------------------------

    def _snapshot(self) -> Tuple[Dict[str, NodeView], list, Dict[str, object]]:
        """(views, claims, pods_by_uid) from ONE claim + pod listing —
        demand detection reuses the same listings instead of re-scanning."""
        overview = self.allocator.placement_overview(TPU_DRIVER_NAME)
        claims = list(self.api.list(RESOURCE_CLAIM))
        pods_by_uid = {p.uid: p for p in self.api.list(POD)}
        device_types = {
            (node, name): t
            for node, entry in overview.items()
            for name, t in entry["dev_type"].items()
        }
        views = build_node_views(
            overview, claims, pods_by_uid, TPU_DRIVER_NAME, device_types,
            is_cordoned=lambda c: CORDON_ANNOTATION in c.meta.annotations,
        )
        return views, claims, pods_by_uid

    # -- demand detection -----------------------------------------------------

    def _demand_targets(self, all_claims, pods_by_uid):
        """(profile targets, domain targets) derived from pending pods
        whose claims cannot place anywhere: the unschedulable demand the
        scheduler parked in its backlog. Reads the snapshot's listings —
        no second cluster-wide scan per pass."""
        profiles: List[Tuple[str, object]] = []   # (profile, involved obj)
        domains: Dict[str, Tuple[int, object]] = {}  # cd uid -> (n, cd)
        # One domain scan for the whole pass, not one per pending claim.
        domains_by_uid = {cd.uid: cd
                          for cd in self.api.list(COMPUTE_DOMAIN)}
        claims_by_key = {(c.meta.namespace, c.meta.name): c
                         for c in all_claims}
        for pod in pods_by_uid.values():
            if pod.phase != "Pending":
                continue
            claims = []
            for ref in pod.resource_claims:
                name = (ref.resource_claim_name
                        or f"{pod.meta.name}-{ref.name}")
                c = claims_by_key.get((pod.meta.namespace, name))
                if c is not None:
                    claims.append(c)
            if not claims or all(c.allocation is not None for c in claims):
                continue
            cd = None
            for c in claims:
                uid = channel_domain_uid(c)
                if uid:
                    cd = domains_by_uid.get(uid)
                    break
            if cd is not None and cd.spec.num_nodes > 1:
                domains.setdefault(cd.uid, (cd.spec.num_nodes, cd))
                continue
            for c in claims:
                if c.allocation is not None:
                    continue
                for req in c.requests:
                    profile = self._request_profile(req)
                    if profile is not None:
                        profiles.append((profile, c))
        # Dedup profile targets, first involved object wins.
        seen: Set[str] = set()
        uniq = []
        for profile, obj in profiles:
            if profile not in seen:
                seen.add(profile)
                uniq.append((profile, obj))
        return uniq, list(domains.values())

    @staticmethod
    def _request_profile(req) -> Optional[str]:
        """The placement-table profile one device request demands, or None
        when fragmentation cannot be what blocks it (plain count-based
        single-chip requests fit any free chip). The selector parse is
        shared with the contention plane (scheduling.tiers)."""
        from k8s_dra_driver_tpu.scheduling.tiers import request_profile

        if req.allocation_mode == "All":
            return WHOLE_HOST
        return request_profile(req)

    # -- the pass -------------------------------------------------------------

    def step(self) -> int:
        """One rebalance pass; returns how many units were migrated."""
        with tracing.span("rebalance.pass", mode=self.config.mode) as sp:
            views, claims, pods_by_uid = self._snapshot()
            self._publish_reclaimable(views)
            frag = tuple(sorted(
                (v.name,
                 v.tables.largest_free_chips(v.used_mask, v.available))
                for v in views.values()))
            if self.config.mode == MODE_ENERGY:
                migrated = self._energy_pass(views)
            else:
                migrated = self._defrag_pass(views, frag, claims,
                                             pods_by_uid)
            self._last_frag = frag
            sp.attrs["migrated"] = migrated
            # Set unconditionally so an idle pass reads 0, not the
            # previous pass's count.
            self.metrics.plan_units.set(value=float(migrated))
            self.metrics.passes_total.inc(self.config.mode)
            return migrated

    def _defrag_pass(self, views: Dict[str, NodeView], frag: tuple,
                     claims, pods_by_uid) -> int:
        profile_targets, domain_targets = self._demand_targets(
            claims, pods_by_uid)
        if (not profile_targets and not domain_targets
                and not self.config.watch_profiles):
            return 0
        if (not profile_targets and not domain_targets
                and frag == self._last_frag):
            # Watch-only config and the fragmentation signal didn't move:
            # last pass's verdict stands.
            return 0
        migrated = 0
        budget = self.config.max_migrations_per_pass
        # Every node vacated by ANY plan this pass stays forbidden as a
        # migration destination for every later plan — plan B must not
        # refill the placement plan A just freed.
        vacated: Set[str] = set()
        for profile in self.config.watch_profiles:
            if not any(p == profile for p, _ in profile_targets):
                profile_targets.append((profile, None))
        for profile, involved in profile_targets:
            plan = plan_profile(views, profile)
            migrated += self._execute(plan, views, involved, budget - migrated,
                                      required=involved is not None,
                                      also_forbidden=vacated)
            if plan is not None:
                vacated |= set(plan.nodes)
        topologies = self.allocator.node_topologies()
        for num_nodes, cd in domain_targets:
            plan = plan_domain_block(
                views, topologies, num_nodes,
                target=f"host block for ComputeDomain {cd.key} "
                       f"({num_nodes} nodes)")
            migrated += self._execute(plan, views, cd, budget - migrated,
                                      required=True, also_forbidden=vacated)
            if plan is not None:
                vacated |= set(plan.nodes)
        return migrated

    def _energy_pass(self, views: Dict[str, NodeView]) -> int:
        migrated = 0
        budget = self.config.max_migrations_per_pass
        received: Set[str] = set()
        for plan in plan_consolidation(views):
            if migrated >= budget:
                break
            source = plan.nodes[0]
            if source in received:
                continue  # got claims this pass: its plan is stale
            min_used = placement_lib.popcount(views[source].used_mask)
            got = self._execute(
                plan, views, None, budget - migrated, required=False,
                min_used=min_used, received=received)
            migrated += got
        if migrated:
            # One POST-migration snapshot drives both the gauge and the
            # annotations, so /metrics and `describe` can never disagree
            # within a pass.
            views, _, _ = self._snapshot()
            self._publish_reclaimable(views)
        self._annotate_drain_ready(set(reclaimable_hosts(views)))
        return migrated

    def _publish_reclaimable(self, views: Dict[str, NodeView]) -> None:
        self.metrics.reclaimable_hosts.set(
            value=float(len(reclaimable_hosts(views))))

    def drain_ready_hosts(self) -> List[str]:
        """Hosts currently reclaimable (zero allocated chips) — the
        drain-ready list energy mode annotates and `describe` renders."""
        views, _, _ = self._snapshot()
        return reclaimable_hosts(views)

    def _annotate_drain_ready(self, empty: Set[str]) -> None:
        """Mark fully-idle hosts drain-ready (and clear the mark when they
        fill back up). Change-gated: a steady cluster writes nothing."""
        for node in self.api.list(NODE):
            name = node.meta.name
            has = DRAIN_READY_ANNOTATION in node.meta.annotations
            want = name in empty
            if has == want:
                continue

            def mutate(obj, want=want):
                if want:
                    obj.meta.annotations[DRAIN_READY_ANNOTATION] = "true"
                else:
                    obj.meta.annotations.pop(DRAIN_READY_ANNOTATION, None)
            try:
                self.api.update_with_retry(NODE, name, "", mutate)
            except NotFoundError:
                continue

    # -- plan execution -------------------------------------------------------

    def _execute(self, plan: Optional[RepackPlan],
                 views: Dict[str, NodeView],
                 involved, budget: int, required: bool,
                 min_used: Optional[int] = None,
                 received: Optional[Set[str]] = None,
                 also_forbidden: Optional[Set[str]] = None) -> int:
        """Run one plan's migrations within ``budget``; returns units moved.
        ``involved``: the object RebalancePlanned narrates on (the pending
        ComputeDomain or claim), falling back to the vacated node.
        ``required=False`` (energy / watch targets) skips silently when a
        unit has no feasible destination instead of alarming.
        ``also_forbidden``: nodes vacated by earlier plans this pass —
        never valid destinations either."""
        if plan is None or not plan.units or budget <= 0:
            return 0
        ref = involved
        if ref is None:
            ref = (self.api.try_get(NODE, plan.nodes[0])
                   or ObjectReference(kind=NODE, name=plan.nodes[0]))
        if required:
            # Demanded repacks narrate up front; opportunistic
            # (energy/watch) plans narrate only when they actually move
            # something — a plan with no viable destination must not spam.
            self.recorder.normal(
                ref, REASON_REBALANCE_PLANNED,
                f"live repack: migrating {len(plan.units)} claim unit(s) "
                f"off {','.join(plan.nodes)} to restore {plan.target}")
        migrated = 0
        forbidden = set(plan.nodes) | (also_forbidden or set())
        for i, unit in enumerate(plan.units):
            if migrated >= budget:
                self.metrics.deferred_total.inc(
                    by=float(len(plan.units) - i))
                break
            outcome = self._migrate_unit(unit, views, forbidden, required,
                                         min_used=min_used,
                                         received=received)
            if outcome == "no-token":
                self.metrics.deferred_total.inc(
                    by=float(len(plan.units) - i))
                break
            if outcome == "migrated":
                if not required and migrated == 0:
                    self.recorder.normal(
                        ref, REASON_REBALANCE_PLANNED,
                        f"live repack: migrating {len(plan.units)} claim "
                        f"unit(s) off {','.join(plan.nodes)} to restore "
                        f"{plan.target}")
                migrated += 1
            elif required:
                # One stuck blocker means the placement cannot be freed
                # this pass; don't churn the remaining units for nothing.
                break
        return migrated

    def _allowed_targets(self, views: Dict[str, NodeView],
                         forbidden: Set[str],
                         min_used: Optional[int]) -> List[str]:
        out = []
        for name, view in views.items():
            if name in forbidden:
                continue
            if min_used is not None:
                # Energy mode: only equal-or-busier hosts (strictly
                # reduces the occupied-host count, so the loop
                # terminates), and never hosts being drained this pass.
                if placement_lib.popcount(view.used_mask) < min_used:
                    continue
            out.append(name)
        return out

    def _migrate_unit(self, unit, views: Dict[str, NodeView],
                      forbidden: Set[str], required: bool,
                      min_used: Optional[int] = None,
                      received: Optional[Set[str]] = None) -> str:
        """One full migration with rollback. Returns "migrated", "failed"
        (rolled back / no destination), "skip" (stale plan or
        backoff-paced), or "no-token" (budget exhausted before anything
        was touched)."""
        retry_key = (unit.pod_namespace, unit.pod_name)
        if not self.retry_backoff.ready(retry_key):
            return "skip"  # failed recently: wait out the backoff
        outcome = self._migrate_unit_inner(unit, views, forbidden, required,
                                           min_used=min_used,
                                           received=received)
        if outcome == "failed":
            self.retry_backoff.failure(retry_key)
        elif outcome == "migrated":
            self.retry_backoff.reset(retry_key)
        return outcome

    def _migrate_unit_inner(self, unit, views: Dict[str, NodeView],
                            forbidden: Set[str], required: bool,
                            min_used: Optional[int] = None,
                            received: Optional[Set[str]] = None) -> str:
        with tracing.span("rebalance.migrate", pod=f"{unit.pod_namespace}/"
                          f"{unit.pod_name}", source=unit.node) as sp:
            claims = []
            for ns, name in unit.claim_keys:
                c = self.api.try_get(RESOURCE_CLAIM, name, ns)
                if (c is None or c.allocation is None
                        or c.allocation.node_name != unit.node):
                    return "skip"  # stale plan: the world moved on
                claims.append(c)
            pod = self.api.try_get(POD, unit.pod_name, unit.pod_namespace)
            if pod is None or pod.node_name != unit.node:
                return "skip"
            src_plugin = self.resolve_plugin(unit.node)
            if src_plugin is None:
                return "skip"
            # Destination first, before any state is touched: a unit with
            # nowhere to go costs neither a cordon nor a budget token.
            target, allocs = self._pick_target(
                claims, views, forbidden, min_used)
            if target is None:
                if required:
                    self._record_failure(
                        claims, unit,
                        "no feasible target node for re-placement")
                    return "failed"
                return "skip"
            dst_plugin = self.resolve_plugin(target)
            if dst_plugin is None:
                return "skip"
            sp.attrs["target"] = target
            # Atomic cordon acquisition BEFORE the budget token: of the
            # distinct actor roles racing on a claim (this rebalancer,
            # the autoscaler's scale-down drain) exactly one wins; a
            # second rebalancer instance is excluded by leader election,
            # not by the cordon (same-owner re-acquisition is the
            # crash-resume path). Losing any claim of the unit means
            # another role owns part of it — back off whole, costing
            # neither a cordon nor a token (a drain storm must not burn
            # the migration budget on units that were never ours).
            acquired = []
            for c in claims:
                if try_cordon(self.api, c, owner="rebalancer"):
                    acquired.append(c)
                    continue
                for got in acquired:
                    release_cordon(self.api, got)
                return "skip"
            if not self._take_token():
                for got in acquired:
                    release_cordon(self.api, got)
                return "no-token"
            try:
                ok = self._move(unit, claims, allocs, src_plugin,
                                dst_plugin, target)
            except Exception:  # noqa: BLE001 — one bad unit must not kill the pass
                # _move is rollback-safe internally; anything reaching here
                # escaped its guarded windows (cordon/bookkeeping). Count
                # it failed and let the pass continue — the next pass's
                # refetch + checkpoint recovery own any residue.
                log.exception("migration of %s/%s failed unexpectedly",
                              unit.pod_namespace, unit.pod_name)
                self._set_cordon(claims, False)
                self.metrics.migrations_total.inc("failed")
                return "failed"
            if ok and received is not None:
                received.add(target)
            return "migrated" if ok else "failed"

    def _pick_target(self, claims, views, forbidden, min_used):
        allowed = self._allowed_targets(views, forbidden, min_used)
        with tracing.span("rebalance.replace"):
            try:
                candidates = self.allocator.feasible_nodes(
                    claims, nodes=allowed)
            except Exception:  # noqa: BLE001 — malformed claim: not migratable
                log.exception("feasibility check failed during migration")
                return None, []
            for node in candidates:
                allocs = []
                fits = True
                for c in claims:
                    r = self.allocator.allocate_on_node(
                        c, node, in_flight=allocs)
                    if r is None:
                        fits = False
                        break
                    allocs.append(r)
                if fits:
                    return node, allocs
        return None, []

    def _move(self, unit, claims, allocs, src_plugin, dst_plugin,
              target: str) -> bool:
        """unprepare(source) -> re-point allocations -> prepare(target) ->
        rebind pod -> uncordon, rolling back to the source placement on any
        failure."""
        source = unit.node
        old_allocs = {c.uid: c.allocation for c in claims}
        migrated_out: List[str] = []
        with tracing.span("rebalance.unprepare", node=source):
            try:
                for c in claims:
                    src_plugin.migrate_claim_out(c.uid)
                    migrated_out.append(c.uid)
            except Exception as e:  # noqa: BLE001 — roll straight back
                log.warning("migrate_out of %s failed: %s", unit.pod_name, e)
                self._restore_source(unit, claims, src_plugin)
                self._record_failure(claims, unit, f"source unprepare: {e}")
                self._set_cordon(claims, False)
                return False
        try:
            for c, alloc in zip(claims, allocs):
                def repoint(obj, alloc=alloc):
                    obj.allocation = alloc
                try:
                    self.api.update_with_retry(
                        RESOURCE_CLAIM, c.meta.name, c.namespace, repoint)
                except NotFoundError:
                    self._rollback(unit, claims, old_allocs, src_plugin,
                                   dst_plugin, "claim vanished mid-migration")
                    return False
            with tracing.span("rebalance.prepare", node=target):
                fresh = [self.api.try_get(RESOURCE_CLAIM, c.meta.name,
                                          c.namespace)
                         for c in claims]
                fresh = [c for c in fresh if c is not None]
                results = dst_plugin.prepare_resource_claims(fresh)
                errs = {uid: r for uid, r in results.items()
                        if isinstance(r, Exception)}
                if len(fresh) != len(claims) or errs:
                    why = "; ".join(str(e) for e in errs.values()) or \
                        "claim vanished mid-migration"
                    self._rollback(unit, claims, old_allocs, src_plugin,
                                   dst_plugin, f"target prepare: {why}")
                    return False
        except Exception as e:  # noqa: BLE001 — the source is already unprepared: ANY escape here must restore it
            log.exception("unexpected error mid-migration of %s/%s",
                          unit.pod_namespace, unit.pod_name)
            self._rollback(unit, claims, old_allocs, src_plugin, dst_plugin,
                           f"unexpected mid-migration error: {e}")
            return False
        # Past this point the migration HAS succeeded (claims prepared on
        # the target): the closing steps are individually best-effort so
        # one hiccup (a flock timeout on migrate_claim_end, a CAS storm on
        # the rebind) cannot strand the unit half-finished or abort the
        # pass.
        for uid in migrated_out:
            try:
                src_plugin.migrate_claim_end(uid)
            except Exception:  # noqa: BLE001 — benign residue: the entry holds no devices and clears on the next prepare/unprepare/restart
                log.exception("migrate_claim_end(%s) on %s failed", uid,
                              source)
        try:
            self._rebind_pod(unit, target)
        except Exception:  # noqa: BLE001 — pod rebind retried by the next pass's stale-plan refetch
            log.exception("rebind of %s/%s failed", unit.pod_namespace,
                          unit.pod_name)
        self._set_cordon(claims, False)
        for c in claims:
            self.recorder.normal(
                c, REASON_CLAIM_MIGRATED,
                f"live repack migrated claim from {source} to {target}")
        if self.history is not None:
            from k8s_dra_driver_tpu.pkg.history import RULE_MIGRATE

            self.history.decide(
                controller="rebalancer", rule=RULE_MIGRATE,
                outcome="migrated", kind=POD,
                namespace=unit.pod_namespace, name=unit.pod_name,
                message=f"live repack moved unit {source} -> {target}",
                inputs={"source": source, "target": target,
                        "chips": unit.num_chips,
                        "claims": sorted(c.meta.name for c in claims)},
                now=self.clock())
        self.metrics.migrations_total.inc("migrated")
        return True

    # -- rollback -------------------------------------------------------------

    def _rollback(self, unit, claims, old_allocs, src_plugin, dst_plugin,
                  why: str) -> None:
        """Mid-migration failure: restore the SOURCE placement exactly.
        Order matters — target unprepare first (free anything half-made
        there), then allocations back, then the source re-prepare (which
        clears the MigrationCheckpoint entries and re-activates the source
        partitions)."""
        with tracing.span("rebalance.rollback", pod=unit.pod_name):
            try:
                dst_plugin.unprepare_resource_claims([c.uid for c in claims])
            except Exception:  # noqa: BLE001 — best effort; target holds nothing prepared
                log.exception("rollback: target unprepare failed")
            for c in claims:
                def restore(obj, alloc=old_allocs.get(c.uid)):
                    obj.allocation = alloc
                try:
                    self.api.update_with_retry(
                        RESOURCE_CLAIM, c.meta.name, c.namespace, restore)
                except NotFoundError:
                    continue
            self._restore_source(unit, claims, src_plugin)
        self._record_failure(claims, unit, why)
        self._set_cordon(claims, False)

    def _restore_source(self, unit, claims, src_plugin) -> None:
        """Re-prepare the claims on their source node; the prepare path
        clears MigrationCheckpoint entries, so after this the checkpoint
        and the partition ledger read exactly as before the migration."""
        fresh = [self.api.try_get(RESOURCE_CLAIM, c.meta.name, c.namespace)
                 for c in claims]
        results = src_plugin.prepare_resource_claims(
            [c for c in fresh if c is not None])
        for uid, r in results.items():
            if isinstance(r, Exception):
                # The pod's kubelet retry loop owns recovery from here; the
                # checkpoint holds no migration entry either way.
                log.error("rollback re-prepare of %s on %s failed: %s",
                          uid, unit.node, r)

    def _record_failure(self, claims, unit, why: str) -> None:
        for c in claims:
            self.recorder.warning(
                c, REASON_MIGRATION_FAILED,
                f"live repack migration off {unit.node} failed; claim "
                f"rolled back to its source placement: {why}")
        if self.history is not None:
            from k8s_dra_driver_tpu.pkg.history import RULE_MIGRATE_FAILED

            self.history.decide(
                controller="rebalancer", rule=RULE_MIGRATE_FAILED,
                outcome="rolled-back", kind=POD,
                namespace=unit.pod_namespace, name=unit.pod_name,
                message=f"migration off {unit.node} failed: {why}",
                inputs={"source": unit.node, "chips": unit.num_chips},
                now=self.clock())
        self.metrics.migrations_total.inc("failed")

    # -- cordon / rebind ------------------------------------------------------

    def _set_cordon(self, claims, on: bool) -> None:
        """Release the unit's cordons (acquisition goes through the
        owner-tagged try_cordon CAS only — the ``cordon-cas`` tpulint
        rule rejects any raw annotation write on this path)."""
        assert not on, "cordons are acquired via try_cordon only"
        with tracing.span("rebalance.uncordon"):
            for c in claims:
                release_cordon(self.api, c)

    def _rebind_pod(self, unit, target: str) -> None:
        """Point the consumer pod at its claims' new home. Phase drops back
        to Pending so the kubelet re-runs the (idempotent) prepare and
        re-materializes the injected env from the target's CDI spec."""
        with tracing.span("rebalance.rebind", pod=unit.pod_name,
                          node=target):
            def mutate(obj):
                obj.node_name = target
                obj.phase = "Pending"
                obj.ready = False
            try:
                self.api.update_with_retry(
                    POD, unit.pod_name, unit.pod_namespace, mutate)
            except NotFoundError:
                pass
