"""Repack planning: minimal migration sets over the bitmask placement view.

Pure functions — no API writes, no device mutations — so every plan is
unit-testable against synthetic views. The controller executes plans.

The planning model:

- A **migration unit** is a consumer pod plus EVERY ResourceClaim it
  holds on its node. Claims move pod-at-a-time: migrating one claim of a
  two-claim pod would leave its siblings allocated on another node, which
  the scheduler rightly treats as a broken pod.
- A unit is **movable** only when every claim is an ordinary TPU
  chip/subslice claim (no ComputeDomain channel/daemon devices, no VFIO
  passthrough, no shared multi-pod claims), its pod is a plain Running
  workload pod (not DaemonSet-owned), and nothing has cordoned it.
  Everything else pins its chips — assembled ComputeDomains are never
  disturbed by construction.
- **Defrag** targets answer "what is the cheapest way to make profile P
  placeable again": for every candidate placement mask of P (the PR 5
  tables), the blocking units are the movable units whose chips intersect
  it; the minimal plan is the placement with the fewest blockers (ties:
  fewest chips moved, then node/profile order). A placement overlapping
  any pinned chip is discarded outright.
- **Host-block** targets generalize that to a multi-host ComputeDomain:
  enumerate contiguous host-grid blocks (pkg.placement.iter_host_blocks)
  whose hosts are all whole-host-capable and pin-free, and take the block
  with the fewest total blocking units.
- **Energy** planning inverts the goal: vacate the least-utilized hosts
  onto busier ones so whole hosts go idle and can be drained.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from k8s_dra_driver_tpu.pkg import placement as placement_lib

# Sentinel profile name for "the whole host" (the shape multi-host
# ComputeDomain workers claim); per-host tables key it by their topology
# string, so targets use this marker instead.
WHOLE_HOST = "whole-host"


@dataclass(frozen=True)
class MigrationUnit:
    """One movable scheduling unit: a consumer pod and all its claims."""

    pod_namespace: str
    pod_name: str
    pod_uid: str
    node: str
    claim_keys: Tuple[Tuple[str, str], ...]  # (namespace, name), sorted
    chip_mask: int                           # union over the unit's claims
    # Effective contention tier (max over the pod and its claims, with
    # the namespace floor applied) — 0 unless the caller supplied a
    # ``unit_tier`` hook to build_node_views. The preemption planner
    # never evicts a unit whose tier >= the preemptor's.
    tier: int = 0

    @property
    def num_chips(self) -> int:
        return placement_lib.popcount(self.chip_mask)


@dataclass
class NodeView:
    """One node's repack-relevant state, derived from the allocator's
    placement overview plus the claim/pod listing."""

    name: str
    tables: object                 # pkg.placement.PlacementTables
    available: int                 # placement-availability bitmap (taints)
    used_mask: int                 # chips held by ANY allocation
    pinned_mask: int = 0           # chips held by immovable claims
    units: List[MigrationUnit] = field(default_factory=list)

    @property
    def movable_mask(self) -> int:
        mask = 0
        for u in self.units:
            mask |= u.chip_mask
        return mask


@dataclass(frozen=True)
class RepackPlan:
    """A chosen repack move: vacate ``units`` so ``target`` becomes
    placeable on ``nodes`` (one node for a profile target, a host-grid
    block for a domain target)."""

    target: str                       # human-readable target description
    nodes: Tuple[str, ...]            # nodes being vacated (forbidden as
                                      # migration destinations)
    units: Tuple[MigrationUnit, ...]  # minimal blocking set, in order
    placement_mask: int = 0           # chip mask freed (profile targets)


def _profile_placements(view: NodeView, profile: str) -> List[int]:
    """Available placement indices of ``profile`` on one node (whole-host
    included via the WHOLE_HOST sentinel)."""
    tables = view.tables
    if profile == WHOLE_HOST:
        indices: Sequence[int] = (tables.whole_host_index,)
    else:
        indices = tables.by_profile.get(profile, ())
    return [i for i in indices if (view.available >> i) & 1]


def profile_placeable(views: Dict[str, NodeView], profile: str) -> bool:
    """Is ``profile`` placeable RIGHT NOW on any node — no migration?"""
    for view in views.values():
        for idx in _profile_placements(view, profile):
            if not (view.tables.placements[idx].mask & view.used_mask):
                return True
    return False


def plan_profile(views: Dict[str, NodeView],
                 profile: str,
                 rank=None) -> Optional[RepackPlan]:
    """Minimal migration set restoring one placement of ``profile``.

    Returns None when the profile is already placeable (nothing to do) or
    no placement can be freed by migration alone (every candidate overlaps
    a pinned chip). The chosen placement minimizes (blocking units, chips
    moved), with node-name and placement-index tie-breaks for
    determinism. With ``rank`` (unit -> int, the preemption planner's
    victim-priority hook) the highest rank in the blocking set leads the
    cost tuple: a set of strictly-cheaper victims always beats a smaller
    set containing a dearer one."""
    if profile_placeable(views, profile):
        return None
    best: Optional[Tuple[Tuple[int, int, int, str, int], NodeView, int,
                         List[MigrationUnit]]] = None
    for name in sorted(views):
        view = views[name]
        for idx in _profile_placements(view, profile):
            mask = view.tables.placements[idx].mask
            if mask & view.pinned_mask:
                continue  # immovable claim in the way: not freeable
            blockers = [u for u in view.units if u.chip_mask & mask]
            if not blockers:
                continue  # free placement would have been caught above
            cost = (max(rank(u) for u in blockers) if rank else 0,
                    len(blockers),
                    sum(u.num_chips for u in blockers), name, idx)
            if best is None or cost < best[0]:
                best = (cost, view, mask, blockers)
    if best is None:
        return None
    _, view, mask, blockers = best
    return RepackPlan(
        target=f"profile {profile} on {view.name}",
        nodes=(view.name,),
        units=tuple(sorted(blockers,
                           key=lambda u: (u.pod_namespace, u.pod_name))),
        placement_mask=mask,
    )


def plan_domain_block(views: Dict[str, NodeView],
                      topologies: Dict[str, dict],
                      num_nodes: int,
                      target: str = "",
                      rank=None) -> Optional[RepackPlan]:
    """Minimal migration set vacating a contiguous host-grid block of
    ``num_nodes`` whole-host-capable hosts within one ICI domain.

    A host qualifies for a block when its whole-host placement is
    available (no taints) and no pinned claim sits on it; among
    qualifying blocks the one with the fewest blocking units wins (ties:
    fewest chips moved, then the deterministic iter_host_blocks order).
    ``rank`` leads the cost like :func:`plan_profile`'s. Returns None
    when a fully-free block already exists — the scheduler places the
    domain itself — or no block can be vacated."""
    candidates = []
    for name, view in sorted(views.items()):
        if not _profile_placements(view, WHOLE_HOST):
            continue  # tainted: can never host a domain worker
        if view.pinned_mask:
            continue  # immovable claim: block is not vacatable
        candidates.append(name)
    best: Optional[Tuple[Tuple[int, int, int, int], object,
                         List[MigrationUnit]]] = None
    for order, block in enumerate(placement_lib.iter_host_blocks(
            topologies, candidates, num_nodes)):
        blockers: List[MigrationUnit] = []
        for node in block.nodes:
            blockers.extend(views[node].units)
        if not blockers:
            return None  # a free block exists: nothing to repack
        cost = (max(rank(u) for u in blockers) if rank else 0,
                len(blockers), sum(u.num_chips for u in blockers), order)
        if best is None or cost < best[0]:
            best = (cost, block, blockers)
    if best is None:
        return None
    _, block, blockers = best
    return RepackPlan(
        target=target or (f"host block {block.shape_str}@{block.origin_str} "
                          f"of {block.ici_domain or '<default>'}"),
        nodes=tuple(block.nodes),
        units=tuple(sorted(blockers,
                           key=lambda u: (u.node, u.pod_namespace,
                                          u.pod_name))),
    )


def plan_consolidation(views: Dict[str, NodeView]) -> List[RepackPlan]:
    """Energy mode: the vacate order that consolidates claims onto the
    fewest hosts. Least-utilized pin-free hosts drain first (name
    tie-break); a host only appears when every claim on it is movable.
    The controller re-places each unit tightest-fit-first (the PR 5
    packing rank), restricted to hosts at least as utilized as the
    source, so moves strictly reduce the occupied-host count and the
    loop terminates."""
    plans: List[RepackPlan] = []
    occupied = [v for v in views.values() if v.units and not v.pinned_mask]
    occupied.sort(key=lambda v: (placement_lib.popcount(v.used_mask), v.name))
    for view in occupied:
        plans.append(RepackPlan(
            target=f"consolidate {view.name} "
                   f"({len(view.units)} unit(s)) for drain",
            nodes=(view.name,),
            units=tuple(sorted(view.units,
                               key=lambda u: (u.pod_namespace, u.pod_name))),
        ))
    return plans


def reclaimable_hosts(views: Dict[str, NodeView]) -> List[str]:
    """Hosts with zero allocated chips — drainable right now."""
    return sorted(n for n, v in views.items() if v.used_mask == 0)


def largest_free_capacity(views: Dict[str, NodeView]) -> int:
    """Sum over nodes of chips in the largest still-placeable profile —
    the cluster-wide reading of the per-node
    ``tpu_dra_node_frag_largest_free_profile`` gauge (bench_rebalance's
    recovery metric)."""
    return sum(
        v.tables.largest_free_chips(v.used_mask, v.available)
        for v in views.values()
    )


def build_node_views(
    overview: Dict[str, dict],
    claims: Sequence,
    pods_by_uid: Dict[str, object],
    tpu_driver_name: str,
    device_types: Dict[Tuple[str, str], str],
    is_cordoned,
    unit_tier=None,
) -> Dict[str, NodeView]:
    """Assemble per-node views from the allocator's placement overview
    plus one claim/pod listing.

    ``device_types``: (node, device name) -> published ``type`` attribute
    (tpu/subslice/vfio/...) so passthrough devices pin their chips.
    ``is_cordoned``: claim -> bool (the controller's cordon annotation).
    ``unit_tier``: optional (pod, claims) -> int stamping each unit's
    contention tier (the preemption planner's victim-priority input)."""
    views: Dict[str, NodeView] = {
        node: NodeView(name=node, tables=entry["tables"],
                       available=entry["available"],
                       used_mask=entry["used_mask"])
        for node, entry in overview.items()
    }
    # Pass 1: per-pod claim grouping with per-claim movability verdicts.
    by_pod: Dict[str, List] = {}
    for claim in claims:
        alloc = claim.allocation
        if alloc is None or alloc.node_name not in views:
            continue
        view = views[alloc.node_name]
        dev_mask = overview[alloc.node_name]["dev_mask"]
        mask = 0
        movable = True
        for r in alloc.devices:
            bits = dev_mask.get(r.device, 0)
            mask |= bits
            if r.driver != tpu_driver_name:
                movable = False  # channel/daemon: a ComputeDomain member
            elif device_types.get((alloc.node_name, r.device)) == "vfio":
                movable = False  # passthrough binds are host state
            elif not bits:
                movable = False  # device without chip counters: unknown
        if is_cordoned(claim):
            movable = False  # a migration is already in flight
        pod_refs = [r for r in claim.reserved_for if r.kind == "Pod"]
        if len(pod_refs) != 1 or len(pod_refs) != len(claim.reserved_for):
            # Shared or consumer-less claims can't migrate pod-at-a-time:
            # pin their chips where they are.
            view.pinned_mask |= mask
            continue
        by_pod.setdefault(pod_refs[0].uid, []).append((claim, mask, movable))
    # Pass 2: pod-level movability. A pod moves as ONE unit, so a single
    # immovable claim (a ComputeDomain channel, a vfio group, a cordon)
    # pins EVERY claim the pod holds — this is what guarantees assembled
    # ComputeDomains are never disturbed: their workers all carry a
    # channel claim.
    for pod_uid, items in by_pod.items():
        pod = pods_by_uid.get(pod_uid)
        node = items[0][0].allocation.node_name
        view = views[node]
        unit_mask = 0
        for _, mask, _m in items:
            unit_mask |= mask
        pod_movable = (
            pod is not None
            and pod.phase == "Running"
            and not any(r.kind == "DaemonSet"
                        for r in pod.meta.owner_references)
            and all(m for _, _, m in items)
            and all(c.allocation.node_name == node for c, _, _ in items)
        )
        if not pod_movable:
            # Pin each claim's chips on its OWN node — a pod whose claims
            # ended up on different nodes (a crashed mid-migration repoint)
            # must pin every node it touches, not fold foreign bit
            # positions into the first claim's view.
            for c, mask, _m in items:
                views[c.allocation.node_name].pinned_mask |= mask
            continue
        view.units.append(MigrationUnit(
            pod_namespace=pod.meta.namespace,
            pod_name=pod.meta.name,
            pod_uid=pod_uid,
            node=node,
            claim_keys=tuple(sorted((c.meta.namespace, c.meta.name)
                                    for c, _, _ in items)),
            chip_mask=unit_mask,
            tier=(unit_tier(pod, [c for c, _, _ in items])
                  if unit_tier else 0),
        ))
    for view in views.values():
        view.units.sort(key=lambda u: (u.pod_namespace, u.pod_name))
    return views
