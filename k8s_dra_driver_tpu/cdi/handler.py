"""Claim-scoped CDI spec files.

CDI is the only mechanism by which devices reach containers — the plugin
never touches the container itself (SURVEY.md §1 L3→runtime;
/root/reference/cmd/gpu-kubelet-plugin/cdi.go:44-49,181-307). Shape kept
from the reference: one spec file per claim UID under the CDI root, spec
kind ``k8s.tpu.google.com/claim``, fully-qualified device ids
``k8s.tpu.google.com/claim=<uid>-<device>`` returned to the kubelet.

The default root is /var/run/cdi — the runtime's default scan dir (the
reference's chart sets CDI_ROOT there; /etc/cdi is only its CLI default).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import yaml

CDI_VERSION = "0.6.0"
CLAIM_SPEC_KIND = "k8s.tpu.google.com/claim"
DEFAULT_CDI_ROOT = "/var/run/cdi"


@dataclass
class ContainerEdits:
    device_nodes: List[str] = field(default_factory=list)   # host paths
    # Structured char devices the runtime must mknod (path/type/major/minor),
    # e.g. slice channels — the reference carries these for IMEX channels
    # (cmd/compute-domain-kubelet-plugin/device_state.go:722-731).
    char_devices: List[Dict[str, object]] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)
    mounts: List[Dict[str, str]] = field(default_factory=list)  # {host_path, container_path, [options]}
    hooks: List[Dict[str, object]] = field(default_factory=list)

    def merged(self, other: "ContainerEdits") -> "ContainerEdits":
        return ContainerEdits(
            device_nodes=[*self.device_nodes, *other.device_nodes],
            char_devices=[*self.char_devices, *other.char_devices],
            env={**self.env, **other.env},
            mounts=[*self.mounts, *other.mounts],
            hooks=[*self.hooks, *other.hooks],
        )

    def to_cdi(self) -> dict:
        out: dict = {}
        if self.device_nodes or self.char_devices:
            out["deviceNodes"] = [{"path": p} for p in self.device_nodes] + [
                dict(d) for d in self.char_devices
            ]
        if self.env:
            out["env"] = [f"{k}={v}" for k, v in sorted(self.env.items())]
        if self.mounts:
            out["mounts"] = [
                {
                    "hostPath": m["host_path"],
                    "containerPath": m["container_path"],
                    "options": m.get("options", "rw,bind").split(","),
                }
                for m in self.mounts
            ]
        if self.hooks:
            out["hooks"] = list(self.hooks)
        return out


class CDIHandler:
    def __init__(self, cdi_root: Optional[str] = None):
        self.cdi_root = cdi_root or os.environ.get("CDI_ROOT", DEFAULT_CDI_ROOT)

    def _spec_path(self, claim_uid: str) -> str:
        return os.path.join(
            self.cdi_root, f"{CLAIM_SPEC_KIND.replace('/', '-')}_{claim_uid}.yaml"
        )

    @staticmethod
    def device_id(claim_uid: str, device_name: str) -> str:
        return f"{CLAIM_SPEC_KIND}={claim_uid}-{device_name}"

    def create_claim_spec_file(
        self,
        claim_uid: str,
        per_device_edits: Dict[str, ContainerEdits],
        common_edits: Optional[ContainerEdits] = None,
    ) -> List[str]:
        """Write the claim's spec; returns fully-qualified CDI device ids."""
        devices = []
        ids = []
        for device_name in sorted(per_device_edits):
            edits = per_device_edits[device_name]
            if common_edits is not None:
                edits = common_edits.merged(edits)
            devices.append(
                {"name": f"{claim_uid}-{device_name}", "containerEdits": edits.to_cdi()}
            )
            ids.append(self.device_id(claim_uid, device_name))
        spec = {"cdiVersion": CDI_VERSION, "kind": CLAIM_SPEC_KIND, "devices": devices}
        os.makedirs(self.cdi_root, exist_ok=True)
        path = self._spec_path(claim_uid)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            yaml.safe_dump(spec, f, sort_keys=False)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return ids

    def delete_claim_spec_file(self, claim_uid: str) -> None:
        try:
            os.unlink(self._spec_path(claim_uid))
        except FileNotFoundError:
            pass

    def claim_spec_exists(self, claim_uid: str) -> bool:
        return os.path.exists(self._spec_path(claim_uid))

    def read_claim_spec(self, claim_uid: str) -> Optional[dict]:
        try:
            with open(self._spec_path(claim_uid), "r", encoding="utf-8") as f:
                return yaml.safe_load(f)
        except FileNotFoundError:
            return None
