"""CDI (Container Device Interface) spec generation."""

from k8s_dra_driver_tpu.cdi.handler import (  # noqa: F401
    CDIHandler,
    ContainerEdits,
    CDI_VERSION,
    CLAIM_SPEC_KIND,
)
