"""Standalone simulated cluster — the `kind create cluster` analog.

Hosts the HTTP API server and runs the SimCluster control loops (scheduler,
DaemonSet controller, kubelet, slice agents, CD controller) continuously,
so external processes — the kubectl CLI, the shell e2e tier, or the real
binaries with --api-backend http — operate against a live "cluster" without
hardware, the way the reference's mock-NVML kind cluster backs its CI
(SURVEY.md §4.2).

    python -m k8s_dra_driver_tpu.sim --port 8001 --profile v5e-16

Prints `cluster up at <url>` when serving; steps the control loops every
--tick seconds until SIGTERM/SIGINT.
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import tempfile
import threading

from k8s_dra_driver_tpu.k8s.httpapi import serve_api
from k8s_dra_driver_tpu.sim.cluster import SimCluster


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        "tpu-dra-simcluster", description="simulated TPU cluster over HTTP"
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8001)
    parser.add_argument("--profile", default="v5e-16",
                        help="mock tpulib topology profile per node")
    parser.add_argument("--num-hosts", type=int, default=None,
                        help="override node count (default: profile's host count)")
    parser.add_argument("--gates", default="", help="feature gates, k=v comma list")
    parser.add_argument("--workdir", default="",
                        help="plugin/CDI state dir (default: temp dir)")
    parser.add_argument("--tick", type=float, default=0.2,
                        help="control-loop step interval seconds")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO if args.verbose else logging.WARNING)
    workdir = args.workdir or tempfile.mkdtemp(prefix="tpu-dra-sim-")
    srv = serve_api(host=args.host, port=args.port)
    sim = SimCluster(
        workdir=workdir, profile=args.profile, num_hosts=args.num_hosts,
        gates=args.gates, api=srv.api,
    )
    sim.start()
    print(f"cluster up at {srv.url} "
          f"({len(sim.nodes)} nodes, profile {args.profile})", flush=True)

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *a: stop.set())
    while not stop.wait(args.tick):
        try:
            sim.step()
        except Exception:  # noqa: BLE001 — a bad pass must not kill the cluster
            logging.exception("sim step failed")
    sim.stop()
    srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
