"""Standalone simulated cluster — the `kind create cluster` analog.

Hosts the HTTP API server and runs the SimCluster control loops (scheduler,
DaemonSet controller, kubelet, slice agents, CD controller) continuously,
so external processes — the kubectl CLI, the shell e2e tier, or the real
binaries with --api-backend http — operate against a live "cluster" without
hardware, the way the reference's mock-NVML kind cluster backs its CI
(SURVEY.md §4.2).

    python -m k8s_dra_driver_tpu.sim --port 8001 --profile v5e-16

Prints `cluster up at <url>` when serving; steps the control loops every
--tick seconds until SIGTERM/SIGINT. With --metrics-port set, the shared
cluster registry and the trace ring buffer are served on that port
(/metrics, /debug/traces, /debug/stacks, /debug/vars).

Subcommand ``trace`` renders the claim-lifecycle timeline for one claim
from a trace dump (a file saved from /debug/traces, or fetched live):

    python -m k8s_dra_driver_tpu.sim trace <claim-uid> --url http://127.0.0.1:9090
    python -m k8s_dra_driver_tpu.sim trace <claim-uid> --input traces.json
    python -m k8s_dra_driver_tpu.sim trace <claim-uid> --input traces.json --format chrome > claim.json

The ``--format chrome`` output is the filtered Chrome trace-event JSON,
loadable in Perfetto / chrome://tracing.
"""

from __future__ import annotations

import argparse
import json
import logging
import signal
import sys
import tempfile
import threading
import urllib.request

from k8s_dra_driver_tpu.k8s.httpapi import serve_api
from k8s_dra_driver_tpu.pkg import tracing
from k8s_dra_driver_tpu.pkg.metrics import MetricsServer
from k8s_dra_driver_tpu.sim.cluster import SimCluster


def run_main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        "tpu-dra-simcluster", description="simulated TPU cluster over HTTP"
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8001)
    parser.add_argument("--profile", default="v5e-16",
                        help="mock tpulib topology profile per node")
    parser.add_argument("--num-hosts", type=int, default=None,
                        help="override node count (default: profile's host count)")
    parser.add_argument("--gates", default="", help="feature gates, k=v comma list")
    parser.add_argument("--workdir", default="",
                        help="plugin/CDI state dir (default: temp dir)")
    parser.add_argument("--tick", type=float, default=0.2,
                        help="control-loop step interval seconds")
    parser.add_argument("--metrics-port", type=int, default=0,
                        help="serve the cluster-wide /metrics + /debug/traces "
                        "here; 0 disables")
    parser.add_argument("--rebalance", choices=("off", "defrag", "energy"),
                        default="off",
                        help="live-repack rebalancer mode (off, or defrag/"
                        "energy; LiveRepack=true in --gates also enables "
                        "defrag)")
    parser.add_argument("--persist-dir", default="",
                        help="back the API store with a WAL+snapshot in this "
                        "directory: a restarted sim restores the previous "
                        "run's state (fingerprint-identical) instead of "
                        "re-running its storm")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO if args.verbose else logging.WARNING)
    workdir = args.workdir or tempfile.mkdtemp(prefix="tpu-dra-sim-")
    api = None
    if args.persist_dir:
        from k8s_dra_driver_tpu.k8s.persist import open_persistent_store

        api = open_persistent_store(args.persist_dir)
        if api.restored_objects:
            print(f"restored {api.restored_objects} objects from "
                  f"{args.persist_dir} in {api.restore_seconds:.1f}s",
                  flush=True)
    srv = serve_api(api=api, host=args.host, port=args.port)
    rebalancer_config = None
    if args.rebalance != "off":
        from k8s_dra_driver_tpu.rebalancer import RebalancerConfig

        rebalancer_config = RebalancerConfig(mode=args.rebalance)
    sim = SimCluster(
        workdir=workdir, profile=args.profile, num_hosts=args.num_hosts,
        gates=args.gates, api=srv.api, rebalancer_config=rebalancer_config,
    )
    sim.start()
    metrics_srv = None
    if args.metrics_port:
        metrics_srv = MetricsServer(sim.metrics_registry, host=args.host,
                                    port=args.metrics_port, debug_path="/debug")
        metrics_srv.start()
    print(f"cluster up at {srv.url} "
          f"({len(sim.nodes)} nodes, profile {args.profile})"
          + (f"; metrics at http://{args.host}:{metrics_srv.port}"
             if metrics_srv else ""),
          flush=True)

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *a: stop.set())
    while not stop.wait(args.tick):
        try:
            sim.step()
        except Exception:  # noqa: BLE001 — a bad pass must not kill the cluster
            logging.exception("sim step failed")
    sim.stop()
    if metrics_srv:
        metrics_srv.stop()
    srv.stop()
    return 0


def trace_main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        "tpu-dra-simcluster trace",
        description="render the claim-lifecycle timeline for one claim "
        "from a /debug/traces dump",
    )
    parser.add_argument("claim_uid", help="ResourceClaim uid to trace")
    parser.add_argument("--input", default="",
                        help="Chrome trace-event JSON file (saved from "
                        "/debug/traces); mutually exclusive with --url")
    parser.add_argument("--url", default="",
                        help="base URL of a running MetricsServer (its "
                        "/debug/traces is fetched), e.g. http://127.0.0.1:9090")
    parser.add_argument("--cluster", default="",
                        help="route to a federated cluster: a name from "
                        "TPU_KUBECTL_CLUSTERS (\"name=url,...\") or a URL — "
                        "the cluster's API server /debug/traces is fetched")
    parser.add_argument("--format", choices=("timeline", "chrome"),
                        default="timeline",
                        help="timeline: human-readable; chrome: filtered "
                        "trace-event JSON for Perfetto/chrome://tracing")
    args = parser.parse_args(argv)
    if args.cluster:
        from k8s_dra_driver_tpu.sim.kubectl import _resolve_cluster

        args.url = _resolve_cluster(args.cluster)
    if bool(args.input) == bool(args.url):
        parser.error("exactly one of --input or --url "
                     "(or --cluster) is required")

    if args.input:
        with open(args.input, "r", encoding="utf-8") as f:
            doc = json.load(f)
    else:
        url = args.url.rstrip("/")
        if not url.endswith("/traces"):
            url += "/debug/traces"
        with urllib.request.urlopen(url, timeout=10) as resp:
            doc = json.load(resp)

    spans = tracing.spans_from_chrome(doc)
    tagged = [s for s in spans if s.about_claim(args.claim_uid)]
    trace_ids = {s.trace_id for s in tagged}
    selected = [s for s in spans if s.trace_id in trace_ids]
    if not selected:
        print(f"no spans reference claim {args.claim_uid}", file=sys.stderr)
        return 1
    if args.format == "chrome":
        print(json.dumps({
            "displayTimeUnit": "ms",
            "traceEvents": [s.to_chrome_event() for s in selected],
        }))
    else:
        print(f"claim {args.claim_uid}: {len(trace_ids)} trace(s), "
              f"{len(selected)} span(s)")
        print(tracing.render_timeline(selected))
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # Subcommand dispatch that keeps the historical flag-only invocation
    # (`python -m k8s_dra_driver_tpu.sim --port ...`) working unchanged.
    if argv and argv[0] == "trace":
        return trace_main(argv[1:])
    if argv and argv[0] in ("describe", "explain", "get", "top"):
        # `sim describe computedomain <name>` / `sim top computedomains` —
        # the kubectl verbs against a running sim apiserver (--server /
        # $TPU_KUBECTL_SERVER), so the debugging loop (status + conditions
        # + deduped events + utilization tables) closes without a second
        # CLI.
        from k8s_dra_driver_tpu.sim.kubectl import main as kubectl_main

        return kubectl_main(argv)
    if argv and argv[0] == "run":
        argv = argv[1:]
    return run_main(argv)


if __name__ == "__main__":
    sys.exit(main())
