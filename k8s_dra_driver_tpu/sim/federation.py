"""Multi-cluster sim harness: a federated fleet with chaos hooks.

:class:`FederatedFleet` stands up the full federation stack in-process:

- a **leader** :class:`~k8s_dra_driver_tpu.sim.cluster.SimCluster` with
  persistence + the ``FederatedFleet`` gate (so its store carries a
  ``ReplicationSource``),
- a **read replica** (:class:`~k8s_dra_driver_tpu.federation.ReplicaStore`)
  following the leader's WAL through a partitionable link,
- optionally a **follower-region** SimCluster with its own hardware
  (spill capacity — where serving traffic lands when the leader region's
  SLO burns or the leader dies),
- a :class:`~k8s_dra_driver_tpu.federation.GlobalScheduler` spanning
  both regions, with decision provenance in the leader's flight recorder.

Chaos follows the sim's annotation idiom — suites drive failures through
the API like any other state, no reaching into the process:

- ``sim.tpu.google.com/replication-partition: "true"`` on the leader's
  designated federation node severs the replication link (streams error,
  the follower reconnect-loops); clearing it heals the link and the
  follower resumes AT ITS WATERMARK — no duplicates, no gaps.
- ``sim.tpu.google.com/leader-down: "true"`` kills the leader region:
  the replica is promoted (read-only -> writable, FailoverStarted/
  FailoverCompleted) and keeps the fleet's serving surface alive.

``fleet.step()`` pumps both clusters and applies pending chaos.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, List, Optional

from k8s_dra_driver_tpu.federation import (
    ClusterView,
    GlobalScheduler,
    ReplicaStore,
    ReplicationSource,
)
from k8s_dra_driver_tpu.k8s.core import NODE
from k8s_dra_driver_tpu.sim.cluster import SimCluster

log = logging.getLogger(__name__)

# Chaos annotations (see module docstring). They live on the leader's
# nodes so kubectl-driven suites can flip them; the fleet harness sweeps
# them each step().
CHAOS_REPLICATION_PARTITION_ANNOTATION = \
    "sim.tpu.google.com/replication-partition"
CHAOS_LEADER_DOWN_ANNOTATION = "sim.tpu.google.com/leader-down"

LEADER_GATES = "StorePersistence=true,FederatedFleet=true"


class PartitionedError(OSError):
    """The chaos-injected replication link failure."""


class _PartitionableSource:
    """Wraps a replication source with a breakable link: while
    partitioned every protocol call (and every in-flight tail, at its
    next yield — within one heartbeat) raises, exactly what a severed
    TCP stream looks like to the follower's supervisor."""

    def __init__(self, inner: ReplicationSource):
        self.inner = inner
        self._partitioned = threading.Event()

    def partition(self) -> None:
        self._partitioned.set()

    def heal(self) -> None:
        self._partitioned.clear()

    @property
    def partitioned(self) -> bool:
        return self._partitioned.is_set()

    def _check(self) -> None:
        if self._partitioned.is_set():
            raise PartitionedError("replication link partitioned (chaos)")

    def status(self) -> dict:
        self._check()
        return self.inner.status()

    def snapshot(self) -> dict:
        self._check()
        return self.inner.snapshot()

    def tail(self, stream: int, from_seq: int,
             stop: Optional[threading.Event] = None):
        self._check()
        for line in self.inner.tail(stream, from_seq, stop=stop):
            self._check()
            yield line


class FederatedFleet:
    """Leader cluster + read replica (+ optional follower region) +
    global scheduler, wired for chaos. See the module docstring."""

    def __init__(self, workdir: str, profile: str = "v5e-16",
                 leader_hosts: Optional[int] = None,
                 follower_hosts: Optional[int] = None,
                 follower_region: bool = True,
                 gates: str = "",
                 leader_weight: float = 1.0,
                 follower_weight: float = 1.0):
        extra = f",{gates}" if gates else ""
        self.leader = SimCluster(os.path.join(workdir, "leader"),
                                 profile=profile, num_hosts=leader_hosts,
                                 gates=LEADER_GATES + extra)
        if getattr(self.leader.api, "replication", None) is None:
            raise RuntimeError("leader store has no ReplicationSource — "
                               "FederatedFleet gate not applied?")
        self.link = _PartitionableSource(self.leader.api.replication)
        # Replica lag alerts go to the LEADER's event plane (the replica
        # store is read-only); the failover pair self-records.
        from k8s_dra_driver_tpu.pkg.events import EventRecorder

        self.replica = ReplicaStore(
            self.link, cluster="leader-replica",
            metrics_registry=self.leader.metrics_registry,
            recorder=EventRecorder(self.leader.api, "federation"),
            history=self.leader.history)
        self.replica.start()
        self.follower: Optional[SimCluster] = None
        if follower_region:
            self.follower = SimCluster(os.path.join(workdir, "follower"),
                                       profile=profile,
                                       num_hosts=follower_hosts,
                                       gates=gates)
        views: List[ClusterView] = [ClusterView(
            name="leader", api=self.leader.api,
            free_chips=self.leader._fleet_free_chips,
            weight=leader_weight, slo=self.leader.slo)]
        if self.follower is not None:
            views.append(ClusterView(
                name="follower", api=self.follower.api,
                free_chips=self.follower._fleet_free_chips,
                weight=follower_weight, slo=self.follower.slo))
        self.scheduler = GlobalScheduler(
            views, history=self.leader.history,
            metrics_registry=self.leader.metrics_registry)
        # Replication lag as a first-class SLO (FleetTelemetry gate on
        # the leader): every fleet.step() feeds the replica's record lag
        # into the leader's evaluator, so a partition burns the error
        # budget through the same multi-window machinery as every other
        # objective and the alert decays to zero after heal.
        if self.leader.slo is not None:
            from k8s_dra_driver_tpu.pkg.slo import replication_lag_objective

            self.leader.slo.add(replication_lag_objective())
        self.leader_alive = True
        self._stopped = False
        self._servers: Dict[str, object] = {}

    # -- chaos ---------------------------------------------------------------

    def partition_replication(self) -> None:
        self.link.partition()

    def heal_replication(self) -> None:
        self.link.heal()

    def kill_leader(self):
        """Leader region dies: stop its control plane and promote the
        replica so the fleet keeps a serving surface (reads immediately;
        writes once promotion flips the store writable). Returns the
        promoted store."""
        if not self.leader_alive:
            return self.replica.api
        self.leader_alive = False
        self.link.partition()  # the dead leader is unreachable too
        self.leader.stop()
        api = self.replica.promote()
        # The promoted store takes over as the leader view for placement:
        # reads and writes land there while the old region is gone.
        self.scheduler.clusters["leader"].api = api
        log.info("leader killed; replica promoted at watermark %d",
                 self.replica.watermark())
        return api

    def _chaos_pass(self) -> None:
        """Honor the chaos annotations on the leader's nodes (skipped
        once the leader is dead — there is nobody left to read)."""
        if not self.leader_alive:
            return
        want_partition = False
        want_down = False
        for node in self.leader.api.list(NODE):
            ann = node.meta.annotations or {}
            if ann.get(CHAOS_REPLICATION_PARTITION_ANNOTATION) == "true":
                want_partition = True
            if ann.get(CHAOS_LEADER_DOWN_ANNOTATION) == "true":
                want_down = True
        if want_down:
            self.kill_leader()
            return
        if want_partition and not self.link.partitioned:
            log.info("chaos: partitioning replication link")
            self.link.partition()
        elif not want_partition and self.link.partitioned:
            log.info("chaos: healing replication link")
            self.link.heal()

    # -- pumping -------------------------------------------------------------

    def step(self) -> None:
        self._chaos_pass()
        if self.leader_alive:
            self.leader.step()
        if self.follower is not None:
            self.follower.step()
        self._observe_replication_lag()

    def _observe_replication_lag(self) -> None:
        """Feed the leader-head-minus-replica-applied record lag into
        the leader's SLO evaluator (declared in __init__). Evaluated by
        the leader's own telemetry pass next step — no extra machinery.

        The lag is computed on the LEADER side (its own WAL head vs the
        replica's applied watermark): a fully partitioned replica cannot
        see the head growing, so its self-reported ``lag_records()``
        flatlines at the moment of the cut — exactly when the objective
        must burn."""
        if not self.leader_alive or self.leader.slo is None:
            return
        from k8s_dra_driver_tpu.k8s.core import ObjectReference
        from k8s_dra_driver_tpu.pkg.slo import REPLICATION_LAG_SLO

        head = int(self.leader.api.replication.status().get("watermark", 0))
        lag = max(0, head - self.replica.watermark())
        self.leader.slo.observe(
            REPLICATION_LAG_SLO, self.leader.telemetry_clock,
            float(lag),
            subject=("", self.replica.cluster),
            ref=ObjectReference(kind="Cluster", name=self.replica.cluster,
                                namespace="", uid=""))

    def settle(self, max_steps: int = 20) -> None:
        if self.leader_alive:
            self.leader.settle(max_steps)
        if self.follower is not None:
            self.follower.settle(max_steps)

    def converged(self) -> bool:
        """Fingerprint-token identity between leader and replica for
        every kind the leader carries — the same O(1) equality the
        persistence restore tests pin."""
        if not self.leader_alive:
            return False
        with self.leader.api._locked_all():
            kinds = set()
            for shard in self.leader.api._shards:
                kinds.update(shard.fp)
        return all(self.replica.api.kind_fingerprint(k)
                   == self.leader.api.kind_fingerprint(k) for k in kinds)

    def wait_converged(self, timeout_s: float = 10.0,
                       poll_s: float = 0.02) -> bool:
        import time

        deadline = time.monotonic() + timeout_s
        if self.leader_alive:
            self.leader.api.flush_watchers()
        while time.monotonic() < deadline:
            if self.converged():
                return True
            time.sleep(poll_s)
        return self.converged()

    def headroom(self) -> Dict[str, int]:
        return self.scheduler.headroom()

    # -- HTTP serving (the fleet lens) ---------------------------------------

    def serve_http(self) -> Dict[str, str]:
        """Stand the fleet's query plane up over HTTP: one HTTPAPIServer
        per cluster surface (leader, its read replica, the follower
        region when present). Attaches each cluster's metrics registry
        for /metrics, and the full peer url map on every api for
        /federation/metrics — so ANY cluster answers the fleet-merged
        scrape. Returns {name: base_url}, the TPU_KUBECTL_CLUSTERS
        vocabulary for ``tpu-kubectl --all-clusters``. Idempotent."""
        from k8s_dra_driver_tpu.k8s.httpapi import HTTPAPIServer

        if self._servers:
            return self.cluster_urls()
        self.leader.api.metrics_registry = self.leader.metrics_registry
        # The replica shares the leader's registry (it was wired with it
        # at construction) — serving it from the replica keeps the
        # scrape alive through leader death.
        self.replica.api.metrics_registry = self.leader.metrics_registry
        surfaces = {"leader": self.leader.api,
                    "leader-replica": self.replica.api}
        if self.follower is not None:
            self.follower.api.metrics_registry = \
                self.follower.metrics_registry
            surfaces["follower"] = self.follower.api
        for name, api in surfaces.items():
            self._servers[name] = HTTPAPIServer(api).start()
        urls = self.cluster_urls()
        for api in surfaces.values():
            api.federation_peers = dict(urls)
        return urls

    def cluster_urls(self) -> Dict[str, str]:
        return {name: srv.url for name, srv in self._servers.items()}

    def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        for srv in self._servers.values():
            srv.stop()
        self._servers.clear()
        self.replica.stop()
        if self.leader_alive:
            self.leader.stop()
        if self.follower is not None:
            self.follower.stop()
