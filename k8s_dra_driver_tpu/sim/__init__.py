"""Cluster simulation — the mock-NVML-kind-CI analog (SURVEY.md §4.2).

The reference tests multi-node behavior on CPU-only CI by running the real
driver against a kind cluster with a mock NVML. With no cluster available
at all, this package emulates the *cluster half* instead: a DRA
structured-parameters allocator (what the scheduler does with
ResourceSlices + counters), pod scheduling/binding, a kubelet that calls
the real plugins' Prepare/Unprepare and materializes CDI env, and a
DaemonSet controller. The driver code under test is the real thing; only
Kubernetes itself is simulated.
"""

from k8s_dra_driver_tpu.sim.allocator import AllocationError, Allocator  # noqa: F401
from k8s_dra_driver_tpu.sim.cluster import SimCluster  # noqa: F401
