"""kubectl-apply analog: load k8s-shaped YAML manifests into the sim API.

Parses the same manifest shapes the reference ships under
demo/specs/quickstart (Pods + ResourceClaims/Templates with DRA device
requests, plus the ComputeDomain CRD) so the demo specs are real YAML a
user could port to a live cluster, not test fixtures.
"""

from __future__ import annotations

from typing import Any, Dict, List

import yaml

from k8s_dra_driver_tpu.api.computedomain import (
    ComputeDomain,
    ComputeDomainChannelSpec,
    ComputeDomainSpec,
)
from k8s_dra_driver_tpu.k8s import APIServer
from k8s_dra_driver_tpu.k8s.core import (
    Container,
    Pod,
    PodResourceClaimRef,
    ResourceClaim,
    ResourceClaimTemplate,
)
from k8s_dra_driver_tpu.k8s.manifest import (
    device_configs_from_spec as _device_configs,
    device_requests_from_spec as _device_requests,
    unwrap_template_spec,
)
from k8s_dra_driver_tpu.k8s.objects import K8sObject, new_meta


class ManifestError(ValueError):
    pass


def _meta(doc: Dict[str, Any]):
    md = doc.get("metadata", {})
    if "name" not in md:
        raise ManifestError(f"manifest {doc.get('kind')} missing metadata.name")
    return new_meta(md["name"], md.get("namespace", "default"),
                    labels=md.get("labels", {}))


def _pod(doc: Dict[str, Any]) -> Pod:
    spec = doc.get("spec", {})
    containers = [
        Container(
            name=c.get("name", "main"),
            image=c.get("image", ""),
            command=c.get("command", []),
            env={e["name"]: str(e.get("value", "")) for e in c.get("env", [])},
        )
        for c in spec.get("containers", [])
    ]
    claims = [
        PodResourceClaimRef(
            name=rc.get("name", "claim"),
            resource_claim_name=rc.get("resourceClaimName", ""),
            resource_claim_template_name=rc.get("resourceClaimTemplateName", ""),
        )
        for rc in spec.get("resourceClaims", [])
    ]
    return Pod(meta=_meta(doc), containers=containers, resource_claims=claims)


def _claim(doc: Dict[str, Any]) -> ResourceClaim:
    spec = doc.get("spec", {})
    return ResourceClaim(
        meta=_meta(doc),
        requests=_device_requests(spec),
        config=_device_configs(spec),
    )


def _claim_template(doc: Dict[str, Any]) -> ResourceClaimTemplate:
    spec = unwrap_template_spec(doc.get("spec", {}))
    return ResourceClaimTemplate(
        meta=_meta(doc),
        requests=_device_requests(spec),
        config=_device_configs(spec),
    )


def _compute_domain(doc: Dict[str, Any]) -> ComputeDomain:
    spec = doc.get("spec", {})
    channel = spec.get("channel", {}) or {}
    rct = channel.get("resourceClaimTemplate", {}) or {}
    return ComputeDomain(
        meta=_meta(doc),
        spec=ComputeDomainSpec(
            num_nodes=spec.get("numNodes", 0),
            topology=spec.get("topology", ""),
            channel=ComputeDomainChannelSpec(
                resource_claim_template_name=rct.get("name", ""),
            ),
        ),
    )


_KIND_BUILDERS = {
    "Pod": _pod,
    "ResourceClaim": _claim,
    "ResourceClaimTemplate": _claim_template,
    "ComputeDomain": _compute_domain,
}


def load_manifests(text: str) -> List[K8sObject]:
    objs: List[K8sObject] = []
    for doc in yaml.safe_load_all(text):
        if not doc:
            continue
        kind = doc.get("kind", "")
        if kind == "Namespace":
            continue  # namespaces are implicit in the fake API
        builder = _KIND_BUILDERS.get(kind)
        if builder is None:
            raise ManifestError(f"unsupported manifest kind {kind!r}")
        objs.append(builder(doc))
    return objs


def apply_file(api: APIServer, path: str) -> List[K8sObject]:
    with open(path, "r", encoding="utf-8") as f:
        objs = load_manifests(f.read())
    created = []
    for obj in objs:
        created.append(api.create(obj))
    return created
